"""AOT-bucketed inference engine: BucketSpec + Predictor.

The reference ships a dedicated inference surface — the C predict API
(src/c_api/c_predict_api.cc: create from (symbol-json, params-blob),
set-input, forward, get-output) — built so a deployed model never touches
the training machinery. On a jit-compiled TPU stack the deployment problem
is different: execution is already compiled, but every NEW request shape
means a fresh XLA trace, and a serving box that compiles in the hot path
is down for seconds at a time. The TPU-native answer (TVM's
compile-for-deployment flow, arXiv:1802.04799; PyGraph's capture-once /
replay-forever discipline for CUDA Graphs, arXiv:2503.19779):

* a :class:`BucketSpec` declares the closed set of (batch x seq/spatial)
  shapes the service will ever execute,
* :class:`Predictor` ahead-of-time compiles ONE donated inference jit per
  bucket at startup (``warmup()``), pads each request up to its bucket,
  and slices outputs back — a device-side slice, so the only
  device->host transfer is the caller's explicit output fetch,
* every compile is reported to the PR-4 retrace watchdog at site
  ``serving.predict``; after warmup the compile count at that site is
  <= #buckets by construction, and a mid-traffic compile (off-template
  request shape, policy env flipped under the server) is attributable
  from ``telemetry.report()`` alone.

Three load paths, mirroring the reference's predict-API inputs:

* ``Predictor(block, spec)`` — a gluon ``HybridBlock`` (its compiled
  forward is rebuilt per bucket from the same ``_run_traced`` machinery
  ``CachedOp`` uses, gluon/block.py:375);
* ``Predictor.from_checkpoint(prefix, epoch, spec)`` — symbol-json +
  params checkpoint via ``SymbolBlock`` (the c_predict_api shape);
* ``Predictor.from_trainer_checkpoint(block, directory, spec)`` — the
  params subtree of a ``contrib.async_checkpoint.save_trainer`` orbax
  checkpoint (a training run promotes straight to serving, no format
  hop).

The bf16/policy levers ride along: ``ops.registry.policy_key`` is part of
every bucket's jit cache key, so ``net.cast('bfloat16')`` + policy envs
serve exactly like they train.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import telemetry
from ..base import MXNetError
from ..ndarray import NDArray

__all__ = ["BucketSpec", "Predictor", "pad_nd", "serve_int8_default"]

# Serializes the FIRST invocation of a freshly-built jit (the trace):
# tracing runs the block body, which temporarily binds tracers into the
# SHARED Parameter objects — two replicas' Predictors compiling at once
# (mxtpu/serving/replicas.py spawns one dispatch worker per replica)
# would race on that binding. Warm-path calls never take this lock.
# Since the compile service this IS the service's central trace lock
# (one process-wide python-trace discipline; replicas' identical
# lowerings additionally dedup through the service's group path so N
# replicas trace once, not N times serialized).
from .. import compile_service as _csvc

_TRACE_LOCK = _csvc.trace_lock()


def _dequant_params(qdtypes, param_datas, param_ranges):
    """In-trace reconstruction of compute-dtype parameter buffers from
    the (possibly int8) stored form. Module-level ON PURPOSE: the
    compile service shares ONE build closure across a ReplicaSet's
    identical lowerings, and a closure over a predictor instance would
    pin that replica's device buffers past its retirement. The range is
    a traced argument: ``refresh_params()`` never recompiles."""
    if not any(q is not None for q in qdtypes):
        return list(param_datas)
    from ..ops.registry import get_op
    deq = get_op("dequantize").fn  # raw jnp-level op
    return [d if qdt is None else deq(d, -r, r).astype(qdt)
            for d, r, qdt in zip(param_datas, param_ranges, qdtypes)]


def serve_int8_default():
    """The int8 inference lever (``MXTPU_SERVE_INT8``, default 0): 1 =
    serving stores weights (and decode KV caches) as symmetric int8 +
    per-tensor scale, dequantized in-executable through
    ``ops.quantization.dequantize`` — roughly half the resident bytes per
    replica, so the KV accountant admits ~2x the sequences. Read at
    Predictor/DecodeEngine CONSTRUCTION (host-side, not ``policy_key``):
    the flag is baked per instance, so a mid-run env flip can never alias
    an executable — it only affects predictors built after it."""
    import os
    # == "1" like every other boolean lever (MXTPU_PALLAS_CONV, ...):
    # "false"/"off" must not silently enable quantization
    return os.environ.get("MXTPU_SERVE_INT8", "0") == "1"


def pad_nd(arr, batch, seq_len=None, seq_axis=1, pad_value=0):
    """Pad ``arr`` (NDArray / jax / numpy) with ``pad_value`` rows up to
    ``batch`` along axis 0 — and, when ``seq_len`` is given and the array
    has a ``seq_axis`` dimension, up to ``seq_len`` along that axis too.
    Device-side (``jnp.pad``): no host sync, so it is safe inside the
    zero-d2h predict span. Returns an NDArray."""
    d = arr._data if isinstance(arr, NDArray) else jnp.asarray(arr)
    pads = [(0, 0)] * d.ndim
    if d.shape[0] > batch:
        raise MXNetError("pad_nd: batch %d exceeds bucket %d"
                         % (d.shape[0], batch))
    pads[0] = (0, batch - d.shape[0])
    if seq_len is not None and d.ndim > seq_axis:
        if d.shape[seq_axis] > seq_len:
            raise MXNetError("pad_nd: axis %d size %d exceeds bucket %d"
                             % (seq_axis, d.shape[seq_axis], seq_len))
        pads[seq_axis] = (0, seq_len - d.shape[seq_axis])
    if not any(p[1] for p in pads):
        return arr if isinstance(arr, NDArray) else NDArray(d)
    return NDArray(jnp.pad(d, pads, constant_values=pad_value))


def _as_nds(args):
    return [a if isinstance(a, NDArray) else NDArray(jnp.asarray(a))
            for a in args]


def _eager_forward(block, nds):
    """One eager forward with taping off — settles deferred parameter
    shapes (shared by Predictor._settle and the pre-restore settle in
    from_trainer_checkpoint)."""
    from .. import autograd
    with autograd.pause():
        block(*nds)


class BucketSpec:
    """The closed set of compiled shapes a Predictor serves.

    ``batch_sizes`` are the batch buckets (ascending); a request of n
    items executes at the smallest bucket >= n (requests larger than the
    max bucket are chunked). ``seq_lens`` optionally adds a second bucket
    axis for variable-length inputs (sequence length / spatial dim along
    ``seq_axis`` of every input that has it); a request whose seq exceeds
    the max seq bucket is refused — sequences, unlike batches, cannot be
    chunked without changing the model's semantics.

    ``decode_slots`` is the third spelling (mutually exclusive with both
    of the above): the CAPACITY buckets of a continuous-batching decode
    cohort (:class:`~mxtpu.serving.decode.DecodeEngine`). A decode slot
    carries KV-cache state across steps, so there is no seq axis to
    bucket (the cache length is fixed at the engine's ``max_len``) and
    no request batch to pad — the buckets say how many LIVE slots a step
    executable covers. A decode spec cannot be served by a
    :class:`Predictor` (and vice versa); both misuses refuse loudly.

    Guidance (docs/serving.md): powers of two up to the throughput knee
    of the model (``tools/serve_bench.py --mode sweep`` finds it);
    #buckets is also the startup compile count and the per-model
    executable-cache footprint, so keep it small (4-8 is typical).
    """

    def __init__(self, batch_sizes=None, seq_lens=None, seq_axis=1,
                 pad_value=0, decode_slots=None):
        if decode_slots is not None:
            # the decode-cohort spelling: capacity buckets ONLY — mixing
            # in prefill-shape axes is a category error and must be as
            # loud as the seq-refusal path (ISSUE 11 satellite)
            if batch_sizes is not None:
                raise MXNetError(
                    "BucketSpec: decode_slots=%r cannot combine with "
                    "batch_sizes=%r — a decode cohort's buckets ARE its "
                    "slot capacities; prefill batch buckets belong to the "
                    "separate prefill BucketSpec (docs/serving.md)"
                    % (decode_slots, batch_sizes))
            if seq_lens is not None:
                raise MXNetError(
                    "BucketSpec: decode_slots=%r cannot combine with "
                    "seq_lens=%r — decode slots carry KV caches of the "
                    "engine's fixed max_len; there is no seq axis to "
                    "bucket (docs/serving.md)" % (decode_slots, seq_lens))
            batch_sizes = decode_slots
        elif batch_sizes is None:
            raise MXNetError(
                "BucketSpec: pass batch_sizes (a served shape set) or "
                "decode_slots (a decode-cohort capacity set)")
        sizes = sorted({int(b) for b in batch_sizes})
        if not sizes or sizes[0] < 1:
            raise MXNetError("BucketSpec: %s must be >= 1, got %r"
                             % ("decode_slots" if decode_slots is not None
                                else "batch_sizes", batch_sizes))
        self.batch_sizes = tuple(sizes)
        self.decode_slots = self.batch_sizes if decode_slots is not None \
            else None
        self.seq_lens = tuple(sorted({int(s) for s in seq_lens})) \
            if seq_lens else None
        self.seq_axis = int(seq_axis)
        self.pad_value = pad_value

    @classmethod
    def pow2(cls, max_batch=None, seq_lens=None, seq_axis=1,
             decode_slots=None):
        """1, 2, 4, ... up to (and including) ``max_batch`` — or, with
        ``decode_slots=n`` instead, the same ladder as decode-cohort
        capacity buckets (``BucketSpec(decode_slots=[1, 2, ..., n])``)."""
        if (max_batch is None) == (decode_slots is None):
            raise MXNetError(
                "BucketSpec.pow2: pass exactly one of max_batch (a "
                "request-batch ladder) or decode_slots (a decode-cohort "
                "capacity ladder), got max_batch=%r decode_slots=%r"
                % (max_batch, decode_slots))
        if decode_slots is not None and seq_lens is not None:
            # same category error the constructor refuses — silently
            # dropping the seq buckets would surface much later as a
            # confusing spec-misuse refusal
            raise MXNetError(
                "BucketSpec.pow2: decode_slots=%r cannot combine with "
                "seq_lens=%r — decode slots carry KV caches of the "
                "engine's fixed max_len (docs/serving.md)"
                % (decode_slots, seq_lens))
        top = int(max_batch if max_batch is not None else decode_slots)
        sizes, b = [], 1
        while b < top:
            sizes.append(b)
            b *= 2
        sizes.append(top)
        if decode_slots is not None:
            return cls(decode_slots=sizes)
        return cls(sizes, seq_lens=seq_lens, seq_axis=seq_axis)

    @property
    def is_decode(self):
        """True for a decode-cohort spec (``decode_slots=`` spelling)."""
        return self.decode_slots is not None

    @property
    def max_slots(self):
        """Largest cohort capacity (decode specs only)."""
        if not self.is_decode:
            raise MXNetError("BucketSpec.max_slots on a non-decode spec "
                             "(declare it with decode_slots=)")
        return self.batch_sizes[-1]

    def slot_bucket(self, n_live):
        """Smallest capacity bucket >= n_live slots (decode specs only;
        None when n_live exceeds the max capacity — the caller queues)."""
        if not self.is_decode:
            raise MXNetError("BucketSpec.slot_bucket on a non-decode spec "
                             "(declare it with decode_slots=)")
        return self.batch_bucket(n_live)

    @property
    def max_batch(self):
        return self.batch_sizes[-1]

    def batch_bucket(self, n):
        """Smallest batch bucket >= n (None when n exceeds the max — the
        caller chunks)."""
        for b in self.batch_sizes:
            if n <= b:
                return b
        return None

    def seq_bucket(self, s):
        """Smallest seq bucket >= s; raises when s exceeds the max."""
        if self.seq_lens is None:
            return None
        for L in self.seq_lens:
            if s <= L:
                return L
        raise MXNetError(
            "request seq length %d exceeds the largest declared bucket %d "
            "(BucketSpec.seq_lens=%s) — sequences cannot be chunked"
            % (s, self.seq_lens[-1], list(self.seq_lens)))

    def buckets(self):
        """Every (batch, seq-or-None) combo — the startup compile set."""
        seqs = self.seq_lens or (None,)
        return [(b, s) for b in self.batch_sizes for s in seqs]

    def __len__(self):
        return len(self.batch_sizes) * len(self.seq_lens or (None,))

    def __repr__(self):
        if self.is_decode:
            return "BucketSpec(decode_slots=%s)" % (list(self.decode_slots),)
        return "BucketSpec(batch=%s%s)" % (
            list(self.batch_sizes),
            ", seq=%s@axis%d" % (list(self.seq_lens), self.seq_axis)
            if self.seq_lens else "")


class Predictor:
    """AOT-bucketed compiled inference over a gluon block.

    One donated ``jax.jit`` per (bucket-shapes, ``policy_key``) — the
    input buffers are freshly materialized padded arrays, so donating
    them back to XLA is free memory headroom; parameters stay
    un-donated and are reused across every call. ``warmup()`` compiles
    the whole :class:`BucketSpec` up front (call it before taking
    traffic; the :class:`~mxtpu.serving.batcher.MicroBatcher` refuses to
    start on a cold predictor unless told otherwise).

    ``predict()`` is thread-compatible after warmup: the jit cache is
    only written on a miss (warmup fills it), and compiled executables
    are safe to invoke concurrently.

    ``device=`` pins the whole predictor — parameters are ``device_put``
    there and every request buffer follows — so a
    :class:`~mxtpu.serving.replicas.ReplicaSet` can run one independent
    replica per device. ``site=`` names the retrace-watchdog site its
    compiles report to (per-replica sites ``serving.predict.r<i>`` keep
    each replica's post-warmup compile count pinned at #buckets; the
    graftlint inventory declares this cache via
    ``tools/graftlint/config.py:JIT_ALLOWLIST``).
    """

    def __init__(self, block, spec, example=None, warmup=False,
                 name="predictor", device=None, site="serving.predict",
                 int8=None, co_resident=None):
        if not hasattr(block, "_forward_eager"):
            raise MXNetError(
                "Predictor serves HybridBlock-family models (got %s); wrap "
                "symbols via Predictor.from_checkpoint" % type(block).__name__)
        if getattr(spec, "is_decode", False):
            raise MXNetError(
                "Predictor cannot serve a decode-cohort BucketSpec "
                "(decode_slots=%s): slot-capacity buckets describe a "
                "continuous-batching DecodeEngine cohort, not request "
                "shapes — declare batch_sizes/seq_lens for a Predictor "
                "(docs/serving.md)" % (list(spec.decode_slots),))
        self._block = block
        self._spec = spec
        self._name = name
        self._device = device
        self._site = site
        self._int8 = serve_int8_default() if int8 is None else bool(int8)
        # zero-arg callable returning bytes ALREADY resident on this
        # device beyond this predictor's own footprint (the zoo passes
        # its co-resident models' ledger totals) — the warmup preflight
        # judges will-it-fit against limit minus this, so overcommit
        # warns BEFORE a page-in OOMs, not after
        self._co_resident = co_resident
        self.param_version = None  # zoo version audit (refresh_params)
        self._params = None        # ordered list, fixed at first build
        self._param_datas = None
        self._param_ranges = None  # per-param int8 range r (None = not quant)
        self._param_qdtypes = None  # per-param original dtype (None = not q)
        self._templates = None     # [(trailing_shape, dtype)] per input
        self._jits = {}            # (padded shapes+dtypes, policy) -> (fn, cell)
        if example is not None:
            self._settle(example if isinstance(example, (tuple, list))
                         else (example,))
        if warmup:
            self.warmup()

    # ------------------------------------------------------------ templates
    def _settle(self, args):
        """Record each input's trailing shape + dtype (the per-bucket zero
        templates warmup compiles against) and fix the parameter list —
        running one eager forward first only if deferred shapes are still
        unsettled."""
        nds = _as_nds(args)
        params = list(self._block.collect_params().values())
        if not params or any(p._data is None for p in params):
            _eager_forward(self._block, nds)
            params = list(self._block.collect_params().values())
        if any(p._data is None for p in params):
            raise MXNetError("Predictor: parameters still uninitialized "
                             "after the example forward")
        self._params = params
        self._snapshot_params()
        self._templates = [(tuple(a._data.shape[1:]), a._data.dtype)
                           for a in nds]

    def param_args(self):
        """The (param_datas, param_ranges) pair every compiled bucket
        takes as its TRACED trailing arguments. Public seam for engines
        that compose extra executables over this predictor's parameters
        (the decode engine's paged prefix-extend and draft/verify jits
        dispatch with exactly these, so ``refresh_params()`` reaches
        them without a recompile): always pass the CURRENT pair at
        dispatch time, never capture the buffers in a closure."""
        return self._param_datas, self._param_ranges

    def _snapshot_params(self):
        """Capture the parameter buffers the jits will run against —
        int8-quantized when the lever is on (shared by _settle and
        refresh_params, so a reload requantizes too)."""
        datas, ranges, qdts = self._quantize_params(
            [p.data()._data for p in self._params],
            sticky=self._param_qdtypes)
        self._param_datas = self._place(datas)
        self._param_ranges = self._place(ranges)
        self._param_qdtypes = qdts

    def _quantize_params(self, datas, sticky=None):
        """``MXTPU_SERVE_INT8`` weight storage: eligible parameter buffers
        (floating, ndim >= 2 — the weight matrices/kernels that dominate
        resident bytes; 1-d biases and BN stats stay exact) become
        symmetric int8 + a per-tensor range via
        ``ops.quantization.quantize``, and the compiled forward
        dequantizes them in-executable with the range as a TRACED argument
        — so ``refresh_params()`` after an in-place weight reload
        requantizes without recompiling a single bucket. ~1/2 the resident
        weight bytes vs bf16 (1/4 vs f32).

        ``sticky`` (the previous per-param dtype list) pins each
        parameter's eligibility after the FIRST snapshot: the
        quantized-vs-exact split is part of every compiled bucket's
        argument STRUCTURE, so a reload that turns a weight degenerate
        (all-zero) must keep its int8 slot (on a unit grid — zeros
        quantize to zeros exactly) rather than silently re-trace every
        executable behind the retrace watchdog's back."""
        n = len(datas)
        if not self._int8:
            return datas, [None] * n, [None] * n
        from ..ops.registry import get_op
        quantize = get_op("quantize").fn  # raw jnp-level op
        out, ranges, qdts = [], [], []
        for i, d in enumerate(datas):
            if sticky is not None:
                eligible = sticky[i] is not None
            else:
                eligible = d.ndim >= 2 and \
                    jnp.issubdtype(d.dtype, jnp.floating)
            r = float(jnp.max(jnp.abs(d))) if eligible else 0.0
            if eligible and not (0.0 < r < float("inf")):
                if sticky is None:
                    # first snapshot: a degenerate tensor simply keeps
                    # exact storage (no grid to land on)
                    eligible = False
                else:
                    r = 1.0  # sticky slot: unit grid, zeros stay exact
            if not eligible:
                out.append(d)
                ranges.append(None)
                qdts.append(None)
                continue
            q, _lo, _hi = quantize(d, -r, r)
            out.append(q)
            ranges.append(jnp.asarray(r, jnp.float32))
            qdts.append(str(d.dtype))
        return out, ranges, qdts

    def _place(self, datas):
        """Commit buffers to this predictor's device (identity when no
        device was pinned — the single-predictor PR-5 path). None entries
        (un-quantized slots of the int8 range list) pass through."""
        if self._device is None:
            return datas
        return [d if d is None else jax.device_put(d, self._device)
                for d in datas]

    @property
    def spec(self):
        return self._spec

    @property
    def device(self):
        return self._device

    @property
    def site(self):
        """The retrace-watchdog site this predictor's compiles report to."""
        return self._site

    @property
    def input_templates(self):
        """[(trailing_shape, dtype)] per input (None before settle)."""
        return self._templates

    @property
    def int8(self):
        """True when this predictor stores weights as int8 + scale."""
        return self._int8

    def refresh_params(self, version=None):
        """Re-snapshot parameter buffers (after an in-place reload) without
        recompiling — the jits close over nothing, params (and their int8
        ranges) are arguments. ``version=`` stamps the live param version
        for audit (``zoo.active_version{model}`` is gauged by the zoo;
        here the refresh itself is counted per site so a param swap is
        attributable from ``telemetry.report()`` alone)."""
        self._snapshot_params()
        if version is not None:
            self.param_version = version
        telemetry.inc("serving.param_refreshes", tag=self._site)

    # ------------------------------------------------------------ compiling
    def _donation(self):
        # donate the request buffers (fresh padded arrays) back to XLA —
        # free memory headroom per in-flight bucket. The CPU backend does
        # not implement donation and would warn per compile, so gate it.
        return (0,) if jax.default_backend() != "cpu" else ()

    def _fn_token(self):
        """Stable block identity for the compile service: class + forward
        source hash + parameter structure incl. the int8 split (an
        edited model or a re-quantized storage layout across restarts
        must miss the disk cache, never replay)."""
        tok = getattr(self, "_fn_token_cache", None)
        if tok is None:
            from .. import compile_service as csvc
            struct = tuple(
                (p.name, tuple(d.shape), str(d.dtype), qdt)
                for p, d, qdt in zip(self._params, self._param_datas,
                                     self._param_qdtypes))
            tok = "predictor:%s:%s:%s" % (
                type(self._block).__name__,
                csvc.source_token(type(self._block)),
                csvc.source_token(struct)[:12])
            self._fn_token_cache = tok
        return tok

    def _service_key(self, shape_key, pol):
        from .. import compile_service as csvc
        return csvc.canonical_key(
            site=self._site, fn_id=self._fn_token(),
            signature=(shape_key, self._int8), policy=pol,
            donation=self._donation(),
            device=csvc.device_token(device=self._device),
            nonce=csvc.instance_nonce(self))

    def _group_token(self, shape_key, pol):
        """Lowering-group token: everything in the service key EXCEPT
        site/device/nonce — a ReplicaSet's members differ only there, so
        their buckets share one traced artifact and compile per
        device."""
        return ("predict", self._fn_token(), shape_key, self._int8, pol,
                self._donation())

    def _prov(self, shape_key, pol):
        return {"predictor": self._name,
                "block": type(self._block).__name__,
                "device": str(self._device) if self._device is not None
                else None,
                "shapes": [list(s) for s, _ in shape_key],
                "int8": self._int8,
                "policy_key": list(pol)}

    def _build_for(self, shape_key):
        """Build closure for one bucket signature. Closes over the
        SHARED block/params/qdtypes only — never over this predictor
        instance — so the compile service can reuse it across a
        ReplicaSet's identical lowerings without pinning any one
        replica's device buffers."""
        block, params = self._block, self._params
        qdtypes = tuple(self._param_qdtypes or ())
        fixed_key = jax.random.PRNGKey(0)  # deterministic inference: no
        # stochastic layers are live under train=False
        donate = self._donation()

        def build():
            cell = {}

            def pure(in_datas, param_datas, param_ranges):
                from ..gluon.block import _flatten_nd, _run_traced

                param_datas = _dequant_params(qdtypes, param_datas,
                                              param_ranges)

                def body():
                    return block(*[NDArray(d) for d in in_datas])

                out, _aux = _run_traced(params, param_datas, fixed_key,
                                        False, body)
                fmt = []
                flat = _flatten_nd(out, fmt)
                cell["out_fmt"] = fmt
                return [o._data for o in flat]

            return jax.jit(pure, donate_argnums=donate), cell

        return build

    def _get_jit(self, shape_key, example_datas=None):
        from .. import compile_service as csvc
        from ..ops.registry import policy_key
        pol = policy_key()
        key = (shape_key, pol)
        hit = self._jits.get(key)
        if hit is not None:
            return hit
        # retrace watchdog: every serving compile is a served-request stall
        # — after warmup this site MUST stay at #buckets (an off-template
        # request shape or a policy env flip under the server shows up
        # here with full provenance). The site name is per-instance so a
        # ReplicaSet member reports at serving.predict.r<i>; the static
        # lint declares this cache via JIT_ALLOWLIST (docs/serving.md).
        example = None
        if example_datas is not None:
            example = csvc.concrete_args(
                (list(example_datas), self._param_datas,
                 self._param_ranges))
        entry = csvc.get_or_build(
            self._service_key(shape_key, pol), self._build_for(shape_key),
            provenance=self._prov(shape_key, pol), example_args=example,
            group=self._group_token(shape_key, pol))
        self._jits[key] = (entry.fn, entry.meta)
        return self._jits[key]

    def _bucket_datas(self, b, s):
        datas = [jnp.zeros((b,) + self._bucket_trailing(t, s), dt)
                 for t, dt in self._templates]
        return self._place(datas)

    def warmup_entries(self):
        """The declared AOT warmup set: one compile-service entry per
        bucket, group-tagged so identical replicas share the trace. A
        ReplicaSet collects every member's entries into ONE concurrent
        ``compile_service.warmup`` call."""
        if self._templates is None:
            raise MXNetError("Predictor.warmup needs input templates: pass "
                             "example= at construction")
        from .. import compile_service as csvc
        from ..ops.registry import policy_key
        pol = policy_key()
        entries = []
        for b, s in self._spec.buckets():
            datas = self._bucket_datas(b, s)
            shape_key = tuple((tuple(d.shape), str(d.dtype))
                              for d in datas)
            entries.append(csvc.WarmupEntry(
                key=self._service_key(shape_key, pol),
                build=self._build_for(shape_key),
                example_args=(datas, self._param_datas,
                              self._param_ranges),
                provenance=self._prov(shape_key, pol),
                group=self._group_token(shape_key, pol)))
        return entries

    def finish_warmup(self):
        """Adopt warmed entries into the instance cache by DISPATCHING
        each bucket once (zero-filled templates, blocking) — the
        executables are already compiled (service hits), so these are
        pure replays, but a model that compiles yet cannot EXECUTE on
        this device (HBM exhausted by workspace allocation) must fail
        here, at startup, not on the first live request. Closes with
        the gauges and the memory pre-flight."""
        for b, s in self._spec.buckets():
            flat, _ = self._run_padded(self._bucket_datas(b, s))
            jax.block_until_ready([o._data for o in flat])
        telemetry.gauge("serving.buckets", len(self._spec))
        # will-it-fit pre-flight over the freshly-warmed bucket
        # executables (no-op on limit-less backends — zero extra
        # lowering on the CPU tier) + the live HBM gauges
        from .. import xprof
        xprof.ensure_memwatch()
        extra = int(self._co_resident()) if self._co_resident else 0
        xprof.preflight(self._site,
                        device=self._device if self._device is not None
                        else 0, extra_bytes=extra)
        return self

    def warmup(self):
        """AOT-compile every bucket in the spec through the compile
        service — concurrent lowers/compiles on the service pool, disk
        hits cost zero compiles. Returns self. Idempotent: warm buckets
        are cache hits."""
        from .. import compile_service as csvc
        csvc.warmup(self.warmup_entries())
        return self.finish_warmup()

    def _bucket_trailing(self, trailing, seq):
        if seq is None:
            return trailing
        ax = self._spec.seq_axis - 1  # trailing shape drops the batch dim
        if ax < len(trailing):
            t = list(trailing)
            t[ax] = seq
            return tuple(t)
        return trailing

    # ------------------------------------------------------------ predicting
    def _run_padded(self, datas):
        """Dispatch already-bucket-shaped jax arrays; returns (flat output
        NDArrays at bucket batch, cell)."""
        shape_key = tuple((tuple(d.shape), str(d.dtype)) for d in datas)
        jitted, cell = self._get_jit(shape_key, example_datas=datas)
        from .. import resilience, xprof
        try:
            resilience.maybe_oom()
            if "out_fmt" not in cell:
                # first invocation of this executable traces the shared
                # block (see _TRACE_LOCK): serialize across replicas'
                # predictors
                with _TRACE_LOCK:
                    out = jitted(list(datas), self._param_datas,
                                 self._param_ranges)
            else:
                out = jitted(list(datas), self._param_datas,
                             self._param_ranges)
        except Exception as e:
            if xprof.is_oom(e):
                # HBM OOM on the predict dispatch: artifact (ledger +
                # per-device memory stats) first, then fail LOUD — the
                # batcher's dispatch error path completes the cohort's
                # futures with this error, never hangs them
                ctx = telemetry.current_trace()
                xprof.oom_flight(self._site, e,
                                 trace_ids=[ctx.trace_id] if ctx else [])
            raise
        return [NDArray(d) for d in out], cell

    def predict_flat(self, args):
        """Pad ``args`` (a tuple of per-input arrays sharing batch axis 0)
        to their bucket, run the compiled forward, and slice back: returns
        ``(flat_outputs, out_fmt, bucket_batch)`` where flat_outputs are
        device NDArrays sliced to the request's n — NO host sync happens
        here; fetching the outputs is the caller's declared d2h.

        Requests larger than the max bucket are chunked through it and
        re-concatenated on device."""
        if self._templates is None:
            self._settle(args)
        spec = self._spec
        # the jit DONATES its input buffers; a caller's live buffer reaching
        # it un-padded (exact bucket fit) would be invalidated under the
        # caller — protect every buffer the caller still holds a reference
        # to (NDArray._data, and raw jax arrays where asarray is identity;
        # numpy inputs become fresh device buffers and need no copy)
        datas, user_bufs = [], set()
        for a in args:
            d = a._data if isinstance(a, NDArray) else jnp.asarray(a)
            protect = isinstance(a, NDArray) or d is a
            if self._device is not None:
                # pinned predictor (ReplicaSet member): commit the request
                # buffers to the replica's device. device_put MAY alias
                # the input buffer (uncommitted array already resident on
                # this device), so protection is never dropped here — the
                # worst case is one extra jnp.copy on an exact-bucket-fit
                # caller buffer, never a donated-out-from-under caller
                d = jax.device_put(d, self._device)
            if protect:
                user_bufs.add(id(d))
            datas.append(d)
        n = int(datas[0].shape[0])
        if n == 0:
            raise MXNetError("predict on an empty batch")
        seq = None
        if spec.seq_lens is not None:
            seq = spec.seq_bucket(int(datas[0].shape[spec.seq_axis])
                                  if datas[0].ndim > spec.seq_axis else 0)
        with telemetry.span("serving.predict", d2h=True):
            b = spec.batch_bucket(n)
            if b is None:
                # chunk through the max bucket, concat on device
                chunks, fmt, bucket = [], None, spec.max_batch
                for lo in range(0, n, bucket):
                    part = [d[lo:lo + bucket] for d in datas]
                    flat, fmt, _ = self._dispatch_one(part, seq, bucket,
                                                      user_bufs)
                    chunks.append(flat)
                flat_out = [NDArray(jnp.concatenate(
                    [c[i]._data for c in chunks], axis=0))
                    for i in range(len(chunks[0]))]
                telemetry.inc("serving.items", n)
                return flat_out, fmt, bucket
            flat, fmt, _ = self._dispatch_one(datas, seq, b, user_bufs)
            telemetry.inc("serving.items", n)
            return flat, fmt, b

    def _dispatch_one(self, datas, seq, bucket, protect=()):
        n = int(datas[0].shape[0])
        padded = [pad_nd(d, bucket, seq_len=seq, seq_axis=self._spec.seq_axis,
                         pad_value=self._spec.pad_value)._data for d in datas]
        padded = [jnp.copy(d) if id(d) in protect else d for d in padded]
        flat, cell = self._run_padded(padded)
        telemetry.observe("serving.batch_fill", n / float(bucket))
        if n != bucket:
            flat = [NDArray(o._data[:n]) for o in flat]
        return flat, cell["out_fmt"], bucket

    def predict(self, *args):
        """The user-facing call: accepts NDArrays / numpy arrays, returns
        the block's output structure (single NDArray or tuple) sliced to
        the request batch. Device outputs — call ``.asnumpy()`` to fetch
        (the one declared d2h of the serving hot path)."""
        from ..gluon.block import _regroup
        flat, fmt, _ = self.predict_flat(args)
        out, _, _ = _regroup(flat, fmt)
        return out

    def _traced_params(self, param_datas, param_ranges):
        """In-trace reconstruction of compute-dtype parameter buffers
        from the (possibly int8) stored form — shared by this predictor's
        own pure fns and the DecodeEngine's step/insert jits (which run
        against the same stored buffers). The range is a traced argument:
        a ``refresh_params()`` re-quantization never recompiles."""
        return _dequant_params(tuple(self._param_qdtypes or ()),
                               param_datas, param_ranges)

    def compile_stats(self):
        """The watchdog's view of THIS predictor's compiles — its own
        retrace site (per-replica for ReplicaSet members):
        {compiles, trips, last} (None before any compile)."""
        return telemetry.retrace_stats(self._site)

    # ------------------------------------------------------------ load paths
    @classmethod
    def from_checkpoint(cls, prefix, epoch, spec, input_names=("data",),
                        example=None, warmup=False, name=None):
        """The c_predict_api shape: (symbol-json, params) checkpoint on
        disk -> a served SymbolBlock. ``prefix``/``epoch`` follow
        ``model.save_checkpoint`` / ``HybridBlock.export`` naming."""
        from .. import symbol as sym_mod
        from ..gluon.block import SymbolBlock
        from ..model import load_checkpoint
        sym, arg_params, aux_params = load_checkpoint(prefix, epoch)
        if sym is None:
            raise MXNetError("no symbol file at %s-symbol.json" % prefix)
        if isinstance(input_names, str):
            input_names = [input_names]
        blk = SymbolBlock(sym, [sym_mod.var(n) for n in input_names])
        pd = blk.collect_params()
        for pname, arr in list(arg_params.items()) + list(aux_params.items()):
            if pname in pd:
                pd[pname].set_data(arr)
        return cls(blk, spec, example=example, warmup=warmup,
                   name=name or ("ckpt:" + str(prefix)))

    @classmethod
    def from_trainer_checkpoint(cls, block, directory, spec, step=None,
                                example=None, warmup=False, name=None):
        """Serve straight from a training run's orbax checkpoint: restores
        ONLY the params subtree of a ``contrib.async_checkpoint.
        save_trainer`` step (latest finalized step when ``step=None``)
        into ``block`` — optimizer state and RNG stay untouched. The
        block must be built + initialized with shapes settled, exactly
        like the trainer that saved (positional keys)."""
        from ..contrib import async_checkpoint as ackpt
        if example is not None and any(
                p._data is None for p in block.collect_params().values()):
            # settle deferred shapes BEFORE the positional-key restore
            _eager_forward(block, _as_nds(
                example if isinstance(example, (tuple, list)) else (example,)))
        ackpt.load_trainer_params_into_block(block, directory, step=step)
        return cls(block, spec, example=example, warmup=warmup,
                   name=name or ("trainer:" + str(directory)))
