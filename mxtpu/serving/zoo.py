"""Multi-tenant model zoo: one serving plane for N models over one pool.

Every serving construct so far (Predictor, ReplicaSet, controller,
decode engine) assumes ONE model per process; production means a *zoo*:
many registered models, a few of them hot, multiplexed over fewer
devices than one-model-per-replica would need. This module is the
PAPER.md dependency-engine lesson (schedule from *observed* demand, not
static assignment) applied at fleet granularity, with **HBM as the
shared currency** and the steady state kept pure replay (PyGraph's
capture/replay economics, arXiv:2503.19779) — a model swap must never
compile on the hot path.

* :class:`ModelZoo` — the registry: named models × immutable versions.
  Each version is a checkpoint ref OR a host-side parameter snapshot,
  plus the model's :class:`~mxtpu.serving.engine.BucketSpec` and the
  ``ops.registry.policy_key()`` snapshot it was registered under; the
  manifest (``zoo_manifest.json``) persists beside the compile-cache
  artifacts so a warm-started process can enumerate what is servable
  without touching a device.
* :class:`ZooScheduler` — multiplexes the registry over a device pool.
  Per-model resident cost comes from the xprof ledger
  (:func:`mxtpu.xprof.site_footprint`: donation-adjusted params +
  executables args, per-dispatch temps, output residents); demand from
  a decayed per-model request rate. Placement evicts the coldest
  resident (``zoo.evictions{model:reason}``; its queued + in-flight
  futures complete FIRST — eviction never strands a request) and pages
  the hot model in as a **disk-warm no-compile event** through
  ``compile_service.warmup`` (every bucket resolves as a disk hit, so
  ``retrace.serving.predict*`` stays 0 on a warm page-in). A request
  for a non-resident model either queues behind a bounded page-in
  (``MXTPU_ZOO_PAGEIN_QUEUE``) or sheds ``zoo_cold`` by policy.
* **Per-tenant SLO classes** — a tenant maps to a priority class +
  deadline default (the existing MicroBatcher priority machinery does
  the rest: interactive wins the coalescing slot, batch ages in and is
  evicted first), and every delivery's deadline verdict feeds the
  per-model :class:`~mxtpu.serving.controller.ServingController`'s
  per-tenant goodput-attainment counters
  (``serving.tenant_attainment{tenant}``).
* **Live rollout** — :meth:`ModelZoo.deploy` generalizes PR 11's
  ``refresh_params`` to versioned canary routing: ``canary_frac`` of a
  model's traffic routes to the new weights by a deterministic hash of
  the request id (stable across processes — a retried request lands on
  the same arm). The canary serves through its OWN executables at
  ``<site>.canary`` (disk-warm where possible; its compiles are pinned
  ≤ #buckets at its own watchdog site), while **promote** swaps the new
  version's params into the stable Predictor via the no-recompile
  ``refresh_params`` path — the int8 quantization-eligibility pin
  (PR 11 stickiness) is re-asserted across the versioned swap by
  construction. Auto-**rollback** fires when the canary's SLO
  attainment drops under ``MXTPU_ZOO_CANARY_FLOOR`` with enough
  verdicts in the window, or when the deploy-time output-parity probe
  regresses past ``MXTPU_ZOO_PARITY_TOL`` (``zoo.rollbacks{reason}`` +
  ``flight_record("canary_rollback")``). Zero requests drop across
  promote/rollback: the retiring arm's queued + in-flight futures
  complete before its executables are released.

Deterministic fault kinds (``MXTPU_FAULT_INJECT``): ``zoo_cold`` — the
next zoo submit sheds as if its model were cold and unpageable;
``canary_rollback`` — the next canary gate evaluation rules regression.

Everything runs on an injected clock; with ``start=False`` the whole
placement/canary matrix is driven sleep-free through :meth:`poll`
(tier-1 tests), with ``start=True`` each resident model gets its
batcher worker and the zoo a monitor thread (the bench/server mode).
"""
from __future__ import annotations

import collections
import json
import logging
import math
import os
import threading
import time
import zlib

import numpy as np

from .. import compile_service as csvc
from .. import telemetry, xprof
from ..base import MXNetError
from ..resilience import inject
from .batcher import DeadlineExceeded, MicroBatcher, QueueFull
from .controller import ServingController
from .engine import Predictor

__all__ = ["ModelZoo", "ZooScheduler", "ZooVersion",
           "zoo_max_resident_default", "zoo_hbm_budget_default",
           "zoo_cold_policy_default", "zoo_pagein_queue_default",
           "zoo_demand_horizon_default", "zoo_canary_floor_default",
           "zoo_canary_window_default", "zoo_parity_tol_default"]

_log = logging.getLogger("mxtpu.serving")

# the retrace-site family every zoo predictor reports under: page-ins
# are gated on this family staying compile-free off a warm disk cache
_SITE_ROOT = "serving.predict.zoo"


# ------------------------------------------------------------------ policies
def zoo_max_resident_default():
    """Count cap on co-resident models per pool device
    (``MXTPU_ZOO_MAX_RESIDENT``, default 0 = uncapped by count — the
    byte budget governs). The lever for backends without memory stats
    (CPU tier) and for tests forcing paging pressure."""
    return int(os.environ.get("MXTPU_ZOO_MAX_RESIDENT", "0"))


def zoo_hbm_budget_default():
    """Per-device HBM budget in bytes for zoo placement
    (``MXTPU_ZOO_HBM_BUDGET``, default 0 = the device's reported
    ``bytes_limit``). Placement evicts the coldest resident before a
    page-in would push the ledger-derived resident total past it."""
    return int(os.environ.get("MXTPU_ZOO_HBM_BUDGET", "0"))


def zoo_cold_policy_default():
    """What a request for a non-resident model does
    (``MXTPU_ZOO_COLD_POLICY``: ``queue`` (default) = wait behind a
    bounded page-in; ``shed`` = refuse immediately with
    ``serving.shed{zoo_cold}``)."""
    v = os.environ.get("MXTPU_ZOO_COLD_POLICY", "queue").strip().lower()
    if v not in ("queue", "shed"):
        raise MXNetError("MXTPU_ZOO_COLD_POLICY must be queue|shed, got %r"
                         % v)
    return v


def zoo_pagein_queue_default():
    """Bound (in requests) on the per-model queue waiting behind a
    page-in (``MXTPU_ZOO_PAGEIN_QUEUE``, default 64): beyond it cold
    submits shed ``zoo_cold`` even under the ``queue`` policy."""
    return int(os.environ.get("MXTPU_ZOO_PAGEIN_QUEUE", "64"))


def zoo_demand_horizon_default():
    """Decay horizon (seconds) of the per-model demand rates placement
    ranks by (``MXTPU_ZOO_DEMAND_HORIZON_S``, default 60)."""
    return float(os.environ.get("MXTPU_ZOO_DEMAND_HORIZON_S", "60"))


def zoo_canary_floor_default():
    """Canary SLO-attainment gate (``MXTPU_ZOO_CANARY_FLOOR``, default
    0.8): a canary whose decayed goodput attainment drops below this
    (with a full verdict window) is auto-rolled-back."""
    return float(os.environ.get("MXTPU_ZOO_CANARY_FLOOR", "0.8"))


def zoo_canary_window_default():
    """Minimum decayed verdict weight before the canary gate judges
    (``MXTPU_ZOO_CANARY_WINDOW``, default 8) — a canary is never rolled
    back on its first unlucky request."""
    return float(os.environ.get("MXTPU_ZOO_CANARY_WINDOW", "8"))


def zoo_parity_tol_default():
    """Output-parity probe tolerance (``MXTPU_ZOO_PARITY_TOL``, default
    1e-2): max absolute element difference between the stable and canary
    outputs on the deploy's probe input before the deploy is refused as
    a parity regression (immediate rollback)."""
    return float(os.environ.get("MXTPU_ZOO_PARITY_TOL", "1e-2"))


class _DecayedRate:
    """Exponentially-decayed event rate on the injected clock — the
    per-model demand signal placement ranks by."""

    __slots__ = ("v", "t", "horizon")

    def __init__(self, horizon_s):
        self.v = 0.0
        self.t = None
        self.horizon = float(horizon_s)

    def _decay(self, now):
        if self.t is not None and now > self.t:
            self.v *= math.exp(-(now - self.t) / self.horizon)
        self.t = now

    def observe(self, n, now):
        self._decay(now)
        self.v += float(n)

    def rate(self, now):
        self._decay(now)
        return self.v / self.horizon


# ------------------------------------------------------------------ registry
class ZooVersion:
    """One immutable version of a zoo model: a parameter source (host
    snapshot or checkpoint ref), the BucketSpec it serves under, and the
    policy snapshot it was registered with. ``ordinal`` is the
    registration sequence number — what ``zoo.active_version{model}``
    gauges (telemetry gauges are numeric; the manifest maps ordinals
    back to names)."""

    __slots__ = ("model", "version", "spec", "policy", "checkpoint",
                 "params", "created", "ordinal")

    def __init__(self, model, version, spec, policy, ordinal,
                 params=None, checkpoint=None):
        self.model = model
        self.version = version
        self.spec = spec
        self.policy = tuple(policy) if policy is not None else ()
        self.checkpoint = checkpoint
        self.params = params          # {param name: host ndarray} or None
        self.created = time.time()
        self.ordinal = int(ordinal)

    def describe(self):
        return {"version": self.version, "ordinal": self.ordinal,
                "created": self.created,
                "checkpoint": self.checkpoint,
                "policy": list(self.policy),
                "spec": repr(self.spec),
                "params": sorted(self.params) if self.params else None}


class _ZooModel:
    __slots__ = ("name", "block", "spec", "example", "versions", "active",
                 "next_ordinal")

    def __init__(self, name, block, spec, example):
        self.name = name
        self.block = block
        self.spec = spec
        self.example = example
        self.versions = collections.OrderedDict()
        self.active = None
        self.next_ordinal = 0


def _snapshot_block_params(block):
    """Host-side copy of every parameter buffer — the immutable params
    a version stores (versions must not alias the live mutable block)."""
    out = {}
    for name, p in block.collect_params().items():
        out[name] = np.array(p.data().asnumpy(), copy=True)
    return out


class ModelZoo:
    """The registry half: named models × immutable versions, manifest
    persisted beside the compile-cache artifacts. Placement/serving is
    :class:`ZooScheduler`'s job; :meth:`deploy` delegates to the
    attached scheduler (and degrades to a registry-only active-version
    flip when none is attached)."""

    def __init__(self, manifest_dir=None):
        self._models = collections.OrderedDict()
        self._lock = threading.RLock()
        self._manifest_dir = manifest_dir
        self._sched = None

    # ------------------------------------------------------------ registration
    def register(self, name, block, spec, example=None, version="v1",
                 checkpoint=None):
        """Register a model under ``name`` with its first version (the
        block's CURRENT parameters unless ``checkpoint`` names an
        external ref). Model names join retrace-site/metric families, so
        they are restricted to ``[A-Za-z0-9_-]``."""
        if not name or not all(c.isalnum() or c in "_-" for c in name):
            raise MXNetError("ModelZoo.register: model name %r must be "
                             "non-empty [A-Za-z0-9_-]" % (name,))
        with self._lock:
            if name in self._models:
                raise MXNetError("ModelZoo.register: model %r already "
                                 "registered — use add_version" % name)
            self._models[name] = _ZooModel(name, block, spec, example)
        self.add_version(name, version, checkpoint=checkpoint)
        return self._models[name]

    def add_version(self, name, version, params=None, checkpoint=None):
        """Add one immutable version: ``params`` (a ``{name: array}``
        host snapshot), a ``checkpoint`` ref (loaded lazily on first
        apply), or — with neither — a snapshot of the block's current
        parameters. The first version becomes active."""
        m = self._get(name)
        with self._lock:
            if version in m.versions:
                raise MXNetError(
                    "ModelZoo.add_version: %s@%s already exists — "
                    "versions are immutable" % (name, version))
            if params is None and checkpoint is None:
                params = _snapshot_block_params(m.block)
            from ..ops.registry import policy_key
            ver = ZooVersion(name, version, m.spec, policy_key(),
                             m.next_ordinal, params=params,
                             checkpoint=checkpoint)
            m.next_ordinal += 1
            m.versions[version] = ver
            if m.active is None:
                m.active = version
        self._persist_manifest()
        return ver

    def _get(self, name):
        with self._lock:
            m = self._models.get(name)
        if m is None:
            raise MXNetError("ModelZoo: unknown model %r (known: %s)"
                             % (name, ", ".join(self.models()) or "none"))
        return m

    def models(self):
        with self._lock:
            return list(self._models)

    def versions(self, name):
        return list(self._get(name).versions)

    def active_version(self, name):
        return self._get(name).active

    def version(self, name, version):
        m = self._get(name)
        with self._lock:
            ver = m.versions.get(version)
        if ver is None:
            raise MXNetError(
                "ModelZoo: unknown version %r for model %r (known: %s)"
                % (version, name, ", ".join(m.versions)))
        return ver

    def set_active(self, name, version):
        ver = self.version(name, version)
        with self._lock:
            self._get(name).active = version
        self._persist_manifest()
        return ver

    # -------------------------------------------------------------- params
    def apply_version(self, name, version):
        """Load a version's parameters into the model's (shared) block —
        the step right before a Predictor build or ``refresh_params``
        snapshots them. Checkpoint-ref versions load (and cache) their
        params here, on first use."""
        m = self._get(name)
        ver = self.version(name, version)
        with self._lock:
            if ver.params is None:
                ver.params = self._load_checkpoint_params(ver)
            pd = m.block.collect_params()
            for pname, arr in ver.params.items():
                if pname in pd:
                    pd[pname].set_data(arr)
        return ver

    @staticmethod
    def _load_checkpoint_params(ver):
        """Resolve a checkpoint-ref version to a host param mapping
        (``model.save_checkpoint`` naming: ``(prefix, epoch)``)."""
        from ..model import load_checkpoint
        prefix, epoch = ver.checkpoint
        _sym, arg_params, aux_params = load_checkpoint(prefix, epoch)
        out = {}
        for pname, arr in list(arg_params.items()) + list(aux_params.items()):
            out[pname] = np.array(arr.asnumpy() if hasattr(arr, "asnumpy")
                                  else arr, copy=True)
        return out

    # ------------------------------------------------------------- manifest
    def _manifest_path(self):
        root = self._manifest_dir or csvc.cache_dir()
        if not root:
            return None
        return os.path.join(root, "zoo_manifest.json")

    def _persist_manifest(self):
        """Best-effort manifest write beside the compile-cache blobs —
        the human/warm-start index of what is servable (per-entry
        executables stay authoritative, exactly like the compile
        service's own ``manifest.json``)."""
        path = self._manifest_path()
        if path is None:
            return
        with self._lock:
            doc = {"format": 1, "models": {
                m.name: {"active": m.active,
                         "spec": repr(m.spec),
                         "versions": {v: ver.describe()
                                      for v, ver in m.versions.items()}}
                for m in self._models.values()}}
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f, indent=1, default=repr)
            os.replace(tmp, path)
        except OSError:  # advisory index only
            _log.debug("zoo manifest write failed", exc_info=True)

    def manifest(self):
        """The persisted manifest dict ({} when absent/unwritable)."""
        path = self._manifest_path()
        if path is None:
            return {}
        try:
            with open(path, "r", encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            return {}

    # --------------------------------------------------------------- rollout
    def attach_scheduler(self, sched):
        self._sched = sched
        return self

    def deploy(self, model, version, canary_frac=1.0, parity_example=None,
               parity_tol=None):
        """Roll ``version`` out for ``model``: ``canary_frac >= 1`` is a
        direct promote (the resident Predictor adopts the new params via
        the no-recompile ``refresh_params`` path); ``0 < canary_frac <
        1`` starts a canary arm taking that fraction of traffic behind
        the auto-rollback gate. Returns a status dict."""
        if self._sched is not None:
            return self._sched.deploy(model, version,
                                      canary_frac=canary_frac,
                                      parity_example=parity_example,
                                      parity_tol=parity_tol)
        ver = self.set_active(model, version)
        telemetry.inc("zoo.deploys", tag=model)
        return {"model": model, "version": version, "mode": "registry",
                "ordinal": ver.ordinal}


# ----------------------------------------------------------------- scheduler
class _ZooFuture:
    """Completion handle for a request that queued behind a page-in: it
    BINDS to the real batcher future once the model is resident (or
    fails with the shed/deadline verdict). ``result`` therefore waits
    at most page-in + service; trace fields proxy through after bind."""

    __slots__ = ("_event", "_inner", "_error")

    def __init__(self):
        self._event = threading.Event()
        self._inner = None
        self._error = None

    def _bind(self, inner):
        self._inner = inner
        self._event.set()

    def _fail(self, error):
        self._error = error
        self._event.set()

    def done(self):
        if not self._event.is_set():
            return False
        return self._error is not None or self._inner.done()

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise DeadlineExceeded("no page-in within %ss" % timeout)
        if self._error is not None:
            raise self._error
        return self._inner.result(timeout)

    @property
    def trace_id(self):
        return self._inner.trace_id if self._inner is not None else None

    @property
    def breakdown(self):
        return self._inner.breakdown if self._inner is not None else None

    @property
    def e2e_s(self):
        return self._inner.e2e_s if self._inner is not None else None


class _Pending:
    __slots__ = ("inputs", "n", "deadline_ms", "priority", "meta", "t0",
                 "future")

    def __init__(self, inputs, n, deadline_ms, priority, meta, t0):
        self.inputs = inputs
        self.n = n
        self.deadline_ms = deadline_ms
        self.priority = priority
        self.meta = meta
        self.t0 = t0
        self.future = _ZooFuture()


class _Arm:
    """One serving arm of a resident model (stable or canary): a warmed
    Predictor + its MicroBatcher + per-arm SLO controller."""

    __slots__ = ("version", "predictor", "batcher", "ctrl", "site")

    def __init__(self, version, predictor, batcher, ctrl):
        self.version = version
        self.predictor = predictor
        self.batcher = batcher
        self.ctrl = ctrl
        self.site = predictor.site


class _Resident:
    __slots__ = ("model", "dslot", "device", "stable", "canary",
                 "canary_frac", "footprint", "warm_summary")

    def __init__(self, model, dslot, device, stable, warm_summary):
        self.model = model
        self.dslot = dslot
        self.device = device
        self.stable = stable
        self.canary = None
        self.canary_frac = 0.0
        self.footprint = 0
        self.warm_summary = warm_summary


class ZooScheduler:
    """See the module docstring. ``zoo`` is the :class:`ModelZoo`;
    ``devices`` the pool (default: every visible device). ``start=False``
    + an injected ``clock`` keeps everything synchronous for tests
    (:meth:`poll` drives dispatch, page-ins run inline at submit);
    ``start=True`` starts per-model batcher workers, runs page-ins on
    side threads, and spins the monitor that evaluates the canary
    gate."""

    def __init__(self, zoo, devices=None, clock=time.monotonic, start=True,
                 max_resident=None, hbm_budget=None, cold_policy=None,
                 pagein_queue=None, demand_horizon_s=None, tenants=None,
                 controller=True, batcher_kw=None):
        import jax
        self._zoo = zoo
        self._devices = list(devices) if devices else list(jax.devices())
        if not self._devices:
            raise MXNetError("ZooScheduler: empty device pool")
        self._clock = clock
        self._threaded = bool(start)
        self.max_resident = int(max_resident if max_resident is not None
                                else zoo_max_resident_default())
        self.hbm_budget = int(hbm_budget if hbm_budget is not None
                              else zoo_hbm_budget_default())
        self.cold_policy = (cold_policy if cold_policy is not None
                            else zoo_cold_policy_default())
        if self.cold_policy not in ("queue", "shed"):
            raise MXNetError("ZooScheduler: cold_policy must be "
                             "queue|shed, got %r" % (self.cold_policy,))
        self.pagein_queue = int(pagein_queue if pagein_queue is not None
                                else zoo_pagein_queue_default())
        self._horizon = float(demand_horizon_s if demand_horizon_s
                              is not None else zoo_demand_horizon_default())
        self._use_controller = bool(controller)
        self._batcher_kw = dict(batcher_kw or {})
        self._lock = threading.RLock()
        self._residents = {}        # model -> _Resident
        self._pending = {}          # model -> deque[_Pending]
        self._paging = set()        # models with a page-in in flight
        self._footprints = {}       # model -> last measured resident bytes
        self._demand = {}           # model -> _DecayedRate
        self._tenants = {}          # tenant -> {"priority","deadline_ms"}
        for t, cls in (tenants or {}).items():
            self.set_tenant(t, **cls)
        self._rid = 0
        self._draining = False
        self._closed = False
        self._monitor = None
        self._stop = threading.Event()
        zoo.attach_scheduler(self)
        telemetry.gauge("zoo.resident_models", 0)
        if self._threaded:
            self._monitor = threading.Thread(
                target=self._monitor_loop, daemon=True,
                name="mxtpu-zoo-monitor")
            self._monitor.start()

    @property
    def registry(self):
        """The :class:`ModelZoo` this scheduler serves."""
        return self._zoo

    # --------------------------------------------------------------- tenants
    def set_tenant(self, tenant, priority="interactive", deadline_ms=None):
        """Declare one tenant's SLO class: its default priority class and
        deadline. Unknown tenants serve as ``interactive`` with no
        deadline default."""
        from .batcher import PRIORITIES
        if priority not in PRIORITIES:
            raise MXNetError("set_tenant: unknown priority %r (expected "
                             "one of %s)" % (priority, "|".join(PRIORITIES)))
        with self._lock:
            self._tenants[tenant] = {"priority": priority,
                                     "deadline_ms": deadline_ms}
        return self

    def tenant_class(self, tenant):
        with self._lock:
            return dict(self._tenants.get(tenant)
                        or {"priority": "interactive", "deadline_ms": None})

    # ------------------------------------------------------------ submission
    def submit(self, model, inputs, tenant=None, deadline_ms=None,
               priority=None, request_id=None, version=None):
        """Route one request by model name. Tenant defaults fill the
        priority/deadline the caller left unset; ``version=`` pins the
        request to a specific live arm (stable or canary) instead of the
        hash route; ``request_id`` feeds the deterministic canary hash
        (one is assigned when absent). Returns a future."""
        m = self._zoo._get(model)  # unknown model refuses loudly
        cls = self.tenant_class(tenant)
        if priority is None:
            priority = cls["priority"]
        if deadline_ms is None:
            deadline_ms = cls["deadline_ms"]
        meta = {"model": model, "tenant": tenant or "default"}
        now = self._clock()
        with self._lock:
            rate = self._demand.get(model)
            if rate is None:
                rate = self._demand[model] = _DecayedRate(self._horizon)
            rate.observe(1, now)
            if request_id is None:
                self._rid += 1
                request_id = self._rid
            if self._draining or self._closed:
                self._shed("draining", model)
            if inject("zoo_cold"):
                # deterministic cold-path fault: this submit behaves as
                # if its model were non-resident and unpageable
                self._shed("zoo_cold", model)
            res = self._residents.get(model)
        if res is None:
            if version is not None:
                self._zoo.version(model, version)  # unknown refuses loudly
                if version != m.active:
                    raise MXNetError(
                        "ModelZoo: version %r of model %r is not live (a "
                        "page-in would serve the active version %r)"
                        % (version, model, m.active))
            return self._cold_submit(m, model, inputs, deadline_ms,
                                     priority, meta, now)
        arm = self._pick_arm(res, version, request_id)
        meta["version"] = arm.version
        return arm.batcher.submit(inputs, deadline_ms=deadline_ms,
                                  priority=priority, meta=meta)

    def _shed(self, reason, model):
        telemetry.inc("serving.shed", tag=reason)
        raise QueueFull("request shed: %s (model %r)" % (reason, model))

    def _pick_arm(self, res, version, request_id):
        """Stable vs canary: an explicit ``version=`` pins (refusing
        versions that are not live on an arm); otherwise the
        deterministic request-id hash sends ``canary_frac`` of traffic
        to the canary."""
        canary = res.canary
        if version is not None:
            if version == res.stable.version:
                return res.stable
            if canary is not None and version == canary.version:
                return canary
            live = [res.stable.version] + (
                [canary.version] if canary is not None else [])
            raise MXNetError(
                "ModelZoo: version %r of model %r is not live (live: %s)"
                % (version, res.model, ", ".join(live)))
        if canary is None or res.canary_frac <= 0.0:
            return res.stable
        h = zlib.crc32(str(request_id).encode("utf-8")) % 10**6
        return canary if h < res.canary_frac * 10**6 else res.stable

    def _cold_submit(self, m, model, inputs, deadline_ms, priority, meta,
                     now):
        if self.cold_policy == "shed":
            self._shed("zoo_cold", model)
        if not isinstance(inputs, (tuple, list)):
            inputs = (inputs,)
        n = int(getattr(inputs[0], "shape", (1,))[0] or 1)
        p = _Pending(inputs, n, deadline_ms, priority, meta, now)
        with self._lock:
            pend = self._pending.setdefault(model, collections.deque())
            if len(pend) >= self.pagein_queue:
                # the bounded page-in queue: a cold burst past the bound
                # sheds instead of building unserviceable backlog
                self._shed("zoo_cold", model)
            pend.append(p)
            start_pagein = model not in self._paging
            if start_pagein:
                self._paging.add(model)
        if start_pagein and self._threaded:
            threading.Thread(target=self._pagein_safe, args=(model,),
                             daemon=True,
                             name="mxtpu-zoo-pagein-%s" % model).start()
        # sync mode: the page-in runs at the next poll() — cold submits
        # accumulate in the bounded pending queue exactly like requests
        # arriving during a threaded page-in
        return p.future

    # ------------------------------------------------------------- placement
    def _site(self, model):
        return "%s.%s" % (_SITE_ROOT, model)

    def _dev_budget(self, dslot):
        if self.hbm_budget > 0:
            return self.hbm_budget
        return xprof.device_memory(self._devices[dslot])["bytes_limit"]

    def _slot_load_locked(self, dslot):
        models = [r for r in self._residents.values() if r.dslot == dslot]
        return len(models), sum(r.footprint for r in models)

    def _fits_locked(self, dslot, est_bytes):
        count, resident = self._slot_load_locked(dslot)
        if self.max_resident > 0 and count >= self.max_resident:
            return False
        budget = self._dev_budget(dslot)
        if budget and resident + est_bytes > budget:
            return False
        return True

    def _coldest_locked(self, dslot, now, incoming):
        """Lowest-demand resident on ``dslot`` — the eviction victim.
        A model with a live canary is pinned: evicting it would tear
        down the rollout mid-evaluation, so capacity pressure routes
        around it."""
        cands = [r for r in self._residents.values()
                 if r.dslot == dslot and r.model != incoming
                 and r.canary is None]
        if not cands:
            return None
        def rate(r):
            d = self._demand.get(r.model)
            return d.rate(now) if d is not None else 0.0
        return min(cands, key=lambda r: (rate(r), r.model))

    def _place(self, model):
        """Pick the pool slot for ``model``, evicting cold residents
        until it fits (HBM-currency: ledger-derived resident bytes vs
        the per-device budget, plus the count cap). When nothing CAN be
        evicted the least-loaded slot is used anyway — the co-residency
        preflight then warns ``memory.overcommit`` instead of this path
        deadlocking a page-in."""
        with self._lock:
            prev = self._residents.get(model)
            if prev is not None:
                return prev.dslot
            # a never-measured incoming model has no ledger footprint yet:
            # remember past measurements, else assume it is comparably
            # sized to the current residents (their mean) so the byte
            # budget still exerts pressure on first page-in
            est = self._footprints.get(model, 0)
            if not est and self._residents:
                est = sum(r.footprint for r in self._residents.values())
                est //= len(self._residents)
        while True:
            now = self._clock()
            with self._lock:
                slots = sorted(range(len(self._devices)),
                               key=lambda i: self._slot_load_locked(i))
                dslot = slots[0]
                if self._fits_locked(dslot, est):
                    return dslot
                victim = self._coldest_locked(dslot, now, model)
            if victim is None:
                return dslot
            self._evict(victim.model, "capacity")

    def co_resident_bytes(self, model, dslot):
        """Ledger-derived resident footprint of every OTHER zoo model on
        the same device — what the warmup preflight adds so
        ``memory.overcommit`` warns BEFORE a page-in OOMs (satellite:
        co-residency-aware preflight)."""
        with self._lock:
            return sum(r.footprint for r in self._residents.values()
                       if r.dslot == dslot and r.model != model)

    def _build_arm(self, model, version, dslot, site):
        """Build + disk-warm one arm: apply the version's params to the
        shared block, snapshot them into a fresh device-pinned Predictor,
        and resolve every bucket through ``compile_service.warmup`` —
        off a warm cache every entry is a disk hit and the arm's retrace
        site stays at ZERO compiles (the page-in gate)."""
        m = self._zoo._get(model)
        ver = self._zoo.version(model, version)
        from ..ops.registry import policy_key
        if ver.policy and tuple(policy_key()) != ver.policy:
            _log.warning(
                "zoo: model %s@%s registered under policy %s but serving "
                "under %s — executables will rebuild for the live policy",
                model, version, list(ver.policy), list(policy_key()))
        with self._lock:
            self._zoo.apply_version(model, version)
            pred = Predictor(
                m.block, m.spec, example=m.example, warmup=False,
                name="zoo:%s@%s" % (model, version),
                device=self._devices[dslot], site=site,
                co_resident=lambda: self.co_resident_bytes(model, dslot))
        summary = csvc.warmup(pred.warmup_entries())
        pred.finish_warmup()
        pred.param_version = version
        kw = dict(self._batcher_kw)
        kw.setdefault("max_batch_size", m.spec.max_batch)
        batcher = MicroBatcher(pred, clock=self._clock,
                               start=self._threaded, **kw)
        ctrl = None
        if self._use_controller:
            # plain-batcher controller: predictive admission + the
            # (per-tenant) goodput-attainment counters the canary gate
            # and placement read — there is no ReplicaSet to scale
            ctrl = ServingController(batcher, min_replicas=1,
                                     max_replicas=1)
        return _Arm(version, pred, batcher, ctrl), summary

    def _pagein_safe(self, model):
        try:
            self._pagein(model)
        except Exception as e:  # noqa: BLE001 — pending futures must fail
            _log.exception("zoo: page-in of %r failed", model)
            with self._lock:
                self._paging.discard(model)
                pend = self._pending.pop(model, ())
            err = MXNetError("zoo page-in of %r failed: %s: %s"
                             % (model, type(e).__name__, e))
            for p in pend:
                p.future._fail(err)

    def _pagein(self, model):
        """The disk-warm no-compile residency event: place (evicting as
        needed), build + warm the stable arm, record the ledger-derived
        footprint, then flush the bounded pending queue into the fresh
        batcher."""
        t0 = time.perf_counter()
        m = self._zoo._get(model)
        dslot = self._place(model)
        version = m.active
        arm, summary = self._build_arm(model, version, dslot,
                                       self._site(model))
        res = _Resident(model, dslot, self._devices[dslot], arm, summary)
        res.footprint = int(xprof.site_footprint(self._site(model),
                                                 family=True))
        with self._lock:
            self._footprints[model] = res.footprint
            self._residents[model] = res
            self._paging.discard(model)
            count = len(self._residents)
        telemetry.inc("zoo.pageins", tag=model)
        telemetry.observe("zoo.pagein_s", time.perf_counter() - t0)
        telemetry.gauge("zoo.resident_models", count)
        telemetry.gauge("zoo.hbm_resident_bytes", res.footprint, tag=model)
        telemetry.gauge("zoo.active_version",
                        self._zoo.version(model, version).ordinal,
                        tag=model)
        _log.info("zoo: paged in %s@%s on device %s (disk=%d built=%d, "
                  "footprint=%.1f MiB)", model, version, res.device,
                  summary.get("disk", 0), summary.get("built", 0),
                  res.footprint / 2**20)
        self._flush_pending(model, res)
        return res

    def _flush_pending(self, model, res):
        with self._lock:
            pend = self._pending.pop(model, None)
        if not pend:
            return
        now = self._clock()
        for p in pend:
            telemetry.observe("zoo.pagein_wait_s", max(0.0, now - p.t0))
            rem = None
            if p.deadline_ms is not None:
                rem = p.deadline_ms - (now - p.t0) * 1e3
                if rem <= 0:
                    # its deadline expired during the page-in: the same
                    # verdict it would get queued (the attainment signal
                    # sees the miss through the controller's expiry path)
                    telemetry.inc("serving.deadline_expired")
                    if res.stable.ctrl is not None:
                        res.stable.ctrl.note_expired(now, meta=p.meta)
                    p.future._fail(DeadlineExceeded(
                        "deadline passed during page-in of %r" % model))
                    continue
            try:
                inner = res.stable.batcher.submit(
                    p.inputs, deadline_ms=rem, priority=p.priority,
                    meta=p.meta)
            except (QueueFull, MXNetError) as e:
                p.future._fail(e)
            else:
                p.future._bind(inner)

    def _evict(self, model, reason):
        """Page a resident model out: its queued + in-flight futures
        complete FIRST (drain discipline — eviction never strands a
        request), then its params/executables are released
        (``compile_service.drop`` over the model's site family covers
        the canary arm too)."""
        with self._lock:
            res = self._residents.pop(model, None)
            if res is None:
                return 0
            count = len(self._residents)
        arms = [res.stable] + ([res.canary] if res.canary else [])
        for arm in arms:
            # close = drain (queued + in-flight complete) + worker stop;
            # new submits for this model already take the cold path
            arm.batcher.close(timeout=30.0)
        dropped = csvc.drop(site=self._site(model))
        telemetry.inc("zoo.evictions", tag="%s:%s" % (model, reason))
        telemetry.gauge("zoo.resident_models", count)
        telemetry.gauge("zoo.hbm_resident_bytes", 0, tag=model)
        _log.info("zoo: evicted %s (%s): %d executable entries released",
                  model, reason, dropped)
        return dropped

    def evict(self, model, reason="manual"):
        """Operational page-out (the bench's churn knob)."""
        return self._evict(model, reason)

    def ensure_resident(self, model):
        """Synchronous page-in (warm-up helper for benches/tests): the
        model is routable when this returns."""
        with self._lock:
            res = self._residents.get(model)
            if res is not None:
                return res
            self._paging.add(model)
        try:
            return self._pagein(model)
        finally:
            with self._lock:
                self._paging.discard(model)

    # --------------------------------------------------------------- rollout
    def deploy(self, model, version, canary_frac=1.0, parity_example=None,
               parity_tol=None):
        """See :meth:`ModelZoo.deploy`. Non-resident models just flip
        the registry's active version (the next page-in serves it)."""
        ver = self._zoo.version(model, version)
        telemetry.inc("zoo.deploys", tag=model)
        with self._lock:
            res = self._residents.get(model)
        if res is None:
            self._zoo.set_active(model, version)
            telemetry.gauge("zoo.active_version", ver.ordinal, tag=model)
            return {"model": model, "version": version, "mode": "staged"}
        if version == res.stable.version:
            return {"model": model, "version": version, "mode": "noop"}
        if canary_frac >= 1.0:
            self._swap_stable(res, version)
            return {"model": model, "version": version, "mode": "promoted"}
        if canary_frac <= 0.0:
            raise MXNetError("deploy: canary_frac must be in (0, 1] "
                             "(got %r)" % (canary_frac,))
        if res.canary is not None:
            raise MXNetError(
                "deploy: model %r already has canary %s@%s live — promote "
                "or roll it back first" % (model, model,
                                           res.canary.version))
        arm, _summary = self._build_arm(model, version, res.dslot,
                                        self._site(model) + ".canary")
        # the canary predictor snapshotted its params — restore the
        # shared registry block to the stable version so the block
        # always mirrors what the registry calls active
        self._zoo.apply_version(model, res.stable.version)
        if parity_example is not None:
            diff = self._parity_diff(res.stable.predictor, arm.predictor,
                                     parity_example)
            tol = (parity_tol if parity_tol is not None
                   else zoo_parity_tol_default())
            if diff > tol:
                arm.batcher.close(timeout=5.0)
                csvc.drop(site=arm.site)
                self._record_rollback(model, version, "parity",
                                      extra={"diff": diff, "tol": tol})
                return {"model": model, "version": version,
                        "mode": "rolled_back", "reason": "parity",
                        "diff": diff}
        with self._lock:
            res.canary = arm
            res.canary_frac = float(canary_frac)
        telemetry.gauge("zoo.canary_frac", canary_frac, tag=model)
        _log.info("zoo: canary %s@%s live at %.0f%% of traffic",
                  model, version, canary_frac * 100)
        return {"model": model, "version": version, "mode": "canary",
                "canary_frac": canary_frac}

    @staticmethod
    def _parity_diff(stable_pred, canary_pred, example):
        """Max absolute element difference between the two arms' outputs
        on the probe input — the deploy-time parity gate."""
        args = example if isinstance(example, (tuple, list)) else (example,)
        def run(pred):
            out = pred.predict(*args)
            outs = out if isinstance(out, tuple) else (out,)
            return [np.asarray(o.asnumpy()) for o in outs]
        a, b = run(stable_pred), run(canary_pred)
        return float(max(np.max(np.abs(x - y)) for x, y in zip(a, b)))

    def _swap_stable(self, res, version):
        """The promote path: the STABLE predictor adopts ``version``'s
        params through ``refresh_params`` — no recompile (params are
        traced arguments) and the int8 quantization-eligibility split
        stays pinned (``_quantize_params(sticky=...)``) across the
        versioned swap."""
        ver = self._zoo.version(res.model, version)
        with self._lock:
            self._zoo.apply_version(res.model, version)
            res.stable.predictor.refresh_params(version=version)
            res.stable.version = version
        self._zoo.set_active(res.model, version)
        telemetry.inc("zoo.promotes", tag=res.model)
        telemetry.gauge("zoo.active_version", ver.ordinal, tag=res.model)
        _log.info("zoo: %s now serving version %s (in-place param swap)",
                  res.model, version)

    def promote(self, model):
        """Promote the live canary: traffic stops routing to the arm,
        its queued + in-flight futures complete, the stable Predictor
        adopts the canary version via the sticky-int8 ``refresh_params``
        swap, and the arm's executables are released. Zero drops."""
        with self._lock:
            res = self._residents.get(model)
            if res is None or res.canary is None:
                raise MXNetError("promote: model %r has no live canary"
                                 % (model,))
            arm = res.canary
            res.canary_frac = 0.0   # stop routing BEFORE the drain
        arm.batcher.close(timeout=30.0)  # in-flight futures complete
        self._swap_stable(res, arm.version)
        with self._lock:
            res.canary = None
        csvc.drop(site=arm.site)
        telemetry.gauge("zoo.canary_frac", 0.0, tag=model)
        return {"model": model, "version": arm.version, "mode": "promoted"}

    def rollback(self, model, reason="manual"):
        """Roll the live canary back: traffic stops routing to it, its
        queued + in-flight futures complete on the canary weights (zero
        drops), the arm's executables are released, and the stable
        version keeps serving untouched."""
        with self._lock:
            res = self._residents.get(model)
            if res is None or res.canary is None:
                raise MXNetError("rollback: model %r has no live canary"
                                 % (model,))
            arm = res.canary
            res.canary_frac = 0.0
        arm.batcher.close(timeout=30.0)
        with self._lock:
            res.canary = None
        csvc.drop(site=arm.site)
        self._record_rollback(model, arm.version, reason)
        telemetry.gauge("zoo.canary_frac", 0.0, tag=model)
        return {"model": model, "version": arm.version,
                "mode": "rolled_back", "reason": reason}

    def _record_rollback(self, model, version, reason, extra=None):
        telemetry.inc("zoo.rollbacks", tag=reason)
        info = {"model": model, "version": version, "reason": reason}
        info.update(extra or {})
        telemetry.flight_record("canary_rollback", extra=info)
        _log.warning("zoo: canary %s@%s rolled back (%s)",
                     model, version, reason)

    # ------------------------------------------------------------ evaluation
    def tick(self, now=None):
        """One control pass: evaluate every live canary's auto-rollback
        gate (injected-fault check first, then the SLO-attainment
        floor). Driven by :meth:`poll` under a fake clock, by the
        monitor thread in threaded mode."""
        if now is None:
            now = self._clock()
        with self._lock:
            live = [(m, r) for m, r in self._residents.items()
                    if r.canary is not None]
        for model, res in live:
            arm = res.canary
            if arm is None:
                continue
            if inject("canary_rollback"):
                self.rollback(model, "injected")
                continue
            if arm.ctrl is None:
                continue
            att, weight = arm.ctrl.attainment(now)
            if weight >= zoo_canary_window_default() and att is not None \
                    and att < zoo_canary_floor_default():
                self.rollback(model, "slo")
                continue

    def poll(self):
        """Fake-clock driver: run any pending page-ins inline, one
        dispatch attempt per live arm batcher, then a canary-gate tick.
        Returns requests dispatched."""
        n = 0
        if not self._threaded:
            with self._lock:
                cold = [m for m in self._paging
                        if m not in self._residents]
            for model in cold:
                self._pagein_safe(model)
        with self._lock:
            residents = list(self._residents.values())
        for res in residents:
            n += res.stable.batcher.poll()
            if res.canary is not None:
                n += res.canary.batcher.poll()
        self.tick(self._clock())
        return n

    def _monitor_loop(self):
        while not self._stop.wait(0.05):
            if self._closed:
                return
            try:
                self.tick(self._clock())
            except Exception:  # noqa: BLE001 — gate errors must not kill
                _log.exception("zoo monitor tick failed")

    # ------------------------------------------------------------- reporting
    @property
    def queue_depth(self):
        with self._lock:
            residents = list(self._residents.values())
            pending = sum(p.n for dq in self._pending.values() for p in dq)
        depth = pending
        for res in residents:
            depth += res.stable.batcher.queue_depth
            if res.canary is not None:
                depth += res.canary.batcher.queue_depth
        return depth

    def input_templates(self, model):
        """Input templates of the model's resident stable arm (None
        while non-resident — the HTTP front then skips dtype coercion)."""
        with self._lock:
            res = self._residents.get(model)
        return res.stable.predictor.input_templates if res else None

    def view(self):
        """The /healthz zoo block: per-model residency, live versions,
        canary state, footprints, per-tenant attainment."""
        now = self._clock()
        with self._lock:
            residents = dict(self._residents)
            pending = {m: sum(p.n for p in dq)
                       for m, dq in self._pending.items() if dq}
            demand = {m: round(r.rate(now), 4)
                      for m, r in self._demand.items()}
        out = {"models": {}, "pending": pending, "demand": demand,
               "devices": len(self._devices),
               "resident_models": len(residents)}
        for model in self._zoo.models():
            res = residents.get(model)
            row = {"resident": res is not None,
                   "active_version": self._zoo.active_version(model),
                   "versions": self._zoo.versions(model)}
            if res is not None:
                row.update({
                    "device": str(res.device),
                    "resident_bytes": res.footprint,
                    "stable_version": res.stable.version,
                    "queue_depth": res.stable.batcher.queue_depth,
                    "warm_disk_hits": res.warm_summary.get("disk", 0),
                    "warm_compiles": res.warm_summary.get("built", 0)})
                if res.stable.ctrl is not None:
                    att, w = res.stable.ctrl.attainment(now)
                    row["attainment"] = round(att, 4) if att is not None \
                        else None
                    row["tenant_attainment"] = \
                        res.stable.ctrl.tenant_attainment(now)
                if res.canary is not None:
                    c = {"version": res.canary.version,
                         "frac": res.canary_frac,
                         "queue_depth": res.canary.batcher.queue_depth}
                    if res.canary.ctrl is not None:
                        att, w = res.canary.ctrl.attainment(now)
                        c["attainment"] = round(att, 4) \
                            if att is not None else None
                        c["verdict_weight"] = round(w, 2)
                    row["canary"] = c
            out["models"][model] = row
        return out

    # ----------------------------------------------------------- drain/close
    def drain(self, timeout=None):
        """Stop admitting (submits shed ``draining``), fail pending
        page-in waiters, finish everything queued + in flight on every
        arm. Returns True when empty — the ModelServer SIGTERM path."""
        with self._lock:
            self._draining = True
            pend = {m: list(dq) for m, dq in self._pending.items()}
            self._pending.clear()
            residents = list(self._residents.values())
        err = QueueFull("request shed: draining")
        for dq in pend.values():
            for p in dq:
                p.future._fail(err)
        ok = True
        for res in residents:
            ok = res.stable.batcher.drain(timeout=timeout) and ok
            if res.canary is not None:
                ok = res.canary.batcher.drain(timeout=timeout) and ok
        return ok

    def close(self, timeout=5.0):
        self.drain(timeout=timeout)
        with self._lock:
            self._closed = True
            residents = list(self._residents.values())
        self._stop.set()
        for res in residents:
            res.stable.batcher.close(timeout=timeout)
            if res.canary is not None:
                res.canary.batcher.close(timeout=timeout)
        if self._monitor is not None:
            self._monitor.join(timeout)
        return self
