"""Continuous-batching autoregressive decode: prefill/decode split + KV slots.

The Predictor/MicroBatcher stack (PR 5/8) serves single-shot inference:
one request, one padded forward, one answer. The LLM workload class is
different — a request is a PROMPT plus a loop of single-token steps, and
throughput comes from keeping a decode cohort full ACROSS steps, not from
padding one batch. The PyGraph capture/replay economics (PAPERS.md:
arXiv:2503.19779) say exactly how to build that on a jit stack: ONE
ahead-of-time decode executable per cohort bucket, replayed thousands of
times, with every per-step tensor living in-executable as donated carry
state so a step is pure replay. This module is that engine:

* **Prefill/decode split** — the prompt runs through the existing
  bucketed :class:`~mxtpu.serving.engine.Predictor` path (seq buckets,
  pad-up, device-side slice; compiles pinned at retrace site
  ``serving.prefill``), producing the prompt's KV cache and first token.
  Decode then runs the continuous-batching step loop below.
* **KV-cache slot manager** — a fixed-capacity cohort (``BucketSpec
  (decode_slots=...)``): each slot carries one sequence's KV cache,
  current token, position, and remaining-token budget as DONATED jit
  carry state. Finished sequences free their slot BETWEEN steps and
  queued prefilled sequences join the RUNNING cohort without a
  recompile: a slot insert is a device-side ``dynamic_update_slice``
  with a *traced* slot index, so slot identity never enters a cache key.
* **AOT bucket replay** — ``warmup()`` compiles one step executable per
  cohort capacity bucket and one insert executable per prefill seq
  bucket; after warmup, the ``serving.decode`` retrace site stays at
  that count by construction (watchdog-pinned), and each step runs at
  the smallest capacity bucket covering the live high-water slot.
* **Zero d2h in the decode loop** — the step dispatch runs under a
  d2h-armed ``serving.decode`` span (asserts zero syncs, exactly like
  ``serving.predict``); the one declared fetch per step (sampled tokens
  + done mask, two tiny vectors) happens outside it in the
  ``serving.fetch`` span.
* **KV residency accounting** — a :class:`KVCacheAccountant` tracks
  per-replica KV bytes by cohort bucket and gates admission: overload
  sheds by *KV residency* (``serving.shed{kv_residency}``), not just
  queue depth. The same accountant plugs into
  :class:`~mxtpu.serving.batcher.MicroBatcher` (``admission_gate=``) and
  :class:`~mxtpu.serving.replicas.ReplicaSet` (``attach_accountant``).
* **int8 path** — ``MXTPU_SERVE_INT8`` stores weights (Predictor) and
  the KV cache (here) as symmetric int8 + per-row scales through
  ``ops/quantization.py``, roughly halving resident bytes per replica —
  the accountant then admits ~2x the sequences at equal memory.

Model contract (:class:`DecodeModel`): a ``HybridBlock`` whose

* ``forward(tokens[b, s])`` returns ``(logits[b, s, V], *kv[b, s, ...])``
  — the PREFILL, served through the Predictor machinery unchanged;
* ``decode_step(kv, tok, pos)`` (jnp-level, traced under the same
  ``_run_traced`` machinery, parameters via ``self.<param>.data()``)
  takes the cohort's KV leaves ``[c, L, ...]`` *without* this step's
  token, the current tokens ``[c]`` and cache lengths ``[c]``, and
  returns ``(logits[c, V], new_entries)`` — the k/v rows this token
  appends, which the ENGINE persists at ``pos`` (and quantizes, in int8
  mode). The model never touches slot bookkeeping.

Failure semantics mirror PR 8: a decode step with no answer within
``MXTPU_SERVE_DISPATCH_TIMEOUT_MS`` trips the wedge watchdog — the stuck
sequences' futures fail loud, their trace_ids land in a
``flight_record("decode_wedge", ...)`` artifact, the cohort carry state
is re-allocated, and the engine keeps serving the queue. An injected
``decode_wedge`` fault drives the whole path sleep-free under a fake
clock.
"""
from __future__ import annotations

import collections
import logging
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import telemetry
from ..base import MXNetError
from ..ndarray import NDArray
from ..resilience import inject, maybe_oom
from .batcher import DeadlineExceeded, QueueFull, _Future
from .engine import _TRACE_LOCK, BucketSpec, Predictor, serve_int8_default
from .replicas import dispatch_timeout_ms_default

__all__ = ["DecodeModel", "DecodeEngine", "DecodeFuture", "KVCacheAccountant",
           "decode_slots_default", "decode_queue_default",
           "decode_max_new_default", "kv_overcommit_default"]

_log = logging.getLogger("mxtpu.serving")


# ------------------------------------------------------------------ policies
def decode_slots_default():
    """Decode-cohort capacity when no ``decode_spec`` is passed
    (``MXTPU_DECODE_SLOTS``, default 8): the engine declares
    ``BucketSpec.pow2(decode_slots=<this>)`` — capacity is also per-slot
    KV bytes x slots of resident HBM, so size it to the memory budget,
    not the offered load (the queue + accountant absorb bursts)."""
    return int(os.environ.get("MXTPU_DECODE_SLOTS", "8"))


def decode_queue_default():
    """Pending-sequence admission bound (``MXTPU_DECODE_QUEUE``, default
    256): submits beyond it shed (``QueueFull`` -> 503) instead of
    growing time-to-first-token without bound."""
    return int(os.environ.get("MXTPU_DECODE_QUEUE", "256"))


def decode_max_new_default():
    """Generation budget when a request names none
    (``MXTPU_DECODE_MAX_NEW``, default 32); generation always also stops
    at the engine's ``max_len`` cache bound and at ``eos_id``."""
    return int(os.environ.get("MXTPU_DECODE_MAX_NEW", "32"))


def kv_overcommit_default():
    """Admitted-sequence overcommit as a multiple of KV pool capacity
    (``MXTPU_SERVE_KV_OVERCOMMIT``, default 2.0): the accountant admits
    (live + queued) sequences up to overcommit x capacity slots — enough
    queue to keep slots full across completions, bounded enough that
    time-to-first-token stays finite under overload."""
    return float(os.environ.get("MXTPU_SERVE_KV_OVERCOMMIT", "2.0"))


class DecodeFuture(_Future):
    """A decode request's completion handle: ``result()`` returns the
    generated token ids (int32 numpy, eos included when hit). Carries the
    trace identity of the batcher futures plus ``ttft_s`` — the
    time-to-first-token the open-loop bench curves plot."""

    __slots__ = ("ttft_s",)

    def __init__(self):
        super().__init__()
        self.ttft_s = None


class _Sequence:
    __slots__ = ("prompt", "max_new", "deadline", "t_enq", "trace", "future",
                 "tokens", "slot")

    def __init__(self, prompt, max_new, deadline, t_enq, trace):
        self.prompt = prompt
        self.max_new = max_new
        self.deadline = deadline
        self.t_enq = t_enq
        self.trace = trace
        self.future = DecodeFuture()
        self.tokens = []
        self.slot = None


class DecodeModel:
    """Marker/contract mixin for autoregressive decode (see the module
    docstring). Concrete models subclass both ``gluon.HybridBlock`` and
    this, implement the prefill ``hybrid_forward`` returning
    ``(logits[b, s, V], *kv[b, s, ...])``, and implement
    :meth:`decode_step`. ``tools/serve_bench.py:build_decode_model`` is
    the executable reference implementation."""

    def decode_step(self, kv, tok, pos):
        """One decode step (jnp-level, traced): ``kv`` — list of cache
        leaves ``[c, L, ...]`` in compute dtype, WITHOUT this step's
        token; ``tok[c]`` int32 current tokens; ``pos[c]`` int32 cache
        lengths (this token's position). Returns ``(logits[c, V],
        entries)`` where ``entries`` is the per-leaf list of new k/v rows
        ``[c, ...]`` — the engine persists them at ``pos``."""
        raise NotImplementedError


# ----------------------------------------------------------- KV accounting
class KVCacheAccountant:
    """Per-replica KV residency ledger feeding admission control.

    Engines (or any KV-carrying server) :meth:`register` their pool —
    per-slot bytes x capacity slots, tagged per replica like the
    ``serving.predict.r<i>`` retrace sites. Admission then asks
    :meth:`would_admit`: a sequence is admitted while (live + queued)
    slots stay under ``overcommit`` x capacity; past that the submit
    sheds ``serving.shed{kv_residency}`` — the overload signal is *KV
    residency*, not queue depth, so a fleet dispatcher can route by how
    much cache memory a replica actually has left. Gauges:
    ``serving.kv_capacity_bytes`` / ``serving.kv_resident_bytes``
    (resident = live slots only; queued sequences hold no device bytes
    yet). ``snapshot()`` (surfaced by ``/healthz``) reports per-tag
    bytes plus the per-cohort-bucket byte ladder."""

    def __init__(self, capacity_bytes=None, overcommit=None):
        self._lock = threading.Lock()
        self._pools = {}
        self._capacity_bytes = capacity_bytes
        self._overcommit = float(overcommit if overcommit is not None
                                 else kv_overcommit_default())

    def register(self, tag, per_slot_bytes, slots, bucket_slots=()):
        """Declare (or re-declare) a replica's KV pool. ``bucket_slots``
        is the cohort capacity ladder, so the snapshot can report bytes
        by bucket."""
        with self._lock:
            cap = self._capacity_bytes
            if cap is None:
                cap = int(per_slot_bytes) * int(slots)
            self._pools[tag] = {
                "per_slot_bytes": int(per_slot_bytes),
                "slots": int(slots),
                "capacity_bytes": int(cap),
                "live": 0, "queued": 0,
                "bucket_bytes": {int(b): int(b) * int(per_slot_bytes)
                                 for b in bucket_slots},
            }
            self._gauges_locked()

    def _gauges_locked(self):
        telemetry.gauge("serving.kv_capacity_bytes",
                        sum(p["capacity_bytes"]
                            for p in self._pools.values()))
        telemetry.gauge("serving.kv_resident_bytes",
                        sum(p["live"] * p["per_slot_bytes"]
                            for p in self._pools.values()))

    def _pool(self, tag):
        p = self._pools.get(tag)
        if p is None:
            raise MXNetError("KVCacheAccountant: unregistered pool %r "
                             "(register() at engine warmup)" % (tag,))
        return p

    def would_admit(self, tag, n=1):
        """True while ``n`` more sequences fit the overcommit bound.
        Unregistered tags admit (a Predictor-only replica holds no KV)."""
        with self._lock:
            p = self._pools.get(tag)
            if p is None:
                return True
            have = p["live"] + p["queued"] + n
            return have * p["per_slot_bytes"] <= \
                p["capacity_bytes"] * self._overcommit

    def try_admit(self, tag, n=1):
        """Atomic check-and-admit: the overcommit test and the queued
        increment happen under ONE lock hold, so concurrent submits
        cannot all pass a stale check and overshoot the bound (the
        DecodeEngine's admission path). Unregistered tags admit.
        Returns True when admitted (the caller owes a matching
        occupy/unqueue), False to shed."""
        with self._lock:
            p = self._pools.get(tag)
            if p is None:
                return True
            have = p["live"] + p["queued"] + n
            if have * p["per_slot_bytes"] > \
                    p["capacity_bytes"] * self._overcommit:
                return False
            p["queued"] += n
            return True

    def unqueue(self, tag):
        """An admitted sequence left the queue without taking a slot
        (expired / shed / engine crash)."""
        with self._lock:
            p = self._pool(tag)
            p["queued"] = max(0, p["queued"] - 1)

    def occupy(self, tag):
        """A queued sequence took a KV slot (bytes now resident)."""
        with self._lock:
            p = self._pool(tag)
            p["queued"] = max(0, p["queued"] - 1)
            p["live"] += 1
            self._gauges_locked()

    def release(self, tag):
        """A live sequence finished; its slot's bytes are free again."""
        with self._lock:
            p = self._pool(tag)
            p["live"] = max(0, p["live"] - 1)
            self._gauges_locked()

    def resident_bytes(self, tag=None):
        """Live KV bytes for one tag (0 when unregistered) or all pools."""
        with self._lock:
            pools = [self._pools.get(tag)] if tag is not None \
                else list(self._pools.values())
            return sum(p["live"] * p["per_slot_bytes"] for p in pools
                       if p is not None)

    def pressure(self):
        """The fleet's KV-residency pressure as a 0..1+ fraction of the
        admission bound: max over pools of (live + queued) / (overcommit
        x capacity slots). The :class:`~mxtpu.serving.controller.
        ServingController` reads this as a scale-up signal — a cache
        near its residency bound sheds next, so capacity should grow
        BEFORE the ``kv_residency`` sheds start. 0.0 with no pools."""
        with self._lock:
            worst = 0.0
            for p in self._pools.values():
                bound = self._overcommit * p["slots"]
                if bound > 0:
                    worst = max(worst, (p["live"] + p["queued"]) / bound)
            return worst

    def gate(self, tag):
        """An ``admission_gate=`` callable for a
        :class:`~mxtpu.serving.batcher.MicroBatcher` guarding ``tag``'s
        pool: returns the shed reason ``kv_residency`` when the pool is
        over budget, None when admissible."""
        def _gate(_n_items):
            return None if self.would_admit(tag) else "kv_residency"
        return _gate

    def snapshot(self):
        """JSON-serializable per-tag view (``/healthz`` surfaces this)."""
        with self._lock:
            out = {}
            for tag, p in self._pools.items():
                out[tag] = {
                    "capacity_bytes": p["capacity_bytes"],
                    "per_slot_bytes": p["per_slot_bytes"],
                    "slots": p["slots"],
                    "live": p["live"],
                    "queued": p["queued"],
                    "resident_bytes": p["live"] * p["per_slot_bytes"],
                    "bucket_bytes": dict(p["bucket_bytes"]),
                }
            return out


def _bcast(mask, ndim):
    """Broadcast a [b] mask against a [b, ...] value."""
    return mask.reshape(mask.shape + (1,) * (ndim - 1))


def _quantize_rows(x):
    """Per-row symmetric int8 through the quantization op: range = max|x|
    over each row's trailing axes (degenerate rows quantize on a unit
    grid, so all-zero rows stay exactly zero). Returns ``(q int8, r f32
    [rows])`` — THE one KV grid rule, shared by the insert path and the
    step write-back so the two can never desynchronize."""
    from ..ops.registry import get_op
    qfn = get_op("quantize").fn
    xf = jnp.asarray(x, jnp.float32)
    r = jnp.max(jnp.abs(xf), axis=tuple(range(1, xf.ndim))) \
        if xf.ndim > 1 else jnp.abs(xf)
    r = jnp.where(r > 0, r, 1.0)
    q, _lo, _hi = qfn(xf, -_bcast(r, xf.ndim), _bcast(r, xf.ndim))
    return q, r


# ------------------------------------------------------------------- engine
class DecodeEngine:
    """The continuous-batching decode loop (see the module docstring).

    ``prefill_spec`` is an ordinary seq-bucketed :class:`BucketSpec`
    (prompts pad to their seq bucket through the Predictor);
    ``decode_spec`` is the ``decode_slots=`` spelling (cohort capacity
    buckets). ``start=True`` runs a background loop thread + wedge
    monitor; ``start=False`` (tests, fake clock) drives everything
    through :meth:`poll`. One engine owns one device's cohort — tag it
    per replica (``replica_tag``) so the shared
    :class:`KVCacheAccountant` ledgers match the ``serving.predict.r<i>``
    site family."""

    def __init__(self, model, prefill_spec, decode_spec=None, max_len=None,
                 eos_id=None, example=None, warmup=True, name="decode",
                 device=None, site="serving.decode",
                 prefill_site="serving.prefill", int8=None,
                 accountant=None, replica_tag="r0", max_queue=None,
                 max_new_default=None, dispatch_timeout_ms=None,
                 clock=time.monotonic, start=False, continuous=True):
        if not hasattr(model, "decode_step"):
            raise MXNetError(
                "DecodeEngine serves DecodeModel-family blocks (got %s): "
                "implement decode_step(kv, tok, pos) -> (logits, entries) "
                "— docs/serving.md" % type(model).__name__)
        if getattr(prefill_spec, "is_decode", False):
            raise MXNetError(
                "DecodeEngine prefill_spec is a decode-cohort spec %r — "
                "prompts need batch x seq buckets (the Predictor path); "
                "pass the capacity spec as decode_spec=" % (prefill_spec,))
        if prefill_spec.seq_lens is None:
            raise MXNetError(
                "DecodeEngine prefill_spec declares no seq_lens: prompts "
                "are variable-length and MUST be seq-bucketed (a prompt "
                "past the largest bucket is refused, docs/serving.md)")
        if decode_spec is None:
            decode_spec = BucketSpec.pow2(decode_slots=decode_slots_default())
        if not getattr(decode_spec, "is_decode", False):
            raise MXNetError(
                "DecodeEngine decode_spec must use the decode_slots= "
                "spelling (got %r): cohort buckets are SLOT capacities, "
                "not request batches" % (decode_spec,))
        self._model = model
        self._prefill_spec = prefill_spec
        self._decode_spec = decode_spec
        self._capacity = decode_spec.max_slots
        self._max_new_default = int(max_new_default
                                    if max_new_default is not None
                                    else decode_max_new_default())
        self._max_len = int(max_len if max_len is not None
                            else prefill_spec.seq_lens[-1]
                            + self._max_new_default)
        if self._max_len < prefill_spec.seq_lens[-1] + 1:
            raise MXNetError(
                "DecodeEngine max_len=%d leaves no room to decode past "
                "the largest prompt bucket (%d)"
                % (self._max_len, prefill_spec.seq_lens[-1]))
        self._eos = -1 if eos_id is None else int(eos_id)
        self._name = name
        self._site = site
        self._int8 = serve_int8_default() if int8 is None else bool(int8)
        self._acct = accountant
        self._tag = replica_tag
        self._max_queue = int(max_queue if max_queue is not None
                              else decode_queue_default())
        self._timeout_s = float(
            dispatch_timeout_ms if dispatch_timeout_ms is not None
            else dispatch_timeout_ms_default()) / 1e3
        self._clock = clock
        self._continuous = bool(continuous)
        if example is None:
            example = np.zeros((1, prefill_spec.seq_lens[0]), np.int32)
        self._pred = Predictor(model, prefill_spec, example=example,
                               warmup=False, name=name + ".prefill",
                               device=device, site=prefill_site,
                               int8=self._int8)
        self._jits = {}            # (kind, bucket, int8, policy) -> jitted
        self._kv_layout = None     # [(trailing_shape, dtype_str)] per leaf
        self._vocab = None
        self._carry = None
        self._carry_gen = 0        # bumped by every wedge reset: a step
        # dispatched against a superseded carry must not write back
        self._last_logits = None   # most recent step's logits (device; the
        # diagnostic parity hook — never fetched by the loop itself)
        self._cond = threading.Condition()
        self._pending = collections.deque()
        self._slots = [None] * self._capacity
        self._inflight_seq = None  # popped from _pending, not yet slotted
        # (mid-prefill): drain/close must not treat the engine as empty
        self._live = 0
        self._step_index = 0
        self._armed = None         # the in-flight step's watchdog entry
        self._prefill_armed = None  # the in-flight prefill/insert's entry
        self._cycles = 0           # loop/poll progress counter (probation)
        self._probation = None     # (deadline, cycles-at-trip) after a wedge
        self._closed = False
        self._draining = False
        self._crashed = False
        self._thread = None
        self._monitor = None
        self._stop = threading.Event()
        if warmup:
            self.warmup()
        if start:
            self.start()

    # ------------------------------------------------------------ properties
    @property
    def capacity(self):
        return self._capacity

    @property
    def int8(self):
        return self._int8

    @property
    def live_slots(self):
        with self._cond:
            return self._live

    @property
    def pending_count(self):
        with self._cond:
            return len(self._pending)

    @property
    def predictor(self):
        """The prefill Predictor (its compiles report at
        ``serving.prefill``)."""
        return self._pred

    @property
    def accountant(self):
        return self._acct

    def per_slot_kv_bytes(self):
        """Resident bytes one slot's KV cache costs (int8: quantized
        leaves + per-position scale rows) — what the accountant ledgers."""
        if self._kv_layout is None:
            raise MXNetError("per_slot_kv_bytes before warmup()")
        total = 0
        for trail, dt in self._kv_layout:
            n = self._max_len * int(np.prod(trail, dtype=np.int64) or 1)
            if self._int8:
                total += n * 1 + self._max_len * 4  # int8 rows + f32 scales
            else:
                total += n * jnp.dtype(dt).itemsize
        return total

    # ---------------------------------------------------------------- warmup
    def warmup(self):
        """Settle the prefill templates, derive the KV layout from one
        probe forward, AOT-compile every prefill bucket, every cohort
        step bucket, and every insert bucket, and allocate the cohort
        carry. After this, a compile at ``serving.decode`` is a served
        stall — the watchdog (and the serve_bench gate) pins the site at
        its post-warmup count. Idempotent."""
        if self._kv_layout is not None:
            return self
        flat, _fmt, _b = self._pred.predict_flat(
            (np.zeros((1, self._prefill_spec.seq_lens[0]), np.int32),))
        if len(flat) < 2:
            raise MXNetError(
                "DecodeModel forward must return (logits, *kv_leaves); "
                "got %d output(s) — the KV cache IS the decode state"
                % len(flat))
        logits = flat[0]
        if logits._data.ndim != 3:
            raise MXNetError(
                "DecodeModel prefill logits must be [batch, seq, vocab], "
                "got shape %s" % (tuple(logits._data.shape),))
        self._vocab = int(logits._data.shape[-1])
        layout = []
        for i, leaf in enumerate(flat[1:]):
            d = leaf._data
            if d.ndim < 2 or d.shape[1] != logits._data.shape[1]:
                raise MXNetError(
                    "DecodeModel kv leaf %d must be [batch, seq, ...] "
                    "(got shape %s)" % (i, tuple(d.shape)))
            layout.append((tuple(int(x) for x in d.shape[2:]),
                           str(d.dtype)))
        self._kv_layout = layout
        self._pred.warmup()
        self._carry = self._alloc_carry()
        # AOT: one step executable per cohort capacity bucket (replayed
        # on the all-inactive cohort — a no-op step), one insert
        # executable per prefill seq bucket (max_new=0 marks the warmed
        # slot done-at-insert, so warmup leaves no live slot behind).
        # First invocations trace the shared block (parameters bind
        # tracers): serialize across engines like the Predictor does.
        with _TRACE_LOCK:
            for b in self._decode_spec.decode_slots:
                step_args = (self._carry, self._pred._param_datas,
                             self._pred._param_ranges)
                self._carry, emitted = self._get_step_jit(
                    b, example_args=step_args)(*step_args)
                jax.block_until_ready(emitted[0])
            V = self._vocab
            for s in self._prefill_spec.seq_lens:
                seq_kv = [jnp.zeros((1, s) + trail, dt)
                          for trail, dt in layout]
                # the probe forward's ACTUAL logits dtype: a bf16 model
                # warmed against f32 zeros would hit the cached wrapper
                # but retrace inside jax on the first real insert — a
                # mid-serving compile stall invisible to record_retrace
                zl = jnp.zeros((1, s, V), logits._data.dtype)
                ins_args = (self._carry, seq_kv, zl,
                            np.int32(0), np.int32(1), np.int32(0))
                self._carry, out = self._get_insert_jit(
                    s, example_args=ins_args)(*ins_args)
                jax.block_until_ready(out)
        telemetry.gauge("serving.decode.buckets",
                        len(self._decode_spec.decode_slots)
                        + len(self._prefill_spec.seq_lens))
        if self._acct is not None:
            self._acct.register(self._tag, self.per_slot_kv_bytes(),
                                self._capacity,
                                bucket_slots=self._decode_spec.decode_slots)
        # will-it-fit pre-flight (mxtpu/xprof.py): Σ AOT step+insert
        # executable footprints vs the device HBM limit — warmup
        # succeeding bucket-by-bucket does not mean every bucket's
        # residents coexist; skipped (zero extra lowering) when the
        # backend exposes no limit (CPU tier)
        from .. import xprof
        xprof.ensure_memwatch()
        xprof.preflight(self._site)
        return self

    def _alloc_carry(self):
        C, L = self._capacity, self._max_len
        if self._int8:
            kv = [jnp.zeros((C, L) + trail, jnp.int8)
                  for trail, _dt in self._kv_layout]
            scales = [jnp.ones((C, L), jnp.float32)
                      for _ in self._kv_layout]
        else:
            kv = [jnp.zeros((C, L) + trail, dt)
                  for trail, dt in self._kv_layout]
            scales = None
        tok = jnp.zeros((C,), jnp.int32)
        pos = jnp.zeros((C,), jnp.int32)
        active = jnp.zeros((C,), jnp.bool_)
        rem = jnp.zeros((C,), jnp.int32)
        return (kv, scales, tok, pos, active, rem)

    # ------------------------------------------------------------- compiling
    def _build_jit(self, kind, bucket, build, donate=(0,),
                   example_args=None):
        """The one compile front door for the decode cache: every miss
        resolves through the compile service (LRU store, disk cache,
        centralized retrace reporting at this engine's site —
        ``serving.decode``; graftlint's JIT_ALLOWLIST declares the cache
        since the site name is per-instance), exactly like
        ``Predictor._get_jit`` — post-warmup the site count stays at
        #cohort-buckets + #insert-buckets by construction, and a
        warm-disk restart reaches it with ZERO compiles."""
        from .. import compile_service as csvc
        from ..ops.registry import policy_key
        pol = policy_key()
        key = (kind, bucket, self._int8, pol)
        hit = self._jits.get(key)
        if hit is not None:
            return hit
        ckey = csvc.canonical_key(
            site=self._site,
            fn_id="decode:%s:%s" % (type(self._model).__name__,
                                    csvc.source_token(type(self._model))),
            # the predictor's param structure joins the signature: two
            # models of the same class but different widths (same
            # kv_layout/vocab) must never alias a disk digest — a
            # shape-mismatched restore would crash, not degrade
            signature=(kind, bucket, self._int8, self._capacity,
                       self._max_len, self._eos,
                       tuple(self._kv_layout or ()), self._vocab,
                       tuple((tuple(d.shape), str(d.dtype))
                             for d in self._pred._param_datas)),
            policy=pol, donation=donate,
            device=csvc.device_token(device=self._pred.device),
            nonce=csvc.instance_nonce(self))
        entry = csvc.get_or_build(
            ckey, lambda: jax.jit(build(), donate_argnums=donate),
            provenance={"engine": self._name, "kind": kind,
                        "bucket": bucket, "int8": self._int8,
                        "capacity": self._capacity,
                        "max_len": self._max_len,
                        "policy_key": list(pol)},
            example_args=csvc.concrete_args(example_args)
            if example_args is not None else None)
        self._jits[key] = entry.fn
        return entry.fn

    def _kv_read(self, kv, scales, b):
        """The first ``b`` slots' caches in compute dtype (int8:
        dequantized through the quantization op, per-position scale rows
        broadcast against the trailing dims)."""
        if not self._int8:
            return [leaf[:b] for leaf in kv]
        from ..ops.registry import get_op
        deq = get_op("dequantize").fn
        out = []
        for (trail, dt), q, s in zip(self._kv_layout, kv, scales):
            rb = s[:b].reshape((b, self._max_len) + (1,) * len(trail))
            out.append(deq(q[:b], -rb, rb).astype(dt))
        return out

    def _kv_write_rows(self, kv, scales, entries, pos_b, act_b, b):
        """Persist this step's new k/v rows at (slot, pos) — inactive
        slots keep their old bytes (the model's row for them is
        garbage). int8: per-row symmetric quantization through the
        quantization op, scale rows ledgered next to the cache."""
        idx = jnp.arange(b)
        new_kv, new_scales = list(kv), None if scales is None \
            else list(scales)
        for i, entry in enumerate(entries):
            if self._int8:
                q, r = _quantize_rows(entry)
                old_q = new_kv[i][idx, pos_b]
                old_s = new_scales[i][idx, pos_b]
                q = jnp.where(_bcast(act_b, q.ndim), q, old_q)
                r = jnp.where(act_b, r, old_s)
                new_kv[i] = new_kv[i].at[idx, pos_b].set(q)
                new_scales[i] = new_scales[i].at[idx, pos_b].set(r)
            else:
                leaf = new_kv[i]
                old = leaf[idx, pos_b]
                row = jnp.where(_bcast(act_b, entry.ndim),
                                entry.astype(leaf.dtype), old)
                new_kv[i] = leaf.at[idx, pos_b].set(row)
        return new_kv, new_scales

    def _get_step_jit(self, b, example_args=None):
        model, pred = self._model, self._pred
        eos, max_len = self._eos, self._max_len
        engine = self

        def build():
            fixed_key = jax.random.PRNGKey(0)

            def pure(carry, param_datas, param_ranges):
                from ..gluon.block import _run_traced
                kv, scales, tok, pos, active, rem = carry
                pds = pred._traced_params(param_datas, param_ranges)
                act_b, tok_b, pos_b = active[:b], tok[:b], pos[:b]
                kv_b = engine._kv_read(kv, scales, b)

                def body():
                    return model.decode_step(kv_b, tok_b, pos_b)

                (logits, entries), _aux = _run_traced(
                    pred._params, pds, fixed_key, False, body)
                next_tok = jnp.argmax(
                    jnp.asarray(logits, jnp.float32), axis=-1).astype(
                        jnp.int32)
                next_tok = jnp.where(act_b, next_tok, tok_b)
                new_pos_b = jnp.where(act_b, pos_b + 1, pos_b)
                rem_b = jnp.where(act_b, rem[:b] - 1, rem[:b])
                done_b = act_b & ((next_tok == eos) | (rem_b <= 0)
                                  | (new_pos_b >= max_len))
                kv, scales = engine._kv_write_rows(kv, scales, entries,
                                                   pos_b, act_b, b)
                tok = tok.at[:b].set(next_tok)
                pos = pos.at[:b].set(new_pos_b)
                active = active.at[:b].set(act_b & ~done_b)
                rem = rem.at[:b].set(rem_b)
                return ((kv, scales, tok, pos, active, rem),
                        (next_tok, done_b, logits))

            return pure

        return self._build_jit("step", b, build,
                               example_args=example_args)

    def _get_insert_jit(self, s, example_args=None):
        """Slot insert for prefill seq bucket ``s``: a device-side
        ``dynamic_update_slice`` of the prompt's KV into a TRACED slot
        index — joining the running cohort never recompiles. Also samples
        the first token from the prefill logits at the prompt's true
        length (and marks the slot done-at-insert when that token already
        ends the sequence), so time-to-first-token needs no decode step."""
        eos, max_len = self._eos, self._max_len
        engine = self

        def build():
            def pure(carry, seq_kv, logits, slot, n, max_new):
                kv, scales, tok, pos, active, rem = carry
                first = jnp.argmax(jnp.asarray(logits[0, n - 1],
                                               jnp.float32)).astype(jnp.int32)
                done0 = (first == eos) | (max_new <= 1) | (n >= max_len)
                for i, leaf in enumerate(seq_kv):
                    row = leaf[0]                      # [s, *trail]
                    if engine._int8:
                        q, r = _quantize_rows(row)
                        kv[i] = lax.dynamic_update_slice(
                            kv[i], q[None],
                            (slot,) + (0,) * (kv[i].ndim - 1))
                        scales[i] = lax.dynamic_update_slice(
                            scales[i], r[None], (slot, 0))
                    else:
                        kv[i] = lax.dynamic_update_slice(
                            kv[i], row[None].astype(kv[i].dtype),
                            (slot,) + (0,) * (kv[i].ndim - 1))
                tok = tok.at[slot].set(first)
                pos = pos.at[slot].set(n)
                active = active.at[slot].set(~done0)
                rem = rem.at[slot].set(max_new - 1)
                out = jnp.stack([first, done0.astype(jnp.int32)])
                return (kv, scales, tok, pos, active, rem), out

            return pure

        return self._build_jit("insert", s, build,
                               example_args=example_args)

    def compile_stats(self):
        """The watchdog's view of this engine's decode-cache compiles."""
        return telemetry.retrace_stats(self._site)

    # ------------------------------------------------------------- admission
    def submit(self, prompt, max_new=None, deadline_ms=None):
        """Admit one prompt (1-d int token ids). Returns a
        :class:`DecodeFuture` whose ``result()`` is the generated int32
        token array; sheds :class:`QueueFull` past the queue bound or
        the accountant's KV-residency budget."""
        trace = telemetry.new_trace()
        t0 = time.perf_counter()
        with telemetry.trace_handoff(trace), \
                telemetry.span("serving.submit"):
            seq = self._admit(prompt, max_new, deadline_ms, trace)
        telemetry.add_stage(trace, "serving.submit",
                            time.perf_counter() - t0)
        return seq.future

    def _admit(self, prompt, max_new, deadline_ms, trace):
        if self._kv_layout is None:
            # refuse at admission like start() does: a cold engine would
            # otherwise crash opaquely inside the insert jit on a None
            # carry at first poll
            raise MXNetError("submit on a cold DecodeEngine: warmup() "
                             "first (AOT replay needs its executables "
                             "before traffic)")
        prompt = np.asarray(prompt)
        if prompt.ndim != 1 or prompt.size < 1:
            raise MXNetError("submit: prompt must be a non-empty 1-d "
                             "token-id array, got shape %s"
                             % (tuple(prompt.shape),))
        if not np.issubdtype(prompt.dtype, np.integer):
            raise MXNetError("submit: prompt dtype %s is not integer "
                             "token ids" % prompt.dtype)
        prompt = prompt.astype(np.int32)
        self._prefill_spec.seq_bucket(prompt.size)  # loud past-max refusal
        if prompt.size >= self._max_len:
            raise MXNetError(
                "submit: prompt of %d tokens leaves no room to decode "
                "within max_len=%d" % (prompt.size, self._max_len))
        max_new = int(max_new if max_new is not None
                      else self._max_new_default)
        if max_new < 1:
            raise MXNetError("submit: max_new must be >= 1, got %d"
                             % max_new)
        now = self._clock()
        deadline = None if deadline_ms is None else now + deadline_ms / 1e3
        seq = _Sequence(prompt, max_new, deadline, now, trace)
        if trace is not None:
            # the trace identity rides the future from ADMISSION, not
            # delivery: a sequence failed by the wedge watchdog must be
            # correlatable with its flight-recorder artifact
            seq.future.trace_id = trace.trace_id
        with self._cond:
            if self._crashed:
                self._shed("worker_crashed")
            if self._draining or self._closed:
                self._shed("draining")
            if len(self._pending) >= self._max_queue:
                self._shed("queue_full")
            if self._acct is not None:
                # atomic check-and-ledger BEFORE the append, under the
                # admission lock: the loop thread can pop (and
                # occupy/unqueue) the sequence the instant the lock
                # releases, and a separate check would let concurrent
                # submits overshoot the overcommit bound
                if not self._acct.try_admit(self._tag):
                    self._shed("kv_residency")
            self._pending.append(seq)
            telemetry.gauge("serving.queue_depth",
                            len(self._pending))
            self._cond.notify_all()
        telemetry.inc("serving.requests")
        return seq

    def _shed(self, reason):
        telemetry.inc("serving.shed", tag=reason)
        raise QueueFull("request shed: %s" % reason)

    # --------------------------------------------------------------- serving
    def poll(self):
        """One engine cycle NOW (wedge scan -> slot admission -> one
        decode step) — the fake-clock test hook and the no-thread drive.
        Returns the number of decode steps executed (0 or 1)."""
        try:
            maybe_oom()  # fault kind 'oom': the decode-loop OOM site
            self._scan_wedges(self._clock())
            self._admit_pending()
            steps = self._step_once()
        except Exception as e:
            # an HBM OOM leaves the artifact here too (the no-thread
            # drive has no crash barrier); the raise stays loud either way
            self._flight_if_oom(e)
            raise
        with self._cond:
            self._cycles += 1
        return steps

    def _flight_if_oom(self, exc):
        """Flight-record a device allocator failure with the KV-cache
        accountant's residency view attached — which cohort/bucket ate
        the headroom is readable post-mortem."""
        from .. import xprof
        if xprof.is_oom(exc):
            xprof.oom_flight(
                "serving.decode", exc,
                extra={"kv": self._acct.snapshot()
                       if self._acct is not None else None})

    def _free_slot_locked(self):
        for i, s in enumerate(self._slots):
            if s is None:
                return i
        return None

    def _admit_pending(self):
        """Move queued prompts into free slots: prefill through the
        bucketed Predictor, then the device-side slot insert — between
        steps, never mid-step, and never with a recompile (the insert
        jit's slot index is traced). The continuous-batching half of the
        throughput story: a restart-per-batch engine
        (``continuous=False``) only refills once the WHOLE cohort
        drained — the idle-slot steps it burns are exactly the tokens/s
        gap serve_bench's decode gate measures."""
        filling = False
        while True:
            with self._cond:
                if not self._pending:
                    return
                if not self._continuous and self._live > 0 and not filling:
                    # restart-per-batch: a draining cohort admits nobody —
                    # but once it fully drains, the whole next cohort
                    # fills in one pass (filling stays True for the rest
                    # of this call)
                    return
                filling = True
                slot = self._free_slot_locked()
                if slot is None:
                    return
                seq = self._pending.popleft()
                self._inflight_seq = seq
                telemetry.gauge("serving.queue_depth", len(self._pending))
            try:
                now = self._clock()
                if seq.deadline is not None and now > seq.deadline:
                    telemetry.inc("serving.deadline_expired")
                    if self._acct is not None:
                        self._acct.unqueue(self._tag)
                    self._fail(seq, DeadlineExceeded(
                        "deadline passed before a KV slot freed (queued "
                        "%.1f ms)" % ((now - seq.t_enq) * 1e3)))
                    continue
                telemetry.add_stage(seq.trace, "serving.queue_wait",
                                    max(0.0, now - seq.t_enq), event=True)
                try:
                    self._prefill_into(seq, slot)
                except Exception as e:  # noqa: BLE001 — complete, re-raise
                    # the popped sequence is in neither _pending nor
                    # _slots: without failing it HERE, the crash barrier
                    # would strand its future forever and leak its
                    # accountant queued count
                    if seq.slot is None and not seq.future.done():
                        if self._acct is not None:
                            self._acct.unqueue(self._tag)
                        self._fail(seq, MXNetError(
                            "prefill failed: %s: %s"
                            % (type(e).__name__, e)))
                    raise
            finally:
                with self._cond:
                    self._inflight_seq = None

    def _prefill_into(self, seq, slot):
        """Prefill one prompt and insert its KV into ``slot``. The
        ``serving.prefill`` stage covers the bucketed prompt forward AND
        the insert dispatch; the first token's fetch is the
        ``serving.fetch`` d2h that makes TTFT a delivered fact, not a
        device promise."""
        n = int(seq.prompt.size)
        s_bucket = self._prefill_spec.seq_bucket(n)
        # pad HOST-side to the seq bucket: prompts arrive as host numpy
        # with arbitrary raw lengths, and an eager device-side pad would
        # compile one anonymous jnp.pad executable per distinct length —
        # exactly the shape churn the bucket discipline exists to kill
        prompt = seq.prompt if n == s_bucket else np.pad(
            seq.prompt, (0, s_bucket - n),
            constant_values=self._prefill_spec.pad_value)
        # the prefill/insert dispatch is device work on the SAME possibly-
        # wedged device the step loop replays: bracket it with its own
        # watchdog entry, or a wedge here would hang the loop thread with
        # no detection at all (the step watchdog only covers steps)
        p_entry = {"seq": seq, "deadline": self._clock() + self._timeout_s,
                   "done": False, "abandoned": False}
        with self._cond:
            self._prefill_armed = p_entry
        try:
            with telemetry.trace_handoff(seq.trace):
                t0 = time.perf_counter()
                flat, _fmt, _b = self._pred.predict_flat((prompt[None, :],))
                # numpy scalars, NOT jnp — a jnp.int32() call is an eager
                # device op per argument, three per insert adds up
                out, gen, superseded = self._dispatch_carry(
                    self._get_insert_jit(s_bucket),
                    [leaf._data for leaf in flat[1:]], flat[0]._data,
                    np.int32(slot), np.int32(n), np.int32(seq.max_new))
                if superseded:
                    # a wedge reset landed mid-insert: this prompt's KV
                    # went into the superseded carry — a wedge casualty,
                    # failed loud like the cohort it would have joined
                    self._fail_wedge_casualty(seq)
                    return
                telemetry.add_stage(seq.trace, "serving.prefill",
                                    time.perf_counter() - t0)
                t0 = time.perf_counter()
                with telemetry.span("serving.fetch", cat="sync"):
                    first_done = NDArray(out).asnumpy()
                telemetry.add_stage(seq.trace, "serving.fetch",
                                    time.perf_counter() - t0)
        finally:
            with self._cond:
                p_entry["done"] = True
                if self._prefill_armed is p_entry:
                    self._prefill_armed = None
        if seq.future.done():
            # a teardown (wedge trip, crash barrier, close) settled this
            # sequence while the device answered late: delivering or
            # touching the ledger again would double-count
            return
        seq.tokens.append(int(first_done[0]))
        ttft = self._clock() - seq.t_enq
        seq.future.ttft_s = ttft
        telemetry.observe("serving.ttft_s", ttft)
        telemetry.inc("serving.decode.tokens")
        if int(first_done[1]):
            # done at insert (eos / max_new==1): the slot was marked
            # inactive in-executable; deliver without ever stepping
            if self._acct is not None:
                self._acct.unqueue(self._tag)
            self._deliver(seq)
            return
        with self._cond:
            if self._carry_gen != gen or self._closed or self._crashed \
                    or seq.future.done():
                # a reset/teardown landed AFTER the write-back but BEFORE
                # this registration — or the prefill watchdog already
                # failed this sequence: the fresh carry has
                # active[slot]=False (or the engine/future is gone), so
                # registering would park it forever or double-ledger it
                register = False
            else:
                register = True
                seq.slot = slot
                self._slots[slot] = seq
                self._live += 1
                telemetry.gauge("serving.decode.slots", self._live)
                if self._acct is not None:
                    # inside the lock: a reset landing right after
                    # registration must find the ledger already moved to
                    # live, so its straggler release balances exactly
                    self._acct.occupy(self._tag)
        if not register:
            self._fail_wedge_casualty(seq)
            return

    def _dispatch_carry(self, jitted, *args):
        """THE wedge-safe carry dispatch protocol (one copy, shared by
        the step and insert paths): snapshot carry + generation under the
        lock, dispatch OUTSIDE it — on a wedged tunnel even the dispatch
        can block (observed BENCH_r03-r05), and a blocked dispatch
        holding ``self._cond`` would deadlock every submit and the
        monitor's wedge scan, the exact moment it must run — then write
        the new carry back only if no wedge reset superseded the
        snapshot. Returns ``(emitted, gen, superseded)``; ``gen`` lets
        the caller re-check for resets landing after its own write-back
        (e.g. before slot registration)."""
        with self._cond:
            carry, gen = self._carry, self._carry_gen
        new_carry, out = jitted(carry, *args)
        with self._cond:
            superseded = self._carry_gen != gen
            if not superseded:
                self._carry = new_carry
        return out, gen, superseded

    def _step_once(self):
        """One decode step for the live cohort at its smallest covering
        capacity bucket: pure replay of the AOT executable (donated
        carry), zero d2h inside the armed ``serving.decode`` span; the
        one declared fetch (sampled tokens + done mask) follows in
        ``serving.fetch``; finished sequences free their slots before
        the next admission pass."""
        with self._cond:
            if self._live == 0:
                return 0
            prev = self._armed
            if prev is not None and not prev["done"] \
                    and not prev["abandoned"]:
                # a step is still in flight (a wedge in the making): a
                # new dispatch must NOT clobber its watchdog entry — the
                # unresolved entry would be discarded before it could
                # trip and the wedge would be swallowed silently
                return 0
            hi = max(i for i, s in enumerate(self._slots)
                     if s is not None) + 1
            b = self._decode_spec.slot_bucket(hi)
            live = [s for s in self._slots[:b] if s is not None]
            idx = self._step_index
            self._step_index += 1
            entry = {"live": live, "idx": idx, "done": False,
                     "abandoned": False,
                     "deadline": self._clock() + self._timeout_s}
            self._armed = entry
        lead = live[0]
        with telemetry.trace_handoff(lead.trace):
            t0 = time.perf_counter()
            wedged = inject("decode_wedge", idx)
            if not wedged:
                with telemetry.span("serving.decode", d2h=True):
                    emitted, _gen, _sup = self._dispatch_carry(
                        self._get_step_jit(b), self._pred._param_datas,
                        self._pred._param_ranges)
            dt = time.perf_counter() - t0
            for s in live:
                telemetry.add_stage(s.trace, "serving.decode", dt)
            if wedged:
                # simulated wedge: the device "never answers" — the entry
                # stays armed and the watchdog scan (monitor thread, or
                # the next poll under a fake clock) trips it
                return 1
            t0 = time.perf_counter()
            with telemetry.span("serving.fetch", cat="sync"):
                toks = NDArray(emitted[0]).asnumpy()
                done = NDArray(emitted[1]).asnumpy()
            dt = time.perf_counter() - t0
            for s in live:
                telemetry.add_stage(s.trace, "serving.fetch", dt)
        with self._cond:
            stale = entry["abandoned"]
            entry["done"] = True
            if self._armed is entry:
                self._armed = None
        if stale:
            # the wedge watchdog already failed this cohort and reset the
            # carry — a late answer must not resurrect freed slots, skew
            # the replay counter, or leave superseded-carry logits in the
            # diagnostic probe hook
            return 1
        self._last_logits = emitted[2]
        telemetry.inc("serving.decode.steps")
        self._harvest(live, toks, done)
        return 1

    def _harvest(self, live, toks, done):
        finished = []
        with self._cond:
            for seq in live:
                slot = seq.slot
                seq.tokens.append(int(toks[slot]))
                telemetry.inc("serving.decode.tokens")
                if done[slot]:
                    finished.append(seq)
                    self._slots[slot] = None
                    seq.slot = None
                    self._live -= 1
            telemetry.gauge("serving.decode.slots", self._live)
            if finished:
                self._cond.notify_all()
        for seq in finished:
            if self._acct is not None:
                self._acct.release(self._tag)
            self._deliver(seq)

    def _deliver(self, seq):
        done = self._clock()
        t0 = time.perf_counter()
        with telemetry.trace_handoff(seq.trace), \
                telemetry.span("serving.deliver"):
            seq.future._value = np.asarray(seq.tokens, np.int32)
        telemetry.add_stage(seq.trace, "serving.deliver",
                            time.perf_counter() - t0)
        if seq.trace is not None:
            seq.future.trace_id = seq.trace.trace_id
            seq.future.breakdown = telemetry.trace_breakdown(seq.trace)
            seq.future.e2e_s = done - seq.t_enq
        seq.future._event.set()
        telemetry.observe("serving.latency_s", done - seq.t_enq)

    @staticmethod
    def _fail(seq, error):
        seq.future._error = error
        seq.future._event.set()

    def _fail_wedge_casualty(self, seq):
        """Fail a mid-insert sequence whose carry was reset out from
        under it (one copy for the write-back and registration checks —
        the ledger call and the message must never diverge)."""
        if seq.future.done():
            return
        if self._acct is not None:
            self._acct.unqueue(self._tag)
        self._fail(seq, DeadlineExceeded(
            "cohort reset by the wedge watchdog during this prompt's "
            "slot insert"))

    def _collect_teardown_locked(self):
        """Under ``self._cond``: collect EVERY unfinished sequence —
        pending, slotted, and the popped-but-unregistered in-flight one
        — clear the slot table and the armed entry, and return
        ``(seqs, slotted_ids)``. One copy of the ledger-critical sweep
        shared by the crash barrier and close(): the release-vs-unqueue
        split and the slot-nulling must never diverge between them."""
        dead = list(self._pending) + [s for s in self._slots
                                      if s is not None]
        slotted = {id(s) for s in self._slots if s is not None}
        if self._inflight_seq is not None:
            dead.append(self._inflight_seq)
            self._inflight_seq = None
        self._pending.clear()
        for s in dead:
            # a later scan/harvest must never see a freed sequence as
            # still slotted (double-release, negative live count)
            s.slot = None
        self._slots = [None] * self._capacity
        self._live = 0
        if self._armed is not None:
            self._armed["abandoned"] = True
            self._armed = None
        if self._prefill_armed is not None:
            self._prefill_armed["abandoned"] = True
            self._prefill_armed = None
        # a late write-back / slot registration / done-at-insert from a
        # thread that resumes after this teardown must see the carry as
        # superseded — the sequences it would touch are failed HERE
        self._carry_gen += 1
        self._cond.notify_all()
        return dead, slotted

    def _fail_collected(self, dead, slotted, err):
        for seq in dead:
            if seq.future.done():
                continue  # e.g. the in-flight seq a racing path handled
            if self._acct is not None:
                if id(seq) in slotted:
                    self._acct.release(self._tag)
                else:
                    self._acct.unqueue(self._tag)
            self._fail(seq, err)

    # ------------------------------------------------------- wedge watchdog
    def _check_probation(self, now):
        """After a wedge trip in THREADED mode the loop thread may be
        genuinely blocked inside the wedged device call — the one thread
        that serves the queue. Probation gives it one full timeout window
        to make loop progress; no progress means blocked-forever, and
        shed-never-hang demands the crash barrier: fail the pending
        queue loud, refuse new submits. (An injected wedge's loop thread
        keeps cycling, so probation clears and serving resumes.)"""
        with self._cond:
            prob = self._probation
            if prob is None:
                return
            deadline, cycles0 = prob
            if self._cycles != cycles0:
                self._probation = None   # loop progressed: recovered
                return
            if now < deadline:
                return
            self._probation = None
        self._worker_crashed(RuntimeError(
            "decode loop made no progress for %.0f ms after a wedge "
            "trip — blocked inside the wedged device call"
            % (self._timeout_s * 1e3)))

    @staticmethod
    def _entry_due(entry, now):
        return entry is not None and not entry["done"] \
            and not entry["abandoned"] and now >= entry["deadline"]

    def _scan_wedges(self, now):
        """A dispatch with no answer past the timeout is a wedged device:
        a STEP wedge kills its slot cohort, a PREFILL/insert wedge kills
        the in-flight prompt (and, since the same device carries the
        cohort, everything slotted falls to the straggler sweep below).
        Either way the stuck sequences fail LOUD (their futures raise,
        their trace_ids land in the ``decode_wedge`` flight artifact) and
        the carry re-allocates — the device state that never answered is
        unrecoverable, the queue is not."""
        self._check_probation(now)
        with self._cond:
            entry = self._armed
            if self._entry_due(entry, now):
                entry["abandoned"] = True
                self._armed = None
                kind, idx = "step", entry["idx"]
                stuck = list(entry["live"])    # slotted: acct release
                queued_stuck = []
            else:
                entry = self._prefill_armed
                if not self._entry_due(entry, now):
                    return
                entry["abandoned"] = True
                self._prefill_armed = None
                kind, idx = "prefill", -1
                stuck = []
                queued_stuck = [entry["seq"]]  # never slotted: unqueue
                # settle the casualty ATOMICALLY with the abandonment: a
                # late-completing prefill on the loop thread checks
                # future.done() under this same lock, so the ledger
                # moves exactly once (failing it after the flight IO
                # below would leave a window to register/deliver AND be
                # unqueued — a double decrement)
                seq = entry["seq"]
                if not seq.future.done():
                    if self._acct is not None:
                        self._acct.unqueue(self._tag)
                    self._fail(seq, DeadlineExceeded(
                        "decode prefill dispatch wedged: no device "
                        "answer within %.0f ms" % (self._timeout_s * 1e3)))
            for seq in stuck:
                if seq.slot is not None:
                    self._slots[seq.slot] = None
                    seq.slot = None
                    self._live -= 1
            telemetry.gauge("serving.decode.slots", self._live)
        telemetry.inc("serving.decode.wedges")
        _log.warning(
            "serving: decode %s dispatch %d wedged (no answer in %.0f ms)"
            " — failing %d stuck sequence(s), resetting the cohort carry",
            kind, idx, self._timeout_s * 1e3,
            len(stuck) + len(queued_stuck))
        telemetry.flight_record(
            "decode_wedge",
            trace_ids=[s.trace.trace_id for s in stuck + queued_stuck
                       if s.trace is not None],
            extra={"kind": kind, "step": idx, "engine": self._name,
                   "stuck": len(stuck) + len(queued_stuck),
                   "timeout_ms": self._timeout_s * 1e3})
        err = DeadlineExceeded(
            "decode %s dispatch wedged: no device answer within %.0f ms"
            % (kind, self._timeout_s * 1e3))
        for seq in stuck:
            telemetry.trace_mark(seq.trace, "serving.wedged")
            if self._acct is not None:
                self._acct.release(self._tag)
            self._fail(seq, err)
        for seq in queued_stuck:
            telemetry.trace_mark(seq.trace, "serving.wedged")
            if not seq.future.done():
                if self._acct is not None:
                    self._acct.unqueue(self._tag)
                self._fail(seq, err)
        with self._cond:
            # the reset kills the WHOLE cohort device state: any live
            # slot not in the armed entry (none under the single-driver
            # model, but defensive) loses its KV too — fail it rather
            # than leave it silently pointing at zeroed cache
            stragglers = [s for s in self._slots if s is not None]
            self._slots = [None] * self._capacity
            self._live = 0
            telemetry.gauge("serving.decode.slots", 0)
            self._carry = self._alloc_carry()
            self._carry_gen += 1
            if self._thread is not None and self._thread.is_alive():
                # threaded mode: the loop thread may be BLOCKED in the
                # wedged device call — give it one timeout window to
                # prove otherwise (see _check_probation)
                self._probation = (now + self._timeout_s, self._cycles)
            self._cond.notify_all()
        for seq in stragglers:
            if self._acct is not None:
                self._acct.release(self._tag)
            self._fail(seq, err)

    # ---------------------------------------------------------------- worker
    def start(self):
        """Run the engine on a background loop thread + wedge monitor
        (the threaded twin of :meth:`poll`). Returns self."""
        if self._thread is not None:
            return self
        if self._kv_layout is None:
            raise MXNetError("DecodeEngine.start on a cold engine: "
                             "warmup() first (AOT replay needs its "
                             "executables before traffic)")
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="mxtpu-serving-decode")
        self._thread.start()
        interval = max(0.005, min(0.25, self._timeout_s / 4))
        self._monitor = threading.Thread(
            target=self._monitor_loop, args=(interval,), daemon=True,
            name="mxtpu-serving-decode-monitor")
        self._monitor.start()
        return self

    def _loop(self):
        try:
            while True:
                with self._cond:
                    while not self._pending and self._live == 0 \
                            and not self._closed:
                        self._cond.wait(0.25)
                    if self._closed and not self._pending \
                            and self._live == 0:
                        return
                self._admit_pending()
                maybe_oom()  # fault kind 'oom': the decode-loop OOM site
                stepped = self._step_once()
                with self._cond:
                    # loop-progress heartbeat: what probation watches to
                    # tell a cycling thread from one blocked in a wedged
                    # device call
                    self._cycles += 1
                    if not stepped and self._live > 0:
                        # live cohort but no step ran (unresolved armed
                        # entry): park briefly instead of spinning until
                        # the watchdog resolves it
                        self._cond.wait(0.005)
        except Exception as e:  # noqa: BLE001 — crash barrier (PR-8)
            # HBM exhaustion in the decode loop: artifact (ledger +
            # per-device memory stats + accountant view) first, then the
            # crash barrier fails every pending future LOUD (no hangs)
            self._flight_if_oom(e)
            self._worker_crashed(e)

    def _monitor_loop(self, interval):
        while not self._stop.is_set():
            self._scan_wedges(self._clock())
            with self._cond:
                if self._closed and not self._pending and self._live == 0:
                    return
            self._stop.wait(interval)

    def _worker_crashed(self, exc):
        """The decode loop died on an unexpected exception: fail every
        pending and live future loud (their worker is gone) and refuse
        new submits — the MicroBatcher crash-barrier discipline."""
        telemetry.inc("serving.worker_crashes")
        _log.error("serving decode loop crashed (%s: %s) — failing queued "
                   "futures and refusing new submits",
                   type(exc).__name__, exc)
        err = MXNetError("serving decode loop crashed: %s: %s"
                         % (type(exc).__name__, exc))
        with self._cond:
            self._crashed = True
            dead, slotted = self._collect_teardown_locked()
        telemetry.flight_record(
            "worker_crash",
            trace_ids=[s.trace.trace_id for s in dead
                       if s.trace is not None],
            extra={"engine": self._name,
                   "error": "%s: %s" % (type(exc).__name__, exc)})
        self._fail_collected(dead, slotted, err)

    def drain(self, timeout=None):
        """Stop admitting (submits shed ``draining``), finish pending +
        live sequences. With no loop thread, outstanding work drains
        synchronously through :meth:`poll` (deadline measured on the
        injected clock). Returns True when empty."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()
        deadline = None if timeout is None else self._clock() + timeout
        while True:
            alive = self._thread is not None and self._thread.is_alive()
            if not alive:
                while self.poll():
                    pass
                self._admit_pending()
            with self._cond:
                if not self._pending and self._live == 0 \
                        and self._inflight_seq is None:
                    return True
                if deadline is not None and self._clock() > deadline:
                    return False
                if not alive:
                    return False
                self._cond.wait(0.05)

    def close(self, timeout=5.0):
        """Drain, then stop the loop + monitor threads. Anything still
        pending after the drain deadline fails loud rather than hanging
        its callers."""
        self.drain(timeout=timeout)
        with self._cond:
            self._closed = True
            self._draining = True
            self._cond.notify_all()
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
        if self._monitor is not None:
            self._monitor.join(timeout)
        # sweep AFTER the joins: only then can no loop iteration race the
        # collection, and a popped-but-unregistered in-flight sequence (a
        # loop thread killed mid-prefill) is caught too instead of
        # leaving its future hanging forever
        with self._cond:
            leftovers, slotted = self._collect_teardown_locked()
        self._fail_collected(leftovers, slotted,
                             DeadlineExceeded("engine closed before "
                                              "completion"))
        return self

    # ------------------------------------------------------------ diagnostics
    def prefill_logits(self, prompt):
        """Diagnostic: the prompt's last-position logits as numpy — the
        int8-vs-f32 logits-parity gate's probe (serve_bench decode mode,
        tests). NOT a serving path: it fetches device output directly."""
        prompt = np.asarray(prompt, np.int32)
        flat, _fmt, _b = self._pred.predict_flat((prompt[None, :],))
        return np.asarray(flat[0]._data[0, prompt.size - 1])

    def step_logits_probe(self, prompt):
        """Diagnostic: prefill + insert into slot of a FRESH probe engine
        state, run one decode step, and return that step's logits row —
        the KV-path half of the int8 parity gate. Uses the engine's real
        executables (the loop's own ``_last_logits`` output, which the
        serving path never fetches), so the probe measures exactly what
        production replays. Serialized against the loop: do not call
        under live traffic."""
        fut = self.submit(prompt, max_new=2)
        for _ in range(64):
            if fut.done():
                break
            self.poll()
        if self._last_logits is None:
            raise MXNetError("step_logits_probe: no decode step ran "
                             "(prompt finished at insert?)")
        out = np.asarray(self._last_logits[0])
        fut.result(timeout=5.0)
        return out
