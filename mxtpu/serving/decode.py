"""Continuous-batching autoregressive decode: prefill/decode split + KV slots.

The Predictor/MicroBatcher stack (PR 5/8) serves single-shot inference:
one request, one padded forward, one answer. The LLM workload class is
different — a request is a PROMPT plus a loop of single-token steps, and
throughput comes from keeping a decode cohort full ACROSS steps, not from
padding one batch. The PyGraph capture/replay economics (PAPERS.md:
arXiv:2503.19779) say exactly how to build that on a jit stack: ONE
ahead-of-time decode executable per cohort bucket, replayed thousands of
times, with every per-step tensor living in-executable as donated carry
state so a step is pure replay. This module is that engine:

* **Prefill/decode split** — the prompt runs through the existing
  bucketed :class:`~mxtpu.serving.engine.Predictor` path (seq buckets,
  pad-up, device-side slice; compiles pinned at retrace site
  ``serving.prefill``), producing the prompt's KV cache and first token.
  Decode then runs the continuous-batching step loop below.
* **KV-cache slot manager** — a fixed-capacity cohort (``BucketSpec
  (decode_slots=...)``): each slot carries one sequence's KV cache,
  current token, position, and remaining-token budget as DONATED jit
  carry state. Finished sequences free their slot BETWEEN steps and
  queued prefilled sequences join the RUNNING cohort without a
  recompile: a slot insert is a device-side ``dynamic_update_slice``
  with a *traced* slot index, so slot identity never enters a cache key.
* **AOT bucket replay** — ``warmup()`` compiles one step executable per
  cohort capacity bucket and one insert executable per prefill seq
  bucket; after warmup, the ``serving.decode`` retrace site stays at
  that count by construction (watchdog-pinned), and each step runs at
  the smallest capacity bucket covering the live high-water slot.
* **Zero d2h in the decode loop** — the step dispatch runs under a
  d2h-armed ``serving.decode`` span (asserts zero syncs, exactly like
  ``serving.predict``); the one declared fetch per step (sampled tokens
  + done mask, two tiny vectors) happens outside it in the
  ``serving.fetch`` span.
* **KV residency accounting** — a :class:`KVCacheAccountant` tracks
  per-replica KV bytes by cohort bucket and gates admission: overload
  sheds by *KV residency* (``serving.shed{kv_residency}``), not just
  queue depth. The same accountant plugs into
  :class:`~mxtpu.serving.batcher.MicroBatcher` (``admission_gate=``) and
  :class:`~mxtpu.serving.replicas.ReplicaSet` (``attach_accountant``).
* **int8 path** — ``MXTPU_SERVE_INT8`` stores weights (Predictor) and
  the KV cache (here) as symmetric int8 + per-row scales through
  ``ops/quantization.py``, roughly halving resident bytes per replica —
  the accountant then admits ~2x the sequences at equal memory.

Model contract (:class:`DecodeModel`): a ``HybridBlock`` whose

* ``forward(tokens[b, s])`` returns ``(logits[b, s, V], *kv[b, s, ...])``
  — the PREFILL, served through the Predictor machinery unchanged;
* ``decode_step(kv, tok, pos)`` (jnp-level, traced under the same
  ``_run_traced`` machinery, parameters via ``self.<param>.data()``)
  takes the cohort's KV leaves ``[c, L, ...]`` *without* this step's
  token, the current tokens ``[c]`` and cache lengths ``[c]``, and
  returns ``(logits[c, V], new_entries)`` — the k/v rows this token
  appends, which the ENGINE persists at ``pos`` (and quantizes, in int8
  mode). The model never touches slot bookkeeping.

Failure semantics mirror PR 8: a decode step with no answer within
``MXTPU_SERVE_DISPATCH_TIMEOUT_MS`` trips the wedge watchdog — the stuck
sequences' futures fail loud, their trace_ids land in a
``flight_record("decode_wedge", ...)`` artifact, the cohort carry state
is re-allocated, and the engine keeps serving the queue. An injected
``decode_wedge`` fault drives the whole path sleep-free under a fake
clock.
"""
from __future__ import annotations

import collections
import logging
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import telemetry
from ..base import MXNetError
from ..ndarray import NDArray
from ..resilience import inject, maybe_oom
from .batcher import DeadlineExceeded, QueueFull, _Future
from .engine import _TRACE_LOCK, BucketSpec, Predictor, serve_int8_default
from .replicas import dispatch_timeout_ms_default

__all__ = ["DecodeModel", "DecodeEngine", "DecodeFuture", "KVCacheAccountant",
           "decode_slots_default", "decode_queue_default",
           "decode_max_new_default", "kv_overcommit_default",
           "kv_page_tokens_default", "prefix_cache_default",
           "spec_decode_k_default"]

_log = logging.getLogger("mxtpu.serving")


# ------------------------------------------------------------------ policies
def decode_slots_default():
    """Decode-cohort capacity when no ``decode_spec`` is passed
    (``MXTPU_DECODE_SLOTS``, default 8): the engine declares
    ``BucketSpec.pow2(decode_slots=<this>)`` — capacity is also per-slot
    KV bytes x slots of resident HBM, so size it to the memory budget,
    not the offered load (the queue + accountant absorb bursts)."""
    return int(os.environ.get("MXTPU_DECODE_SLOTS", "8"))


def decode_queue_default():
    """Pending-sequence admission bound (``MXTPU_DECODE_QUEUE``, default
    256): submits beyond it shed (``QueueFull`` -> 503) instead of
    growing time-to-first-token without bound."""
    return int(os.environ.get("MXTPU_DECODE_QUEUE", "256"))


def decode_max_new_default():
    """Generation budget when a request names none
    (``MXTPU_DECODE_MAX_NEW``, default 32); generation always also stops
    at the engine's ``max_len`` cache bound and at ``eos_id``."""
    return int(os.environ.get("MXTPU_DECODE_MAX_NEW", "32"))


def kv_overcommit_default():
    """Admitted-sequence overcommit as a multiple of KV pool capacity
    (``MXTPU_SERVE_KV_OVERCOMMIT``, default 2.0): the accountant admits
    (live + queued) sequences up to overcommit x capacity slots — enough
    queue to keep slots full across completions, bounded enough that
    time-to-first-token stays finite under overload."""
    return float(os.environ.get("MXTPU_SERVE_KV_OVERCOMMIT", "2.0"))


def kv_page_tokens_default():
    """KV page size in tokens (``MXTPU_KV_PAGE_TOKENS``, default 0 =
    rowed worst-case slots, the PR 11 layout). A power-of-two > 0 turns
    on PAGED KV: slots carry page tables instead of ``max_len`` rows, so
    HBM residency tracks actual tokens and finished sequences return
    their pages to the pool between steps — the accountant then admits
    by real free-page headroom instead of pessimistic rows."""
    return int(os.environ.get("MXTPU_KV_PAGE_TOKENS", "0"))


def prefix_cache_default():
    """Prefix caching on paged KV (``MXTPU_PREFIX_CACHE``, default off):
    full prompt-aligned pages are registered under a rolling token-chunk
    hash and SHARED (refcounted, read-only) across prompts with the same
    prefix — a templated-prompt cohort stores each system prompt once
    and prefill skips straight to the first novel token."""
    return os.environ.get("MXTPU_PREFIX_CACHE", "0") \
        not in ("0", "", "false", "False")


def spec_decode_k_default():
    """Speculative-decoding draft length (``MXTPU_SPEC_DECODE_K``,
    default 0 = off): a draft model proposes k greedy tokens per step
    and the target executable verifies them in ONE batched pass with
    longest-accepted-prefix commit — tokens/step rises above 1 at
    identical target math (greedy streams are bit-identical with and
    without speculation)."""
    return int(os.environ.get("MXTPU_SPEC_DECODE_K", "0"))


class DecodeFuture(_Future):
    """A decode request's completion handle: ``result()`` returns the
    generated token ids (int32 numpy, eos included when hit). Carries the
    trace identity of the batcher futures plus ``ttft_s`` — the
    time-to-first-token the open-loop bench curves plot."""

    __slots__ = ("ttft_s",)

    def __init__(self):
        super().__init__()
        self.ttft_s = None


class _Sequence:
    __slots__ = ("prompt", "max_new", "deadline", "t_enq", "trace", "future",
                 "tokens", "slot", "pages", "reserved", "pos")

    def __init__(self, prompt, max_new, deadline, t_enq, trace):
        self.prompt = prompt
        self.max_new = max_new
        self.deadline = deadline
        self.t_enq = t_enq
        self.trace = trace
        self.future = DecodeFuture()
        self.tokens = []
        self.slot = None
        self.pages = []     # paged mode: mapped page ids, chunk order
        self.reserved = 0   # paged mode: accountant pages still queued
        self.pos = 0        # paged mode: host mirror of the device pos


class DecodeModel:
    """Marker/contract mixin for autoregressive decode (see the module
    docstring). Concrete models subclass both ``gluon.HybridBlock`` and
    this, implement the prefill ``hybrid_forward`` returning
    ``(logits[b, s, V], *kv[b, s, ...])``, and implement
    :meth:`decode_step`. ``tools/serve_bench.py:build_decode_model`` is
    the executable reference implementation."""

    def decode_step(self, kv, tok, pos):
        """One decode step (jnp-level, traced): ``kv`` — list of cache
        leaves ``[c, L, ...]`` in compute dtype, WITHOUT this step's
        token; ``tok[c]`` int32 current tokens; ``pos[c]`` int32 cache
        lengths (this token's position). Returns ``(logits[c, V],
        entries)`` where ``entries`` is the per-leaf list of new k/v rows
        ``[c, ...]`` — the engine persists them at ``pos``."""
        raise NotImplementedError

    def decode_chunk(self, kv, toks, pos):
        """OPTIONAL: score ``t`` chained tokens in ONE forward (jnp-level,
        traced) — the speculative-verification fast path. ``toks[c, t]``
        are the pending token followed by t-1 draft proposals; the
        position of ``toks[:, j]`` is ``pos + j``. Attention for query j
        spans the cache (rows ``< pos``) plus the chunk's own rows
        ``<= j`` (causal within the chunk) — the chunk rows are NOT in
        ``kv``. Returns ``(logits[c, t, V], entries)`` with per-leaf new
        rows ``[c, t, ...]``; the engine persists/discards them by its
        commit rule. Rows whose position overflows ``L`` may be garbage —
        the engine masks them. Models that do not implement this verify
        through ``decode_step`` chained t times (bit-identical, slower);
        int8 engines always chain so within-chunk reads see the same
        quantize->dequantize grid as step-at-a-time decode."""
        raise NotImplementedError


# ----------------------------------------------------------- KV accounting
class KVCacheAccountant:
    """Per-replica KV residency ledger feeding admission control.

    Engines (or any KV-carrying server) :meth:`register` their pool —
    per-slot bytes x capacity slots, tagged per replica like the
    ``serving.predict.r<i>`` retrace sites. Admission then asks
    :meth:`would_admit`: a sequence is admitted while (live + queued)
    slots stay under ``overcommit`` x capacity; past that the submit
    sheds ``serving.shed{kv_residency}`` — the overload signal is *KV
    residency*, not queue depth, so a fleet dispatcher can route by how
    much cache memory a replica actually has left. Gauges:
    ``serving.kv_capacity_bytes`` / ``serving.kv_resident_bytes``
    (resident = live slots only; queued sequences hold no device bytes
    yet). ``snapshot()`` (surfaced by ``/healthz``) reports per-tag
    bytes plus the per-cohort-bucket byte ladder."""

    def __init__(self, capacity_bytes=None, overcommit=None):
        self._lock = threading.Lock()
        self._pools = {}
        self._capacity_bytes = capacity_bytes
        self._overcommit = float(overcommit if overcommit is not None
                                 else kv_overcommit_default())

    def register(self, tag, per_slot_bytes, slots, bucket_slots=(),
                 page_tokens=0):
        """Declare (or re-declare) a replica's KV pool. ``bucket_slots``
        is the cohort capacity ladder, so the snapshot can report bytes
        by bucket. A PAGED engine registers its page pool here instead:
        ``per_slot_bytes`` is one page's bytes, ``slots`` the pool's page
        count, and ``page_tokens`` the page size — the same ledger then
        admits by real free-page headroom, not worst-case rows."""
        with self._lock:
            cap = self._capacity_bytes
            if cap is None:
                cap = int(per_slot_bytes) * int(slots)
            self._pools[tag] = {
                "per_slot_bytes": int(per_slot_bytes),
                "slots": int(slots),
                "capacity_bytes": int(cap),
                "page_tokens": int(page_tokens),
                "live": 0, "queued": 0,
                "bucket_bytes": {int(b): int(b) * int(per_slot_bytes)
                                 for b in bucket_slots},
            }
            self._gauges_locked()

    def _gauges_locked(self):
        telemetry.gauge("serving.kv_capacity_bytes",
                        sum(p["capacity_bytes"]
                            for p in self._pools.values()))
        telemetry.gauge("serving.kv_resident_bytes",
                        sum(p["live"] * p["per_slot_bytes"]
                            for p in self._pools.values()))

    def _pool(self, tag):
        p = self._pools.get(tag)
        if p is None:
            raise MXNetError("KVCacheAccountant: unregistered pool %r "
                             "(register() at engine warmup)" % (tag,))
        return p

    def would_admit(self, tag, n=1):
        """True while ``n`` more sequences fit the overcommit bound.
        Unregistered tags admit (a Predictor-only replica holds no KV)."""
        with self._lock:
            p = self._pools.get(tag)
            if p is None:
                return True
            have = p["live"] + p["queued"] + n
            return have * p["per_slot_bytes"] <= \
                p["capacity_bytes"] * self._overcommit

    def try_admit(self, tag, n=1):
        """Atomic check-and-admit: the overcommit test and the queued
        increment happen under ONE lock hold, so concurrent submits
        cannot all pass a stale check and overshoot the bound (the
        DecodeEngine's admission path). Unregistered tags admit.
        Returns True when admitted (the caller owes a matching
        occupy/unqueue), False to shed."""
        with self._lock:
            p = self._pools.get(tag)
            if p is None:
                return True
            have = p["live"] + p["queued"] + n
            if have * p["per_slot_bytes"] > \
                    p["capacity_bytes"] * self._overcommit:
                return False
            p["queued"] += n
            return True

    def unqueue(self, tag, n=1):
        """``n`` admitted slots/pages left the queue without going
        resident (expired / shed / engine crash / unused page
        reservation)."""
        with self._lock:
            p = self._pool(tag)
            p["queued"] = max(0, p["queued"] - n)

    def occupy(self, tag, n=1):
        """``n`` queued slots/pages went resident (bytes now on
        device)."""
        with self._lock:
            p = self._pool(tag)
            p["queued"] = max(0, p["queued"] - n)
            p["live"] += n
            self._gauges_locked()

    def release(self, tag, n=1):
        """``n`` resident slots/pages freed (sequence finished, page
        refcount hit zero)."""
        with self._lock:
            p = self._pool(tag)
            p["live"] = max(0, p["live"] - n)
            self._gauges_locked()

    def resident_bytes(self, tag=None):
        """Live KV bytes for one tag (0 when unregistered) or all pools."""
        with self._lock:
            pools = [self._pools.get(tag)] if tag is not None \
                else list(self._pools.values())
            return sum(p["live"] * p["per_slot_bytes"] for p in pools
                       if p is not None)

    def pressure(self):
        """The fleet's KV-residency pressure as a 0..1+ fraction of the
        admission bound: max over pools of (live + queued) / (overcommit
        x capacity slots). The :class:`~mxtpu.serving.controller.
        ServingController` reads this as a scale-up signal — a cache
        near its residency bound sheds next, so capacity should grow
        BEFORE the ``kv_residency`` sheds start. 0.0 with no pools."""
        with self._lock:
            worst = 0.0
            for p in self._pools.values():
                bound = self._overcommit * p["slots"]
                if bound > 0:
                    worst = max(worst, (p["live"] + p["queued"]) / bound)
            return worst

    def gate(self, tag):
        """An ``admission_gate=`` callable for a
        :class:`~mxtpu.serving.batcher.MicroBatcher` guarding ``tag``'s
        pool: returns the shed reason ``kv_residency`` when the pool is
        over budget, None when admissible."""
        def _gate(_n_items):
            return None if self.would_admit(tag) else "kv_residency"
        return _gate

    def snapshot(self):
        """JSON-serializable per-tag view (``/healthz`` surfaces this)."""
        with self._lock:
            out = {}
            for tag, p in self._pools.items():
                out[tag] = {
                    "capacity_bytes": p["capacity_bytes"],
                    "per_slot_bytes": p["per_slot_bytes"],
                    "slots": p["slots"],
                    "page_tokens": p.get("page_tokens", 0),
                    "live": p["live"],
                    "queued": p["queued"],
                    "resident_bytes": p["live"] * p["per_slot_bytes"],
                    "bucket_bytes": dict(p["bucket_bytes"]),
                }
            return out


def _bcast(mask, ndim):
    """Broadcast a [b] mask against a [b, ...] value."""
    return mask.reshape(mask.shape + (1,) * (ndim - 1))


def _quantize_rows(x):
    """Per-row symmetric int8 through the quantization op: range = max|x|
    over each row's trailing axes (degenerate rows quantize on a unit
    grid, so all-zero rows stay exactly zero). Returns ``(q int8, r f32
    [rows])`` — THE one KV grid rule, shared by the insert path and the
    step write-back so the two can never desynchronize."""
    from ..ops.registry import get_op
    qfn = get_op("quantize").fn
    xf = jnp.asarray(x, jnp.float32)
    r = jnp.max(jnp.abs(xf), axis=tuple(range(1, xf.ndim))) \
        if xf.ndim > 1 else jnp.abs(xf)
    r = jnp.where(r > 0, r, 1.0)
    q, _lo, _hi = qfn(xf, -_bcast(r, xf.ndim), _bcast(r, xf.ndim))
    return q, r


class _PrefixCache:
    """Host-side index of SHARED read-only prompt pages (paged mode,
    ``MXTPU_PREFIX_CACHE``): a rolling chunk hash chains page-aligned
    token blocks, each entry pinning one pool page by refcount. Shared
    pages are full prompt-aligned chunks and are never written — a
    diverging suffix lives in its own private pages from the first
    unmatched chunk on, so copy-on-write materializes at page
    granularity with zero copies. Entries whose page nobody else
    references are evictable (LRU) when the free list runs dry.
    All calls run under the engine's lock."""

    def __init__(self):
        self._entries = collections.OrderedDict()  # h -> entry

    def __len__(self):
        return len(self._entries)

    @staticmethod
    def chunk_hash(parent, tokens):
        import hashlib
        h = hashlib.sha1()
        h.update(parent.encode("ascii"))
        h.update(np.ascontiguousarray(tokens, np.int32).tobytes())
        return h.hexdigest()

    def lookup(self, prompt, pt):
        """Longest cached page-aligned strict-prefix match: returns
        ``(matched_chunks, [page ids])`` — matched tokens stay <= n-1 so
        the extend path always has a novel token to prefill."""
        n = int(prompt.size)
        jmax = (n - 1) // pt
        pids, h = [], ""
        for j in range(jmax):
            chunk = prompt[j * pt:(j + 1) * pt]
            h = self.chunk_hash(h, chunk)
            e = self._entries.get(h)
            if e is None or not np.array_equal(e["tokens"], chunk):
                break
            self._entries.move_to_end(h)
            pids.append(e["pid"])
        return len(pids), pids

    def put(self, h, tokens, pid):
        """Register a full chunk's page (caller increfs the page for the
        cache's pin). Returns False when the hash is already present (the
        caller keeps its private copy unregistered)."""
        if h in self._entries:
            return False
        self._entries[h] = {"tokens": np.array(tokens, np.int32),
                            "pid": int(pid)}
        self._entries.move_to_end(h)
        return True

    def evict_one(self, page_ref):
        """Drop the least-recently-used entry whose page only the cache
        pins (refcount 1). Returns its pid, or None."""
        for h, e in self._entries.items():
            if page_ref[e["pid"]] == 1:
                del self._entries[h]
                return e["pid"]
        return None

    def drain(self):
        """Clear every entry (wedge reset / close — the device pages
        they pin are gone). Returns the pinned pids."""
        pids = [e["pid"] for e in self._entries.values()]
        self._entries.clear()
        return pids


# ------------------------------------------------------------------- engine
class DecodeEngine:
    """The continuous-batching decode loop (see the module docstring).

    ``prefill_spec`` is an ordinary seq-bucketed :class:`BucketSpec`
    (prompts pad to their seq bucket through the Predictor);
    ``decode_spec`` is the ``decode_slots=`` spelling (cohort capacity
    buckets). ``start=True`` runs a background loop thread + wedge
    monitor; ``start=False`` (tests, fake clock) drives everything
    through :meth:`poll`. One engine owns one device's cohort — tag it
    per replica (``replica_tag``) so the shared
    :class:`KVCacheAccountant` ledgers match the ``serving.predict.r<i>``
    site family."""

    def __init__(self, model, prefill_spec, decode_spec=None, max_len=None,
                 eos_id=None, example=None, warmup=True, name="decode",
                 device=None, site="serving.decode",
                 prefill_site="serving.prefill", int8=None,
                 accountant=None, replica_tag="r0", max_queue=None,
                 max_new_default=None, dispatch_timeout_ms=None,
                 clock=time.monotonic, start=False, continuous=True,
                 page_tokens=None, pool_pages=None, prefix_cache=None,
                 draft_model=None, spec_k=None,
                 draft_site="serving.draft"):
        if not hasattr(model, "decode_step"):
            raise MXNetError(
                "DecodeEngine serves DecodeModel-family blocks (got %s): "
                "implement decode_step(kv, tok, pos) -> (logits, entries) "
                "— docs/serving.md" % type(model).__name__)
        if getattr(prefill_spec, "is_decode", False):
            raise MXNetError(
                "DecodeEngine prefill_spec is a decode-cohort spec %r — "
                "prompts need batch x seq buckets (the Predictor path); "
                "pass the capacity spec as decode_spec=" % (prefill_spec,))
        if prefill_spec.seq_lens is None:
            raise MXNetError(
                "DecodeEngine prefill_spec declares no seq_lens: prompts "
                "are variable-length and MUST be seq-bucketed (a prompt "
                "past the largest bucket is refused, docs/serving.md)")
        if decode_spec is None:
            decode_spec = BucketSpec.pow2(decode_slots=decode_slots_default())
        if not getattr(decode_spec, "is_decode", False):
            raise MXNetError(
                "DecodeEngine decode_spec must use the decode_slots= "
                "spelling (got %r): cohort buckets are SLOT capacities, "
                "not request batches" % (decode_spec,))
        self._model = model
        self._prefill_spec = prefill_spec
        self._decode_spec = decode_spec
        self._capacity = decode_spec.max_slots
        self._max_new_default = int(max_new_default
                                    if max_new_default is not None
                                    else decode_max_new_default())
        self._max_len = int(max_len if max_len is not None
                            else prefill_spec.seq_lens[-1]
                            + self._max_new_default)
        if self._max_len < prefill_spec.seq_lens[-1] + 1:
            raise MXNetError(
                "DecodeEngine max_len=%d leaves no room to decode past "
                "the largest prompt bucket (%d)"
                % (self._max_len, prefill_spec.seq_lens[-1]))
        self._eos = -1 if eos_id is None else int(eos_id)
        self._name = name
        self._site = site
        self._int8 = serve_int8_default() if int8 is None else bool(int8)
        self._acct = accountant
        self._tag = replica_tag
        self._max_queue = int(max_queue if max_queue is not None
                              else decode_queue_default())
        self._timeout_s = float(
            dispatch_timeout_ms if dispatch_timeout_ms is not None
            else dispatch_timeout_ms_default()) / 1e3
        self._clock = clock
        self._continuous = bool(continuous)
        # ---- paged KV / prefix reuse / speculative decoding (ISSUE 16)
        pt = int(page_tokens if page_tokens is not None
                 else kv_page_tokens_default())
        if pt < 0 or (pt and (pt & (pt - 1))):
            raise MXNetError(
                "DecodeEngine page_tokens=%d must be 0 (rowed) or a "
                "power of two (page-offset math is a mask/shift inside "
                "the traced step)" % pt)
        self._pt = pt
        self._maxp = 0 if not pt else -(-self._max_len // pt)
        if pool_pages is not None and not pt:
            raise MXNetError("DecodeEngine pool_pages without "
                             "page_tokens: the rowed layout has no pool")
        self._pool_pages = 0 if not pt else int(
            pool_pages if pool_pages is not None
            else self._capacity * self._maxp)
        if pt and self._pool_pages < self._maxp:
            raise MXNetError(
                "DecodeEngine pool_pages=%d cannot hold even one "
                "max_len=%d sequence (%d pages of %d tokens)"
                % (self._pool_pages, self._max_len, self._maxp, pt))
        self._prefix_on = bool(prefix_cache if prefix_cache is not None
                               else prefix_cache_default())
        self._spec_k = int(spec_k if spec_k is not None
                           else spec_decode_k_default())
        if self._prefix_on and not pt:
            raise MXNetError("DecodeEngine prefix_cache needs paged KV "
                             "(MXTPU_KV_PAGE_TOKENS > 0): shared prompts "
                             "are shared PAGES")
        if self._spec_k and not pt:
            raise MXNetError("DecodeEngine spec_k needs paged KV "
                             "(MXTPU_KV_PAGE_TOKENS > 0)")
        if self._spec_k and draft_model is None:
            raise MXNetError("DecodeEngine spec_k=%d without a "
                             "draft_model: speculation needs a proposer"
                             % self._spec_k)
        if self._spec_k and self._prefix_on:
            raise MXNetError(
                "DecodeEngine prefix_cache with spec_k: a prefix hit "
                "skips the prefill the DRAFT cache also needs — run one "
                "lever per engine (docs/serving.md)")
        if draft_model is not None and not self._spec_k:
            self._spec_k = 0
            draft_model = None
        if draft_model is not None and not hasattr(draft_model,
                                                   "decode_step"):
            raise MXNetError("DecodeEngine draft_model must be a "
                             "DecodeModel (decode_step)")
        self._draft_model = draft_model
        self._draft_site = draft_site
        self._draft_pred = None
        self._dkv_layout = None
        # host page-pool state (guarded by self._cond; the Condition's
        # default RLock makes the ledger helpers re-entrant)
        self._free_pages = []
        self._page_ref = None
        self._ptab = None
        self._prefix = _PrefixCache() if self._prefix_on else None
        if example is None:
            example = np.zeros((1, prefill_spec.seq_lens[0]), np.int32)
        self._pred = Predictor(model, prefill_spec, example=example,
                               warmup=False, name=name + ".prefill",
                               device=device, site=prefill_site,
                               int8=self._int8)
        if self._draft_model is not None:
            # the draft Predictor exists for its param plumbing (the
            # draft prefill itself runs fused inside the insert
            # executables); the per-cohort draft-chain executables
            # report at serving.draft — the site the zero-post-warmup
            # watchdog pins
            self._draft_pred = Predictor(
                self._draft_model, prefill_spec, example=example,
                warmup=False, name=name + ".draft", device=device,
                site=self._draft_site, int8=False)
        self._jits = {}            # (kind, bucket, int8, policy) -> jitted
        self._kv_layout = None     # [(trailing_shape, dtype_str)] per leaf
        self._vocab = None
        self._carry = None
        self._carry_gen = 0        # bumped by every wedge reset: a step
        # dispatched against a superseded carry must not write back
        self._last_logits = None   # most recent step's logits (device; the
        # diagnostic parity hook — never fetched by the loop itself)
        self._cond = threading.Condition()
        self._pending = collections.deque()
        self._slots = [None] * self._capacity
        self._inflight_seq = None  # popped from _pending, not yet slotted
        # (mid-prefill): drain/close must not treat the engine as empty
        self._live = 0
        self._step_index = 0
        self._armed = None         # the in-flight step's watchdog entry
        self._prefill_armed = None  # the in-flight prefill/insert's entry
        self._cycles = 0           # loop/poll progress counter (probation)
        self._probation = None     # (deadline, cycles-at-trip) after a wedge
        self._closed = False
        self._draining = False
        self._crashed = False
        self._thread = None
        self._monitor = None
        self._stop = threading.Event()
        if warmup:
            self.warmup()
        if start:
            self.start()

    # ------------------------------------------------------------ properties
    @property
    def capacity(self):
        return self._capacity

    @property
    def int8(self):
        return self._int8

    @property
    def live_slots(self):
        with self._cond:
            return self._live

    @property
    def pending_count(self):
        with self._cond:
            return len(self._pending)

    @property
    def predictor(self):
        """The prefill Predictor (its compiles report at
        ``serving.prefill``)."""
        return self._pred

    @property
    def accountant(self):
        return self._acct

    @property
    def page_tokens(self):
        """Tokens per KV page (0 = rowed worst-case layout)."""
        return self._pt

    @property
    def pool_pages(self):
        """Page-pool size (0 in rowed mode). Page id 0 is a scratch
        page on top of this count — inactive-slot and overflow writes
        land there, so the pool ids are 1..pool_pages."""
        return self._pool_pages

    @property
    def spec_k(self):
        """Speculative draft length (0 = plain one-token steps)."""
        return self._spec_k

    def per_slot_kv_bytes(self):
        """Resident bytes one slot's KV cache costs (int8: quantized
        leaves + per-position scale rows) — what the accountant ledgers.
        In paged mode this is the WORST-CASE cost (max_len tokens); the
        accountant instead ledgers :meth:`page_bytes` x pages actually
        mapped."""
        if self._kv_layout is None:
            raise MXNetError("per_slot_kv_bytes before warmup()")
        total = 0
        for trail, dt in self._kv_layout:
            n = self._max_len * int(np.prod(trail, dtype=np.int64) or 1)
            if self._int8:
                total += n * 1 + self._max_len * 4  # int8 rows + f32 scales
            else:
                total += n * jnp.dtype(dt).itemsize
        return total

    def page_bytes(self):
        """Resident bytes one pool page costs (``page_tokens`` rows of
        every KV leaf; int8: quantized rows + per-position scales)."""
        if self._kv_layout is None:
            raise MXNetError("page_bytes before warmup()")
        if not self._pt:
            raise MXNetError("page_bytes on a rowed engine "
                             "(page_tokens=0)")
        total = 0
        for trail, dt in self._kv_layout:
            n = self._pt * int(np.prod(trail, dtype=np.int64) or 1)
            if self._int8:
                total += n * 1 + self._pt * 4
            else:
                total += n * jnp.dtype(dt).itemsize
        return total

    # ---------------------------------------------------------------- warmup
    def warmup(self):
        """Settle the prefill templates, derive the KV layout from one
        probe forward, AOT-compile every prefill bucket, every cohort
        step bucket, and every insert bucket, and allocate the cohort
        carry. After this, a compile at ``serving.decode`` is a served
        stall — the watchdog (and the serve_bench gate) pins the site at
        its post-warmup count. Idempotent."""
        if self._kv_layout is not None:
            return self
        flat, _fmt, _b = self._pred.predict_flat(
            (np.zeros((1, self._prefill_spec.seq_lens[0]), np.int32),))
        if len(flat) < 2:
            raise MXNetError(
                "DecodeModel forward must return (logits, *kv_leaves); "
                "got %d output(s) — the KV cache IS the decode state"
                % len(flat))
        logits = flat[0]
        if logits._data.ndim != 3:
            raise MXNetError(
                "DecodeModel prefill logits must be [batch, seq, vocab], "
                "got shape %s" % (tuple(logits._data.shape),))
        self._vocab = int(logits._data.shape[-1])
        layout = []
        for i, leaf in enumerate(flat[1:]):
            d = leaf._data
            if d.ndim < 2 or d.shape[1] != logits._data.shape[1]:
                raise MXNetError(
                    "DecodeModel kv leaf %d must be [batch, seq, ...] "
                    "(got shape %s)" % (i, tuple(d.shape)))
            layout.append((tuple(int(x) for x in d.shape[2:]),
                           str(d.dtype)))
        self._kv_layout = layout
        self._pred.warmup()
        if self._draft_pred is not None:
            dflat, _df, _db = self._draft_pred.predict_flat(
                (np.zeros((1, self._prefill_spec.seq_lens[0]), np.int32),))
            if len(dflat) < 2 or dflat[0]._data.ndim != 3:
                raise MXNetError("draft_model must follow the DecodeModel "
                                 "prefill contract (logits, *kv_leaves)")
            if int(dflat[0]._data.shape[-1]) != self._vocab:
                raise MXNetError(
                    "draft_model vocab %d != target vocab %d — the "
                    "draft proposes TARGET token ids"
                    % (int(dflat[0]._data.shape[-1]), self._vocab))
            self._dkv_layout = [
                (tuple(int(x) for x in leaf._data.shape[2:]),
                 str(leaf._data.dtype)) for leaf in dflat[1:]]
            # no _draft_pred.warmup(): the draft prefill runs FUSED
            # inside the insert executables (warmed below) — the draft
            # Predictor only supplies params and the probe above
        with self._cond:
            if self._pt:
                self._reset_pool_locked()
            self._carry = self._alloc_carry()
        # AOT: one step executable per cohort capacity bucket (replayed
        # on the all-inactive cohort — a no-op step; spec mode compiles
        # the draft-chain + verify pair instead), one insert executable
        # per prefill seq bucket (max_new=0 marks the warmed slot
        # done-at-insert, so warmup leaves no live slot behind), and —
        # prefix mode — one extend executable per seq bucket. First
        # invocations trace the shared block (parameters bind tracers):
        # serialize across engines like the Predictor does.
        with _TRACE_LOCK:
            ptab0 = None if not self._pt else \
                np.zeros((self._capacity, self._maxp), np.int32)
            for b in self._decode_spec.decode_slots:
                if self._spec_k:
                    d_args = (self._carry, self._draft_pred._param_datas,
                              self._draft_pred._param_ranges)
                    self._carry, props = self._get_draft_jit(
                        b, example_args=d_args)(*d_args)
                    v_args = (self._carry, ptab0, props,
                              self._pred._param_datas,
                              self._pred._param_ranges)
                    self._carry, emitted = self._get_verify_jit(
                        b, example_args=v_args)(*v_args)
                elif self._pt:
                    step_args = (self._carry, ptab0,
                                 self._pred._param_datas,
                                 self._pred._param_ranges)
                    self._carry, emitted = self._get_step_jit(
                        b, example_args=step_args)(*step_args)
                else:
                    step_args = (self._carry, self._pred._param_datas,
                                 self._pred._param_ranges)
                    self._carry, emitted = self._get_step_jit(
                        b, example_args=step_args)(*step_args)
                jax.block_until_ready(emitted[0])
            V = self._vocab
            for s in self._prefill_spec.seq_lens:
                seq_kv = [jnp.zeros((1, s) + trail, dt)
                          for trail, dt in layout]
                # the probe forward's ACTUAL logits dtype: a bf16 model
                # warmed against f32 zeros would hit the cached wrapper
                # but retrace inside jax on the first real insert — a
                # mid-serving compile stall invisible to record_retrace
                zl = jnp.zeros((1, s, V), logits._data.dtype)
                if self._pt:
                    pages0 = np.zeros(-(-s // self._pt), np.int32)
                    if self._spec_k:
                        ins_args = (self._carry, seq_kv, zl,
                                    np.zeros(s, np.int32), pages0,
                                    np.int32(0), np.int32(1), np.int32(0),
                                    self._draft_pred._param_datas,
                                    self._draft_pred._param_ranges)
                    else:
                        ins_args = (self._carry, seq_kv, zl, pages0,
                                    np.int32(0), np.int32(1), np.int32(0))
                else:
                    ins_args = (self._carry, seq_kv, zl,
                                np.int32(0), np.int32(1), np.int32(0))
                self._carry, out = self._get_insert_jit(
                    s, example_args=ins_args)(*ins_args)
                jax.block_until_ready(out)
                if self._prefix is not None:
                    ext_args = (self._carry, np.zeros(self._maxp, np.int32),
                                np.zeros(s, np.int32), np.int32(0),
                                np.int32(0), np.int32(0), np.int32(0),
                                self._pred._param_datas,
                                self._pred._param_ranges)
                    self._carry, out = self._get_extend_jit(
                        s, example_args=ext_args)(*ext_args)
                    jax.block_until_ready(out)
        telemetry.gauge("serving.decode.buckets",
                        len(self._decode_spec.decode_slots)
                        + len(self._prefill_spec.seq_lens))
        if self._acct is not None:
            if self._pt:
                # page-granular ledger: one "slot" = one page, so the
                # byte gauges and the admission bound track pages
                # actually mapped, not worst-case rows
                self._acct.register(self._tag, self.page_bytes(),
                                    self._pool_pages,
                                    page_tokens=self._pt)
            else:
                self._acct.register(
                    self._tag, self.per_slot_kv_bytes(), self._capacity,
                    bucket_slots=self._decode_spec.decode_slots)
        # will-it-fit pre-flight (mxtpu/xprof.py): Σ AOT step+insert
        # executable footprints vs the device HBM limit — warmup
        # succeeding bucket-by-bucket does not mean every bucket's
        # residents coexist; skipped (zero extra lowering) when the
        # backend exposes no limit (CPU tier)
        from .. import xprof
        xprof.ensure_memwatch()
        xprof.preflight(self._site)
        return self

    def _alloc_carry(self):
        C, L = self._capacity, self._max_len
        if self._pt:
            # paged: leaves are [pool+1, page_tokens, ...] — page id 0
            # is the scratch page (inactive-slot writes, unmapped table
            # entries, and clamped overflow all land there)
            rows = (self._pool_pages + 1, self._pt)
            if self._int8:
                kv = [jnp.zeros(rows + trail, jnp.int8)
                      for trail, _dt in self._kv_layout]
                scales = [jnp.ones(rows, jnp.float32)
                          for _ in self._kv_layout]
            else:
                kv = [jnp.zeros(rows + trail, dt)
                      for trail, dt in self._kv_layout]
                scales = None
        elif self._int8:
            kv = [jnp.zeros((C, L) + trail, jnp.int8)
                  for trail, _dt in self._kv_layout]
            scales = [jnp.ones((C, L), jnp.float32)
                      for _ in self._kv_layout]
        else:
            kv = [jnp.zeros((C, L) + trail, dt)
                  for trail, dt in self._kv_layout]
            scales = None
        tok = jnp.zeros((C,), jnp.int32)
        pos = jnp.zeros((C,), jnp.int32)
        active = jnp.zeros((C,), jnp.bool_)
        rem = jnp.zeros((C,), jnp.int32)
        carry = (kv, scales, tok, pos, active, rem)
        if self._spec_k:
            # the draft's KV stays ROWED in compute dtype: the draft is
            # small by design, and keeping it worst-case keeps the
            # proposer off the page pool entirely
            carry += ([jnp.zeros((C, L) + trail, dt)
                       for trail, dt in self._dkv_layout],)
        return carry

    # ------------------------------------------------------ page pool (host)
    def _reset_pool_locked(self):
        """(Re)build the free list, refcounts, and page tables — engine
        construction and every carry re-allocation (wedge reset, crash,
        close): the device pages a reset zeroes must never stay mapped."""
        P = self._pool_pages
        self._free_pages = list(range(P, 0, -1))   # pop() -> 1, 2, ...
        self._page_ref = np.zeros(P + 1, np.int32)
        self._ptab = np.zeros((self._capacity, max(1, self._maxp)),
                              np.int32)
        self._page_gauges_locked()

    def _page_gauges_locked(self):
        if not self._pt:
            return
        free = len(self._free_pages)
        telemetry.gauge("serving.kv_page_free", free)
        telemetry.gauge("serving.kv_page_resident", self._pool_pages - free)
        telemetry.gauge("serving.kv_page_shared",
                        int(np.sum(self._page_ref[1:] >= 2)))
        telemetry.gauge("serving.kv_resident_tokens",
                        sum(s.pos for s in self._slots if s is not None))

    def _take_page_locked(self, seq):
        """Allocate one pool page for ``seq`` (ledger + refcount + map).
        Returns the pid, or None on exhaustion — physical (free list dry
        even after evicting cache-only pages) or ledgered (the
        accountant's page headroom is gone and the sequence holds no
        reservation to convert)."""
        if seq.reserved <= 0:
            if self._acct is not None \
                    and not self._acct.try_admit(self._tag):
                return None
            seq.reserved += 1
        if not self._free_pages and self._prefix is not None:
            pid = self._prefix.evict_one(self._page_ref)
            if pid is not None:
                self._decref_locked(pid)
        if not self._free_pages:
            # physically dry: hand the reservation back before refusing
            if self._acct is not None:
                self._acct.unqueue(self._tag)
            seq.reserved -= 1
            return None
        pid = self._free_pages.pop()
        self._page_ref[pid] = 1
        if self._acct is not None:
            self._acct.occupy(self._tag)
        seq.reserved -= 1
        seq.pages.append(pid)
        return pid

    def _share_page_locked(self, seq, pid):
        """Attach a cache-shared page to ``seq`` (refcount only — the
        page's bytes are already ledgered live)."""
        self._page_ref[pid] += 1
        seq.pages.append(pid)

    def _decref_locked(self, pid):
        """Drop one reference; at zero the page returns to the free list
        and its bytes leave the accountant's resident count."""
        self._page_ref[pid] -= 1
        if self._page_ref[pid] <= 0:
            self._page_ref[pid] = 0
            self._free_pages.append(pid)
            if self._acct is not None:
                self._acct.release(self._tag)

    def _free_seq_ledger(self, seq, slotted):
        """THE one teardown ledger for a sequence (normal completion,
        done-at-insert, deadline expiry, wedge casualty, wedge scan,
        crash barrier, close): paged mode derefs every mapped page and
        hands back any unconverted reservation; rowed mode keeps the PR
        11 release-vs-unqueue split. One copy, so no path can leak pool
        pages or drive the free count negative."""
        if self._pt:
            with self._cond:
                for pid in seq.pages:
                    self._decref_locked(pid)
                seq.pages = []
                if seq.reserved > 0 and self._acct is not None:
                    self._acct.unqueue(self._tag, n=seq.reserved)
                seq.reserved = 0
                self._page_gauges_locked()
        elif self._acct is not None:
            if slotted:
                self._acct.release(self._tag)
            else:
                self._acct.unqueue(self._tag)

    def _register_prefix_locked(self, seq, m_chunks):
        """Publish this prompt's FULL chunks into the prefix cache (the
        cache holds one extra reference per entry, so a published page
        outlives its first owner). Only chunks wholly inside the prompt
        register — the page holding the first generated token is private
        by construction, which is what makes shared pages read-only
        without any copy-on-write machinery."""
        if self._prefix is None:
            return
        pt = self._pt
        n = int(seq.prompt.size)
        h = ""
        for j in range(n // pt):
            chunk = seq.prompt[j * pt:(j + 1) * pt]
            h = _PrefixCache.chunk_hash(h, chunk)
            if j >= m_chunks and j < len(seq.pages):
                if self._prefix.put(h, chunk, seq.pages[j]):
                    self._page_ref[seq.pages[j]] += 1
        self._page_gauges_locked()

    # ------------------------------------------------------------- compiling
    def _build_jit(self, kind, bucket, build, donate=(0,),
                   example_args=None):
        """The one compile front door for the decode cache: every miss
        resolves through the compile service (LRU store, disk cache,
        centralized retrace reporting at this engine's site —
        ``serving.decode``; graftlint's JIT_ALLOWLIST declares the cache
        since the site name is per-instance), exactly like
        ``Predictor._get_jit`` — post-warmup the site count stays at
        #cohort-buckets + #insert-buckets by construction, and a
        warm-disk restart reaches it with ZERO compiles."""
        from .. import compile_service as csvc
        from ..ops.registry import policy_key
        pol = policy_key()
        key = (kind, bucket, self._int8, pol)
        hit = self._jits.get(key)
        if hit is not None:
            return hit
        ckey = csvc.canonical_key(
            site=self._site,
            fn_id="decode:%s:%s" % (type(self._model).__name__,
                                    csvc.source_token(type(self._model))),
            # the predictor's param structure joins the signature: two
            # models of the same class but different widths (same
            # kv_layout/vocab) must never alias a disk digest — a
            # shape-mismatched restore would crash, not degrade
            # the paged dims join the signature: a paged and a rowed
            # engine of the same model (or two pool sizes) must never
            # alias a disk digest — a shape-mismatched restore would
            # crash, not degrade
            signature=(kind, bucket, self._int8, self._capacity,
                       self._max_len, self._eos,
                       tuple(self._kv_layout or ()), self._vocab,
                       tuple((tuple(d.shape), str(d.dtype))
                             for d in self._pred._param_datas),
                       self._pt, self._pool_pages, self._spec_k,
                       tuple(self._dkv_layout or ())),
            policy=pol, donation=donate,
            device=csvc.device_token(device=self._pred.device),
            nonce=csvc.instance_nonce(self))
        entry = csvc.get_or_build(
            ckey, lambda: jax.jit(build(), donate_argnums=donate),
            provenance={"engine": self._name, "kind": kind,
                        "bucket": bucket, "int8": self._int8,
                        "capacity": self._capacity,
                        "max_len": self._max_len,
                        "policy_key": list(pol)},
            example_args=csvc.concrete_args(example_args)
            if example_args is not None else None)
        self._jits[key] = entry.fn
        return entry.fn

    def _build_draft_jit(self, kind, bucket, build, donate=(0,),
                         example_args=None):
        """The compile front door for the DRAFT-model executables
        (speculative decoding): same compile-service seam as
        ``_build_jit`` but reporting at the ``serving.draft`` site — the
        sixth entry in graftlint's caches inventory, with its own
        zero-post-warmup watchdog pin. One draft-chain executable per
        cohort capacity bucket; the draft Predictor's prefill buckets
        share the site."""
        from .. import compile_service as csvc
        from ..ops.registry import policy_key
        pol = policy_key()
        key = (kind, bucket, self._int8, pol)
        hit = self._jits.get(key)
        if hit is not None:
            return hit
        ckey = csvc.canonical_key(
            site=self._draft_site,
            fn_id="draft:%s:%s" % (type(self._draft_model).__name__,
                                   csvc.source_token(
                                       type(self._draft_model))),
            signature=(kind, bucket, self._capacity, self._max_len,
                       self._spec_k, tuple(self._dkv_layout or ()),
                       self._vocab,
                       tuple((tuple(d.shape), str(d.dtype))
                             for d in self._draft_pred._param_datas)),
            policy=pol, donation=donate,
            device=csvc.device_token(device=self._pred.device),
            nonce=csvc.instance_nonce(self))
        entry = csvc.get_or_build(
            ckey, lambda: jax.jit(build(), donate_argnums=donate),
            provenance={"engine": self._name, "kind": kind,
                        "bucket": bucket, "spec_k": self._spec_k,
                        "capacity": self._capacity,
                        "max_len": self._max_len,
                        "policy_key": list(pol)},
            example_args=csvc.concrete_args(example_args)
            if example_args is not None else None)
        self._jits[key] = entry.fn
        return entry.fn

    def _kv_read(self, kv, scales, b):
        """The first ``b`` slots' caches in compute dtype (int8:
        dequantized through the quantization op, per-position scale rows
        broadcast against the trailing dims)."""
        if not self._int8:
            return [leaf[:b] for leaf in kv]
        from ..ops.registry import get_op
        deq = get_op("dequantize").fn
        out = []
        for (trail, dt), q, s in zip(self._kv_layout, kv, scales):
            rb = s[:b].reshape((b, self._max_len) + (1,) * len(trail))
            out.append(deq(q[:b], -rb, rb).astype(dt))
        return out

    def _kv_write_rows(self, kv, scales, entries, pos_b, act_b, b):
        """Persist this step's new k/v rows at (slot, pos) — inactive
        slots keep their old bytes (the model's row for them is
        garbage). int8: per-row symmetric quantization through the
        quantization op, scale rows ledgered next to the cache."""
        idx = jnp.arange(b)
        new_kv, new_scales = list(kv), None if scales is None \
            else list(scales)
        for i, entry in enumerate(entries):
            if self._int8:
                q, r = _quantize_rows(entry)
                old_q = new_kv[i][idx, pos_b]
                old_s = new_scales[i][idx, pos_b]
                q = jnp.where(_bcast(act_b, q.ndim), q, old_q)
                r = jnp.where(act_b, r, old_s)
                new_kv[i] = new_kv[i].at[idx, pos_b].set(q)
                new_scales[i] = new_scales[i].at[idx, pos_b].set(r)
            else:
                leaf = new_kv[i]
                old = leaf[idx, pos_b]
                row = jnp.where(_bcast(act_b, entry.ndim),
                                entry.astype(leaf.dtype), old)
                new_kv[i] = leaf.at[idx, pos_b].set(row)
        return new_kv, new_scales

    def _kv_gather(self, kv, scales, ptab_b, b):
        """Dense ``[b, max_len, ...]`` compute-dtype views of the paged
        pool through the slots' page tables (int8: dequantized) — the
        traced gather that makes paging invisible to ``decode_step``.
        Unmapped table entries read the scratch page: stale bytes, but
        the model's position mask never attends past ``pos``."""
        L, pt, maxp = self._max_len, self._pt, self._maxp
        out = []
        if not self._int8:
            for (trail, _dt), leaf in zip(self._kv_layout, kv):
                d = leaf[ptab_b]               # [b, maxp, pt, *trail]
                out.append(d.reshape((b, maxp * pt) + trail)[:, :L])
            return out
        from ..ops.registry import get_op
        deq = get_op("dequantize").fn
        for (trail, dt), q, s in zip(self._kv_layout, kv, scales):
            dq = q[ptab_b].reshape((b, maxp * pt) + trail)[:, :L]
            rs = s[ptab_b].reshape((b, maxp * pt))[:, :L]
            rb = rs.reshape((b, L) + (1,) * len(trail))
            out.append(deq(dq, -rb, rb).astype(dt))
        return out

    def _kv_scatter_rows(self, kv, scales, entries, page_b, off_b, keep_b):
        """Persist one new k/v row per slot at (page, offset); slots with
        ``keep_b`` False redirect to the scratch page — old pool bytes
        are never disturbed, and a page is quantized row-by-row as it
        fills, so old pages never requantize (int8 grids match the rowed
        engine's exactly)."""
        pg = jnp.where(keep_b, page_b, 0)
        new_kv = list(kv)
        new_scales = None if scales is None else list(scales)
        for i, entry in enumerate(entries):
            if self._int8:
                q, r = _quantize_rows(entry)
                new_kv[i] = new_kv[i].at[pg, off_b].set(q)
                new_scales[i] = new_scales[i].at[pg, off_b].set(r)
            else:
                new_kv[i] = new_kv[i].at[pg, off_b].set(
                    entry.astype(new_kv[i].dtype))
        return new_kv, new_scales

    def _kv_row_update(self, kv_b, entries, idx, wp, upd):
        """Refresh a dense gathered view with one sub-step's new rows so
        the NEXT chained forward sees them without re-gathering the
        pool. int8 runs the rows through the same quantize->dequantize
        roundtrip a pool re-gather would apply, so the speculative
        chain stays bit-identical to step-at-a-time decode."""
        out = []
        if not self._int8:
            for leaf, entry in zip(kv_b, entries):
                old = leaf[idx, wp]
                row = jnp.where(_bcast(upd, entry.ndim),
                                entry.astype(leaf.dtype), old)
                out.append(leaf.at[idx, wp].set(row))
            return out
        from ..ops.registry import get_op
        deq = get_op("dequantize").fn
        for (_trail, dt), leaf, entry in zip(self._kv_layout, kv_b,
                                             entries):
            q, r = _quantize_rows(entry)
            rb = _bcast(r, q.ndim)
            row = deq(q, -rb, rb).astype(dt)
            old = leaf[idx, wp]
            out.append(leaf.at[idx, wp].set(
                jnp.where(_bcast(upd, row.ndim), row, old)))
        return out

    def _page_of(self, ptab_b, idx, p):
        """Traced page lookup for position ``p`` (clamped into the
        table; callers mask overflow to the scratch page via keep)."""
        chunk = jnp.minimum(p // self._pt, self._maxp - 1)
        return ptab_b[idx, chunk]

    def _get_step_jit(self, b, example_args=None):
        model, pred = self._model, self._pred
        eos, max_len = self._eos, self._max_len
        pt = self._pt
        engine = self

        def build():
            fixed_key = jax.random.PRNGKey(0)

            def pure_rowed(carry, param_datas, param_ranges):
                from ..gluon.block import _run_traced
                kv, scales, tok, pos, active, rem = carry
                pds = pred._traced_params(param_datas, param_ranges)
                act_b, tok_b, pos_b = active[:b], tok[:b], pos[:b]
                kv_b = engine._kv_read(kv, scales, b)

                def body():
                    return model.decode_step(kv_b, tok_b, pos_b)

                (logits, entries), _aux = _run_traced(
                    pred._params, pds, fixed_key, False, body)
                next_tok = jnp.argmax(
                    jnp.asarray(logits, jnp.float32), axis=-1).astype(
                        jnp.int32)
                next_tok = jnp.where(act_b, next_tok, tok_b)
                new_pos_b = jnp.where(act_b, pos_b + 1, pos_b)
                rem_b = jnp.where(act_b, rem[:b] - 1, rem[:b])
                done_b = act_b & ((next_tok == eos) | (rem_b <= 0)
                                  | (new_pos_b >= max_len))
                kv, scales = engine._kv_write_rows(kv, scales, entries,
                                                   pos_b, act_b, b)
                tok = tok.at[:b].set(next_tok)
                pos = pos.at[:b].set(new_pos_b)
                active = active.at[:b].set(act_b & ~done_b)
                rem = rem.at[:b].set(rem_b)
                return ((kv, scales, tok, pos, active, rem),
                        (next_tok, done_b, logits))

            def pure_paged(carry, ptab, param_datas, param_ranges):
                from ..gluon.block import _run_traced
                kv, scales, tok, pos, active, rem = carry[:6]
                pds = pred._traced_params(param_datas, param_ranges)
                act_b, tok_b, pos_b = active[:b], tok[:b], pos[:b]
                ptab_b, idx = ptab[:b], jnp.arange(b)
                kv_b = engine._kv_gather(kv, scales, ptab_b, b)

                def body():
                    return model.decode_step(kv_b, tok_b, pos_b)

                (logits, entries), _aux = _run_traced(
                    pred._params, pds, fixed_key, False, body)
                next_tok = jnp.argmax(
                    jnp.asarray(logits, jnp.float32), axis=-1).astype(
                        jnp.int32)
                next_tok = jnp.where(act_b, next_tok, tok_b)
                new_pos_b = jnp.where(act_b, pos_b + 1, pos_b)
                rem_b = jnp.where(act_b, rem[:b] - 1, rem[:b])
                done_b = act_b & ((next_tok == eos) | (rem_b <= 0)
                                  | (new_pos_b >= max_len))
                keep = act_b & (pos_b < max_len)
                page_b = engine._page_of(ptab_b, idx, pos_b)
                kv, scales = engine._kv_scatter_rows(
                    kv, scales, entries, page_b, pos_b % pt, keep)
                tok = tok.at[:b].set(next_tok)
                pos = pos.at[:b].set(new_pos_b)
                active = active.at[:b].set(act_b & ~done_b)
                rem = rem.at[:b].set(rem_b)
                return ((kv, scales, tok, pos, active, rem) + carry[6:],
                        (next_tok, done_b, logits))

            return pure_paged if pt else pure_rowed

        return self._build_jit("step", b, build,
                               example_args=example_args)

    def _get_draft_jit(self, b, example_args=None):
        """The speculative proposer for cohort bucket ``b``: k greedy
        draft tokens per live slot, chained inside ONE executable over
        the draft's rowed KV (compiles pinned at ``serving.draft``)."""
        dmodel, dpred = self._draft_model, self._draft_pred
        k, max_len = self._spec_k, self._max_len

        def build():
            fixed_key = jax.random.PRNGKey(0)

            def pure(carry, param_datas, param_ranges):
                from ..gluon.block import _run_traced
                tok, pos, active = carry[2], carry[3], carry[4]
                dkv = list(carry[6])
                pds = dpred._traced_params(param_datas, param_ranges)
                act_b, idx = active[:b], jnp.arange(b)
                cur = tok[:b]
                props = []
                # k + 1 feeds for k proposals: the LAST feed exists only
                # to write d_k's KV row (logits discarded, DCE'd).  On a
                # full accept the commit's bonus token advances pos past
                # pos+k, so without that row the draft cache keeps a
                # permanent hole there and silently diverges after every
                # clean macro — acceptance decays even with draft==target.
                for j in range(k + 1):
                    p_j = pos[:b] + j
                    dkv_b = [leaf[:b] for leaf in dkv]

                    def body(kv_b=dkv_b, c=cur, p=p_j):
                        return dmodel.decode_step(kv_b, c, p)

                    (logits, entries), _aux = _run_traced(
                        dpred._params, pds, fixed_key, False, body)
                    wp = jnp.minimum(p_j, max_len - 1)
                    keep = act_b & (p_j < max_len)
                    for i, entry in enumerate(entries):
                        old = dkv[i][idx, wp]
                        row = jnp.where(_bcast(keep, entry.ndim),
                                        entry.astype(dkv[i].dtype), old)
                        dkv[i] = dkv[i].at[idx, wp].set(row)
                    if j < k:
                        cur = jnp.where(act_b, jnp.argmax(
                            jnp.asarray(logits, jnp.float32),
                            axis=-1).astype(jnp.int32), cur)
                        props.append(cur)
                return (carry[:6] + (dkv,), jnp.stack(props, axis=1))

            return pure

        return self._build_draft_jit("draft", b, build,
                                     example_args=example_args)

    def _get_verify_jit(self, b, example_args=None):
        """The speculative commit for cohort bucket ``b``: the TARGET
        model ingests the pending token plus the k draft proposals in
        one chained executable, emits greedy tokens g_1..g_{k+1}, and
        commits the longest prefix where draft == target — truncated by
        exactly the non-speculative stopping rule (eos / budget /
        max_len), so the committed stream is bit-identical to plain
        greedy decode. Rows written past the commit are stale-but-masked
        and get overwritten when those positions are really reached.

        The pool is gathered ONCE per macro-step. Models that implement
        :meth:`DecodeModel.decode_chunk` (f32 engines only) score all
        k+1 positions in a SINGLE causal forward; otherwise the k+1
        forwards chain over a dense working copy refreshed row-by-row
        (``_kv_row_update``). Either way the whole chain's rows write
        back to the pool in one batched scatter."""
        model, pred = self._model, self._pred
        eos, max_len = self._eos, self._max_len
        pt, k, maxp = self._pt, self._spec_k, self._maxp
        base = DecodeModel.decode_chunk
        chunked = (not self._int8) and getattr(
            type(model), "decode_chunk", base) is not base
        engine = self

        def build():
            fixed_key = jax.random.PRNGKey(0)

            def pure(carry, ptab, props, param_datas, param_ranges):
                from ..gluon.block import _run_traced
                kv, scales, tok, pos, active, rem = carry[:6]
                pds = pred._traced_params(param_datas, param_ranges)
                act_b, tok_b, pos_b = active[:b], tok[:b], pos[:b]
                rem_b = rem[:b]
                ptab_b, idx = ptab[:b], jnp.arange(b)
                kv_b = engine._kv_gather(kv, scales, ptab_b, b)
                if chunked:
                    ctoks = jnp.concatenate(
                        [tok_b[:, None], props], axis=1)   # [b, k+1]

                    def body(kv_j=kv_b, c=ctoks, p=pos_b):
                        return model.decode_chunk(kv_j, c, p)

                    (logits, entries), _aux = _run_traced(
                        pred._params, pds, fixed_key, False, body)
                    outs = jnp.argmax(
                        jnp.asarray(logits, jnp.float32),
                        axis=-1).astype(jnp.int32)         # [b, k+1]
                    stacked = [
                        e.reshape((b * (k + 1),) + tuple(e.shape[2:]))
                        for e in entries]
                else:
                    cur, gs, rows = tok_b, [], []
                    for j in range(k + 1):
                        p_j = pos_b + j

                        def body(kv_j=list(kv_b), c=cur, p=p_j):
                            return model.decode_step(kv_j, c, p)

                        (logits, entries), _aux = _run_traced(
                            pred._params, pds, fixed_key, False, body)
                        rows.append(entries)
                        gs.append(jnp.argmax(
                            jnp.asarray(logits, jnp.float32),
                            axis=-1).astype(jnp.int32))
                        if j < k:
                            wp = jnp.minimum(p_j, max_len - 1)
                            upd = act_b & (p_j < max_len)
                            kv_b = engine._kv_row_update(
                                kv_b, entries, idx, wp, upd)
                            cur = props[:, j]
                    outs = jnp.stack(gs, axis=1)          # [b, k+1]
                    stacked = [
                        jnp.stack([r[i] for r in rows], axis=1).reshape(
                            (b * (k + 1),) + tuple(rows[0][i].shape[1:]))
                        for i in range(len(rows[0]))]
                p_all = pos_b[:, None] + jnp.arange(k + 1)[None, :]
                keep = (act_b[:, None]
                        & (p_all < max_len)).reshape(-1)
                chunk = jnp.minimum(p_all // pt, maxp - 1)
                page = jnp.take_along_axis(
                    ptab_b, chunk, axis=1).reshape(-1)
                off = (p_all % pt).reshape(-1)
                kv, scales = engine._kv_scatter_rows(
                    kv, scales, stacked, page, off, keep)
                acc = jnp.cumprod(
                    (props == outs[:, :k]).astype(jnp.int32), axis=1)
                a = jnp.sum(acc, axis=1)              # accepted drafts
                i1 = jnp.arange(k + 1)[None, :]       # token index - 1
                stop = (outs == eos) \
                    | ((rem_b[:, None] - (i1 + 1)) <= 0) \
                    | ((pos_b[:, None] + i1 + 1) >= max_len)
                within = (i1 <= a[:, None]) & act_b[:, None]
                s_in = stop & within
                prev = jnp.cumsum(s_in, axis=1) - s_in.astype(jnp.int32)
                emit = within & (prev == 0)
                counts = jnp.sum(emit.astype(jnp.int32), axis=1)
                done_b = jnp.any(stop & emit, axis=1)
                last = jnp.maximum(counts - 1, 0)
                new_tok = jnp.where(act_b, outs[idx, last], tok_b)
                new_pos = pos_b + counts
                tok = tok.at[:b].set(new_tok)
                pos = pos.at[:b].set(new_pos)
                active = active.at[:b].set(act_b & ~done_b)
                rem = rem.at[:b].set(rem_b - counts)
                masked = jnp.where(emit, outs, -1)
                # one packed int32 fetch for the host: [b, k+1] masked
                # emitted tokens | counts | done — three d2h syncs per
                # macro-step would eat the dispatch savings speculation
                # exists to win
                packed = jnp.concatenate(
                    [masked, counts[:, None],
                     done_b.astype(jnp.int32)[:, None]], axis=1)
                return ((kv, scales, tok, pos, active, rem) + carry[6:],
                        packed)

            return pure

        return self._build_jit("verify", b, build,
                               example_args=example_args)

    def _get_extend_jit(self, s, example_args=None):
        """The prefix-hit prefill for seq bucket ``s``: the matched
        chunks' pages are SHARED (read-only), so only the novel suffix
        runs — a chained ``decode_step`` loop writing suffix rows into
        the slot's private pages and emitting the first token from the
        last prompt position. Prefill skips straight to the first novel
        token, per ISSUE 16."""
        model, pred = self._model, self._pred
        eos, max_len = self._eos, self._max_len
        pt, maxp = self._pt, self._maxp
        engine = self

        def build():
            fixed_key = jax.random.PRNGKey(0)

            def pure(carry, ptab_row, toks, m, n, slot, max_new,
                     param_datas, param_ranges):
                from ..gluon.block import _run_traced
                kv, scales, tok, pos, active, rem = carry[:6]
                pds = pred._traced_params(param_datas, param_ranges)

                def step_t(t, state):
                    kv, scales, fl = state
                    p = m + t
                    proc = p < n
                    kv_b = engine._kv_gather(kv, scales, ptab_row[None], 1)
                    cur = toks[jnp.minimum(p, s - 1)][None]

                    def body(kv_j=kv_b, c=cur, pp=p[None]):
                        return model.decode_step(kv_j, c, pp)

                    (logits, entries), _aux = _run_traced(
                        pred._params, pds, fixed_key, False, body)
                    keep = jnp.asarray(proc & (p < max_len))[None]
                    chunk = jnp.minimum(p // pt, maxp - 1)
                    page = ptab_row[chunk][None]
                    kv, scales = engine._kv_scatter_rows(
                        kv, scales, entries, page, (p % pt)[None], keep)
                    fl = jnp.where(p == n - 1,
                                   jnp.asarray(logits[0], jnp.float32), fl)
                    return (kv, scales, fl)

                kv, scales, fl = lax.fori_loop(
                    0, s, step_t,
                    (kv, scales, jnp.zeros((engine._vocab,), jnp.float32)))
                first = jnp.argmax(fl).astype(jnp.int32)
                done0 = (first == eos) | (max_new <= 1) | (n >= max_len)
                tok = tok.at[slot].set(first)
                pos = pos.at[slot].set(n)
                active = active.at[slot].set(~done0)
                rem = rem.at[slot].set(max_new - 1)
                out = jnp.stack([first, done0.astype(jnp.int32)])
                return ((kv, scales, tok, pos, active, rem) + carry[6:],
                        out)

            return pure

        return self._build_jit("extend", s, build,
                               example_args=example_args)

    def _get_insert_jit(self, s, example_args=None):
        """Slot insert for prefill seq bucket ``s``: a device-side
        ``dynamic_update_slice`` of the prompt's KV into a TRACED slot
        index — joining the running cohort never recompiles. Also samples
        the first token from the prefill logits at the prompt's true
        length (and marks the slot done-at-insert when that token already
        ends the sequence), so time-to-first-token needs no decode step.
        Paged mode instead scatters the prompt's KV page-chunk by
        page-chunk into the TRACED page ids the host allocated (the
        prefill -> page handoff); spec mode additionally seeds the
        draft's rowed KV — the draft prefill runs FUSED inside this
        executable (prompt tokens + draft params ride as traced args),
        so admitting a request costs one insert dispatch, not a second
        Predictor round-trip for the draft."""
        eos, max_len = self._eos, self._max_len
        pt, spec = self._pt, bool(self._spec_k)
        dmodel, dpred = self._draft_model, self._draft_pred
        engine = self

        def build():
            fixed_key = jax.random.PRNGKey(0)
            def write_rowed(kv, scales, seq_kv, slot):
                for i, leaf in enumerate(seq_kv):
                    row = leaf[0]                      # [s, *trail]
                    if engine._int8:
                        q, r = _quantize_rows(row)
                        kv[i] = lax.dynamic_update_slice(
                            kv[i], q[None],
                            (slot,) + (0,) * (kv[i].ndim - 1))
                        scales[i] = lax.dynamic_update_slice(
                            scales[i], r[None], (slot, 0))
                    else:
                        kv[i] = lax.dynamic_update_slice(
                            kv[i], row[None].astype(kv[i].dtype),
                            (slot,) + (0,) * (kv[i].ndim - 1))
                return kv, scales

            def write_paged(kv, scales, seq_kv, pages):
                chunks = int(pages.shape[0])
                pad = chunks * pt - s
                for i, leaf in enumerate(seq_kv):
                    row = leaf[0]                      # [s, *trail]
                    if pad:
                        row = jnp.pad(row, ((0, pad),)
                                      + ((0, 0),) * (row.ndim - 1))
                    if engine._int8:
                        q, r = _quantize_rows(row)
                        qc = q.reshape((chunks, pt) + q.shape[1:])
                        rc = r.reshape((chunks, pt))
                        for j in range(chunks):
                            kv[i] = kv[i].at[pages[j]].set(qc[j])
                            scales[i] = scales[i].at[pages[j]].set(rc[j])
                    else:
                        rc = row.astype(kv[i].dtype).reshape(
                            (chunks, pt) + row.shape[1:])
                        for j in range(chunks):
                            kv[i] = kv[i].at[pages[j]].set(rc[j])
                return kv, scales

            def finish(carry_rest, tok, pos, active, rem, first, done0,
                       slot, n, max_new):
                tok = tok.at[slot].set(first)
                pos = pos.at[slot].set(n)
                active = active.at[slot].set(~done0)
                rem = rem.at[slot].set(max_new - 1)
                out = jnp.stack([first, done0.astype(jnp.int32)])
                return carry_rest + (tok, pos, active, rem), out

            def pure_rowed(carry, seq_kv, logits, slot, n, max_new):
                kv, scales, tok, pos, active, rem = carry
                first = jnp.argmax(jnp.asarray(logits[0, n - 1],
                                               jnp.float32)).astype(jnp.int32)
                done0 = (first == eos) | (max_new <= 1) | (n >= max_len)
                kv, scales = write_rowed(kv, scales, seq_kv, slot)
                (kv, scales, tok, pos, active, rem), out = finish(
                    (kv, scales), tok, pos, active, rem, first, done0,
                    slot, n, max_new)
                return (kv, scales, tok, pos, active, rem), out

            def pure_paged(carry, seq_kv, logits, pages, slot, n, max_new):
                kv, scales, tok, pos, active, rem = carry[:6]
                first = jnp.argmax(jnp.asarray(logits[0, n - 1],
                                               jnp.float32)).astype(jnp.int32)
                done0 = (first == eos) | (max_new <= 1) | (n >= max_len)
                kv, scales = write_paged(kv, scales, seq_kv, pages)
                (kv, scales, tok, pos, active, rem), out = finish(
                    (kv, scales), tok, pos, active, rem, first, done0,
                    slot, n, max_new)
                return ((kv, scales, tok, pos, active, rem) + carry[6:],
                        out)

            def pure_spec(carry, seq_kv, logits, toks, pages, slot, n,
                          max_new, ddatas, dranges):
                from ..gluon.block import _run_traced
                kv, scales, tok, pos, active, rem = carry[:6]
                dkv = list(carry[6])
                first = jnp.argmax(jnp.asarray(logits[0, n - 1],
                                               jnp.float32)).astype(jnp.int32)
                done0 = (first == eos) | (max_new <= 1) | (n >= max_len)
                kv, scales = write_paged(kv, scales, seq_kv, pages)
                dpds = dpred._traced_params(ddatas, dranges)

                def dbody():
                    return dmodel(NDArray(toks[None, :]))

                dout, _aux = _run_traced(dpred._params, dpds, fixed_key,
                                         False, dbody)
                for i, leaf in enumerate(dout[1:]):
                    row = leaf._data[0][None]          # [1, s, *trail]
                    dkv[i] = lax.dynamic_update_slice(
                        dkv[i], row.astype(dkv[i].dtype),
                        (slot,) + (0,) * (dkv[i].ndim - 1))
                (kv, scales, tok, pos, active, rem), out = finish(
                    (kv, scales), tok, pos, active, rem, first, done0,
                    slot, n, max_new)
                return ((kv, scales, tok, pos, active, rem, dkv), out)

            if spec:
                return pure_spec
            return pure_paged if pt else pure_rowed

        return self._build_jit("insert", s, build,
                               example_args=example_args)

    def compile_stats(self):
        """The watchdog's view of this engine's decode-cache compiles."""
        return telemetry.retrace_stats(self._site)

    # ------------------------------------------------------------- admission
    def submit(self, prompt, max_new=None, deadline_ms=None):
        """Admit one prompt (1-d int token ids). Returns a
        :class:`DecodeFuture` whose ``result()`` is the generated int32
        token array; sheds :class:`QueueFull` past the queue bound or
        the accountant's KV-residency budget."""
        trace = telemetry.new_trace()
        t0 = time.perf_counter()
        with telemetry.trace_handoff(trace), \
                telemetry.span("serving.submit"):
            seq = self._admit(prompt, max_new, deadline_ms, trace)
        telemetry.add_stage(trace, "serving.submit",
                            time.perf_counter() - t0)
        return seq.future

    def _admit(self, prompt, max_new, deadline_ms, trace):
        if self._kv_layout is None:
            # refuse at admission like start() does: a cold engine would
            # otherwise crash opaquely inside the insert jit on a None
            # carry at first poll
            raise MXNetError("submit on a cold DecodeEngine: warmup() "
                             "first (AOT replay needs its executables "
                             "before traffic)")
        prompt = np.asarray(prompt)
        if prompt.ndim != 1 or prompt.size < 1:
            raise MXNetError("submit: prompt must be a non-empty 1-d "
                             "token-id array, got shape %s"
                             % (tuple(prompt.shape),))
        if not np.issubdtype(prompt.dtype, np.integer):
            raise MXNetError("submit: prompt dtype %s is not integer "
                             "token ids" % prompt.dtype)
        prompt = prompt.astype(np.int32)
        self._prefill_spec.seq_bucket(prompt.size)  # loud past-max refusal
        if prompt.size >= self._max_len:
            raise MXNetError(
                "submit: prompt of %d tokens leaves no room to decode "
                "within max_len=%d" % (prompt.size, self._max_len))
        max_new = int(max_new if max_new is not None
                      else self._max_new_default)
        if max_new < 1:
            raise MXNetError("submit: max_new must be >= 1, got %d"
                             % max_new)
        now = self._clock()
        deadline = None if deadline_ms is None else now + deadline_ms / 1e3
        seq = _Sequence(prompt, max_new, deadline, now, trace)
        if trace is not None:
            # the trace identity rides the future from ADMISSION, not
            # delivery: a sequence failed by the wedge watchdog must be
            # correlatable with its flight-recorder artifact
            seq.future.trace_id = trace.trace_id
        with self._cond:
            if self._crashed:
                self._shed("worker_crashed")
            if self._draining or self._closed:
                self._shed("draining")
            if len(self._pending) >= self._max_queue:
                self._shed("queue_full")
            if self._acct is not None:
                # atomic check-and-ledger BEFORE the append, under the
                # admission lock: the loop thread can pop (and
                # occupy/unqueue) the sequence the instant the lock
                # releases, and a separate check would let concurrent
                # submits overshoot the overcommit bound. Paged mode
                # admits by real page headroom: the prompt's pages are
                # reserved here (exact, not worst-case rows) and decode
                # growth draws page-by-page later.
                need = 1 if not self._pt \
                    else -(-min(prompt.size + 1, self._max_len) // self._pt)
                if not self._acct.try_admit(self._tag, n=need):
                    self._shed("kv_residency")
                seq.reserved = need if self._pt else 0
            self._pending.append(seq)
            telemetry.gauge("serving.queue_depth",
                            len(self._pending))
            self._cond.notify_all()
        telemetry.inc("serving.requests")
        return seq

    def _shed(self, reason):
        telemetry.inc("serving.shed", tag=reason)
        raise QueueFull("request shed: %s" % reason)

    # --------------------------------------------------------------- serving
    def poll(self):
        """One engine cycle NOW (wedge scan -> slot admission -> one
        decode step) — the fake-clock test hook and the no-thread drive.
        Returns the number of decode steps executed (0 or 1)."""
        try:
            maybe_oom()  # fault kind 'oom': the decode-loop OOM site
            self._scan_wedges(self._clock())
            self._admit_pending()
            steps = self._step_once()
        except Exception as e:
            # an HBM OOM leaves the artifact here too (the no-thread
            # drive has no crash barrier); the raise stays loud either way
            self._flight_if_oom(e)
            raise
        with self._cond:
            self._cycles += 1
        return steps

    def _flight_if_oom(self, exc):
        """Flight-record a device allocator failure with the KV-cache
        accountant's residency view attached — which cohort/bucket ate
        the headroom is readable post-mortem."""
        from .. import xprof
        if xprof.is_oom(exc):
            xprof.oom_flight(
                "serving.decode", exc,
                extra={"kv": self._acct.snapshot()
                       if self._acct is not None else None})

    def _free_slot_locked(self):
        for i, s in enumerate(self._slots):
            if s is None:
                return i
        return None

    def _admit_pending(self):
        """Move queued prompts into free slots: prefill through the
        bucketed Predictor, then the device-side slot insert — between
        steps, never mid-step, and never with a recompile (the insert
        jit's slot index is traced). The continuous-batching half of the
        throughput story: a restart-per-batch engine
        (``continuous=False``) only refills once the WHOLE cohort
        drained — the idle-slot steps it burns are exactly the tokens/s
        gap serve_bench's decode gate measures."""
        filling = False
        while True:
            with self._cond:
                if not self._pending:
                    return
                if not self._continuous and self._live > 0 and not filling:
                    # restart-per-batch: a draining cohort admits nobody —
                    # but once it fully drains, the whole next cohort
                    # fills in one pass (filling stays True for the rest
                    # of this call)
                    return
                filling = True
                slot = self._free_slot_locked()
                if slot is None:
                    return
                seq = self._pending.popleft()
                self._inflight_seq = seq
                telemetry.gauge("serving.queue_depth", len(self._pending))
            try:
                now = self._clock()
                if seq.deadline is not None and now > seq.deadline:
                    telemetry.inc("serving.deadline_expired")
                    self._free_seq_ledger(seq, slotted=False)
                    self._fail(seq, DeadlineExceeded(
                        "deadline passed before a KV slot freed (queued "
                        "%.1f ms)" % ((now - seq.t_enq) * 1e3)))
                    continue
                telemetry.add_stage(seq.trace, "serving.queue_wait",
                                    max(0.0, now - seq.t_enq), event=True)
                try:
                    self._prefill_into(seq, slot)
                except Exception as e:  # noqa: BLE001 — complete, re-raise
                    # the popped sequence is in neither _pending nor
                    # _slots: without failing it HERE, the crash barrier
                    # would strand its future forever and leak its
                    # accountant queued count
                    if seq.slot is None and not seq.future.done():
                        self._free_seq_ledger(seq, slotted=False)
                        self._fail(seq, MXNetError(
                            "prefill failed: %s: %s"
                            % (type(e).__name__, e)))
                    raise
            finally:
                with self._cond:
                    self._inflight_seq = None

    def _prefill_into(self, seq, slot):
        """Prefill one prompt and insert its KV into ``slot``. The
        ``serving.prefill`` stage covers the bucketed prompt forward AND
        the insert dispatch; the first token's fetch is the
        ``serving.fetch`` d2h that makes TTFT a delivered fact, not a
        device promise."""
        n = int(seq.prompt.size)
        s_bucket = self._prefill_spec.seq_bucket(n)
        # pad HOST-side to the seq bucket: prompts arrive as host numpy
        # with arbitrary raw lengths, and an eager device-side pad would
        # compile one anonymous jnp.pad executable per distinct length —
        # exactly the shape churn the bucket discipline exists to kill
        prompt = seq.prompt if n == s_bucket else np.pad(
            seq.prompt, (0, s_bucket - n),
            constant_values=self._prefill_spec.pad_value)
        # paged mode: map the prompt's pages BEFORE any device work —
        # shared prefix chunks attach by refcount (never re-prefilled,
        # never re-stored), the rest come off the free list against this
        # sequence's admission reservation
        m_chunks = 0
        if self._pt:
            chunks = -(-n // self._pt)
            with self._cond:
                if self._prefix is not None:
                    m_chunks, pids = self._prefix.lookup(seq.prompt,
                                                         self._pt)
                    for pid in pids:
                        self._share_page_locked(seq, pid)
                ok = True
                while len(seq.pages) < chunks:
                    if self._take_page_locked(seq) is None:
                        ok = False
                        break
                if ok:
                    self._ptab[slot, :] = 0
                    self._ptab[slot, :len(seq.pages)] = seq.pages
                self._page_gauges_locked()
            if self._prefix is not None:
                if m_chunks:
                    telemetry.inc("serving.prefix.hits")
                else:
                    telemetry.inc("serving.prefix.misses")
            if not ok:
                # page pool exhausted at prefill: shed loud, exactly the
                # kv_residency degradation row — never a silent park
                telemetry.inc("serving.shed", tag="kv_residency")
                self._free_seq_ledger(seq, slotted=False)
                self._fail(seq, QueueFull(
                    "request shed: kv_residency (KV page pool exhausted "
                    "at prefill)"))
                return
        # the prefill/insert dispatch is device work on the SAME possibly-
        # wedged device the step loop replays: bracket it with its own
        # watchdog entry, or a wedge here would hang the loop thread with
        # no detection at all (the step watchdog only covers steps)
        p_entry = {"seq": seq, "deadline": self._clock() + self._timeout_s,
                   "done": False, "abandoned": False}
        with self._cond:
            self._prefill_armed = p_entry
        try:
            with telemetry.trace_handoff(seq.trace):
                t0 = time.perf_counter()
                # numpy scalars, NOT jnp — a jnp.int32() call is an eager
                # device op per argument, three per insert adds up
                if m_chunks:
                    # prefix HIT: the matched chunks already hold their
                    # KV — skip the Predictor prefill entirely and extend
                    # in-place from the first novel token
                    with self._cond:
                        ptab_row = self._ptab[slot].copy()
                    pd, pr = self._pred.param_args()
                    out, gen, superseded = self._dispatch_carry(
                        self._get_extend_jit(s_bucket), ptab_row,
                        prompt.astype(np.int32, copy=False),
                        np.int32(m_chunks * self._pt), np.int32(n),
                        np.int32(slot), np.int32(seq.max_new), pd, pr)
                else:
                    flat, _fmt, _b = self._pred.predict_flat(
                        (prompt[None, :],))
                    if not self._pt:
                        out, gen, superseded = self._dispatch_carry(
                            self._get_insert_jit(s_bucket),
                            [leaf._data for leaf in flat[1:]],
                            flat[0]._data, np.int32(slot), np.int32(n),
                            np.int32(seq.max_new))
                    else:
                        pages_arg = np.zeros(-(-s_bucket // self._pt),
                                             np.int32)
                        pages_arg[:len(seq.pages)] = seq.pages
                        if self._spec_k:
                            out, gen, superseded = self._dispatch_carry(
                                self._get_insert_jit(s_bucket),
                                [leaf._data for leaf in flat[1:]],
                                flat[0]._data,
                                prompt.astype(np.int32, copy=False),
                                pages_arg, np.int32(slot), np.int32(n),
                                np.int32(seq.max_new),
                                *self._draft_pred.param_args())
                        else:
                            out, gen, superseded = self._dispatch_carry(
                                self._get_insert_jit(s_bucket),
                                [leaf._data for leaf in flat[1:]],
                                flat[0]._data, pages_arg, np.int32(slot),
                                np.int32(n), np.int32(seq.max_new))
                if superseded:
                    # a wedge reset landed mid-insert: this prompt's KV
                    # went into the superseded carry — a wedge casualty,
                    # failed loud like the cohort it would have joined
                    self._fail_wedge_casualty(seq)
                    return
                telemetry.add_stage(seq.trace, "serving.prefill",
                                    time.perf_counter() - t0)
                t0 = time.perf_counter()
                with telemetry.span("serving.fetch", cat="sync"):
                    first_done = NDArray(out).asnumpy()
                telemetry.add_stage(seq.trace, "serving.fetch",
                                    time.perf_counter() - t0)
        finally:
            with self._cond:
                p_entry["done"] = True
                if self._prefill_armed is p_entry:
                    self._prefill_armed = None
        if seq.future.done():
            # a teardown (wedge trip, crash barrier, close) settled this
            # sequence while the device answered late: delivering or
            # touching the ledger again would double-count
            return
        seq.tokens.append(int(first_done[0]))
        ttft = self._clock() - seq.t_enq
        seq.future.ttft_s = ttft
        telemetry.observe("serving.ttft_s", ttft)
        telemetry.inc("serving.decode.tokens")
        if int(first_done[1]):
            # done at insert (eos / max_new==1): the slot was marked
            # inactive in-executable; deliver without ever stepping —
            # but the prompt's full chunks still publish to the prefix
            # cache (the cache pin keeps them alive past the deref)
            if self._pt:
                with self._cond:
                    self._register_prefix_locked(seq, m_chunks)
            self._free_seq_ledger(seq, slotted=False)
            self._deliver(seq)
            return
        with self._cond:
            if self._carry_gen != gen or self._closed or self._crashed \
                    or seq.future.done():
                # a reset/teardown landed AFTER the write-back but BEFORE
                # this registration — or the prefill watchdog already
                # failed this sequence: the fresh carry has
                # active[slot]=False (or the engine/future is gone), so
                # registering would park it forever or double-ledger it
                register = False
            else:
                register = True
                seq.slot = slot
                seq.pos = n
                self._slots[slot] = seq
                self._live += 1
                telemetry.gauge("serving.decode.slots", self._live)
                if self._pt:
                    # pages moved queued->live one at a time as they were
                    # taken; what's left is the prefix publication and the
                    # residency gauges
                    self._register_prefix_locked(seq, m_chunks)
                elif self._acct is not None:
                    # inside the lock: a reset landing right after
                    # registration must find the ledger already moved to
                    # live, so its straggler release balances exactly
                    self._acct.occupy(self._tag)
        if not register:
            self._fail_wedge_casualty(seq)
            return

    def _dispatch_carry(self, jitted, *args):
        """THE wedge-safe carry dispatch protocol (one copy, shared by
        the step and insert paths): snapshot carry + generation under the
        lock, dispatch OUTSIDE it — on a wedged tunnel even the dispatch
        can block (observed BENCH_r03-r05), and a blocked dispatch
        holding ``self._cond`` would deadlock every submit and the
        monitor's wedge scan, the exact moment it must run — then write
        the new carry back only if no wedge reset superseded the
        snapshot. Returns ``(emitted, gen, superseded)``; ``gen`` lets
        the caller re-check for resets landing after its own write-back
        (e.g. before slot registration)."""
        with self._cond:
            carry, gen = self._carry, self._carry_gen
        new_carry, out = jitted(carry, *args)
        with self._cond:
            superseded = self._carry_gen != gen
            if not superseded:
                self._carry = new_carry
        return out, gen, superseded

    def _step_once(self):
        """One decode step for the live cohort at its smallest covering
        capacity bucket: pure replay of the AOT executable (donated
        carry), zero d2h inside the armed ``serving.decode`` span; the
        one declared fetch (sampled tokens + done mask) follows in
        ``serving.fetch``; finished sequences free their slots before
        the next admission pass."""
        with self._cond:
            if self._live == 0:
                return 0
            prev = self._armed
            if prev is not None and not prev["done"] \
                    and not prev["abandoned"]:
                # a step is still in flight (a wedge in the making): a
                # new dispatch must NOT clobber its watchdog entry — the
                # unresolved entry would be discarded before it could
                # trip and the wedge would be swallowed silently
                return 0
            casualties = []
            if self._pt:
                # pre-step page allocation: every live sequence must have
                # a page mapped for each position this step writes (one,
                # or k+1 under speculation) BEFORE the dispatch — the
                # executable only gathers/scatters through the table it
                # is handed. Exhaustion shed a sequence loud; its table
                # row zeroes so the zombie slot's writes land on the
                # scratch page until the slot is re-inserted.
                t_step = 1 + self._spec_k
                for s in [x for x in self._slots if x is not None]:
                    hi_chunk = min(s.pos + t_step - 1,
                                   self._max_len - 1) // self._pt
                    ok = True
                    while len(s.pages) <= hi_chunk:
                        if self._take_page_locked(s) is None:
                            ok = False
                            break
                    if ok:
                        self._ptab[s.slot, :len(s.pages)] = s.pages
                    else:
                        self._ptab[s.slot, :] = 0
                        self._slots[s.slot] = None
                        s.slot = None
                        self._live -= 1
                        casualties.append(s)
                        # return the casualty's pages NOW, inside the
                        # pass — the next lane may need only one of
                        # them: shed the minimum, not every grower
                        # caught behind the same dry free list
                        self._free_seq_ledger(s, slotted=True)
                if casualties:
                    telemetry.gauge("serving.decode.slots", self._live)
                    self._page_gauges_locked()
            if self._live == 0:
                alive = False
            else:
                alive = True
                hi = max(i for i, s in enumerate(self._slots)
                         if s is not None) + 1
                b = self._decode_spec.slot_bucket(hi)
                live = [s for s in self._slots[:b] if s is not None]
                idx = self._step_index
                self._step_index += 1
                entry = {"live": live, "idx": idx, "done": False,
                         "abandoned": False,
                         "deadline": self._clock() + self._timeout_s}
                self._armed = entry
                ptab_snap = self._ptab.copy() if self._pt else None
        for s in casualties:
            # pages already came home inside the allocation pass — only
            # the shed accounting and the loud failure happen here
            telemetry.inc("serving.shed", tag="kv_residency")
            self._fail(s, QueueFull(
                "request shed: kv_residency (KV page pool exhausted "
                "mid-decode)"))
        if not alive:
            return 0
        lead = live[0]
        with telemetry.trace_handoff(lead.trace):
            t0 = time.perf_counter()
            wedged = inject("decode_wedge", idx)
            if not wedged:
                with telemetry.span("serving.decode", d2h=True):
                    if self._spec_k:
                        emitted, _gen, _sup = self._dispatch_spec(
                            b, ptab_snap)
                    elif self._pt:
                        emitted, _gen, _sup = self._dispatch_carry(
                            self._get_step_jit(b), ptab_snap,
                            *self._pred.param_args())
                    else:
                        emitted, _gen, _sup = self._dispatch_carry(
                            self._get_step_jit(b),
                            self._pred._param_datas,
                            self._pred._param_ranges)
            dt = time.perf_counter() - t0
            for s in live:
                telemetry.add_stage(s.trace, "serving.decode", dt)
            if wedged:
                # simulated wedge: the device "never answers" — the entry
                # stays armed and the watchdog scan (monitor thread, or
                # the next poll under a fake clock) trips it
                return 1
            t0 = time.perf_counter()
            with telemetry.span("serving.fetch", cat="sync"):
                if self._spec_k:
                    packed = NDArray(emitted).asnumpy()
                    toks = packed[:, :self._spec_k + 1]
                    counts = packed[:, self._spec_k + 1]
                    done = packed[:, self._spec_k + 2]
                else:
                    toks = NDArray(emitted[0]).asnumpy()
                    counts = None
                    done = NDArray(emitted[1]).asnumpy()
            dt = time.perf_counter() - t0
            for s in live:
                telemetry.add_stage(s.trace, "serving.fetch", dt)
        with self._cond:
            stale = entry["abandoned"]
            entry["done"] = True
            if self._armed is entry:
                self._armed = None
        if stale:
            # the wedge watchdog already failed this cohort and reset the
            # carry — a late answer must not resurrect freed slots, skew
            # the replay counter, or leave superseded-carry logits in the
            # diagnostic probe hook
            return 1
        if self._spec_k:
            # accept-rate accounting: each live lane verified k proposals
            # and committed counts-1 of them (the +1 is the free token
            # the verify pass itself produces)
            telemetry.inc("serving.decode.spec_proposed",
                          self._spec_k * len(live))
            telemetry.inc("serving.decode.spec_accepted",
                          int(sum(max(0, int(counts[s.slot]) - 1)
                                  for s in live)))
            self._last_logits = None
        else:
            self._last_logits = emitted[2]
        telemetry.inc("serving.decode.steps")
        self._harvest(live, toks, done, counts)
        return 1

    def _dispatch_spec(self, b, ptab_snap):
        """One speculative macro-step: the draft proposes k tokens
        (rowed draft KV inside the carry), the target verifies the whole
        chain in one paged executable — two dispatches replace k+1,
        and the commit rule keeps the emitted stream bit-identical to
        plain greedy. Composed INSIDE one carry write-back so a wedge
        reset between the halves supersedes both."""
        draft_fn = self._get_draft_jit(b)
        verify_fn = self._get_verify_jit(b)

        def composed(carry, ptab, dpd, dpr, pd, pr):
            carry, props = draft_fn(carry, dpd, dpr)
            return verify_fn(carry, ptab, props, pd, pr)

        return self._dispatch_carry(
            composed, ptab_snap,
            *self._draft_pred.param_args(), *self._pred.param_args())

    def _harvest(self, live, toks, done, counts=None):
        finished = []
        with self._cond:
            for seq in live:
                slot = seq.slot
                if counts is None:
                    seq.tokens.append(int(toks[slot]))
                    telemetry.inc("serving.decode.tokens")
                    seq.pos += 1
                else:
                    c = int(counts[slot])
                    for i in range(c):
                        seq.tokens.append(int(toks[slot][i]))
                    telemetry.inc("serving.decode.tokens", c)
                    seq.pos += c
                if done[slot]:
                    finished.append(seq)
                    self._slots[slot] = None
                    if self._pt:
                        self._ptab[slot, :] = 0
                    seq.slot = None
                    self._live -= 1
            telemetry.gauge("serving.decode.slots", self._live)
            if self._pt:
                self._page_gauges_locked()
            if finished:
                self._cond.notify_all()
        for seq in finished:
            self._free_seq_ledger(seq, slotted=True)
            self._deliver(seq)

    def _deliver(self, seq):
        done = self._clock()
        t0 = time.perf_counter()
        with telemetry.trace_handoff(seq.trace), \
                telemetry.span("serving.deliver"):
            seq.future._value = np.asarray(seq.tokens, np.int32)
        telemetry.add_stage(seq.trace, "serving.deliver",
                            time.perf_counter() - t0)
        if seq.trace is not None:
            seq.future.trace_id = seq.trace.trace_id
            seq.future.breakdown = telemetry.trace_breakdown(seq.trace)
            seq.future.e2e_s = done - seq.t_enq
        seq.future._event.set()
        telemetry.observe("serving.latency_s", done - seq.t_enq)

    @staticmethod
    def _fail(seq, error):
        seq.future._error = error
        seq.future._event.set()

    def _fail_wedge_casualty(self, seq):
        """Fail a mid-insert sequence whose carry was reset out from
        under it (one copy for the write-back and registration checks —
        the ledger call and the message must never diverge)."""
        if seq.future.done():
            return
        self._free_seq_ledger(seq, slotted=False)
        self._fail(seq, DeadlineExceeded(
            "cohort reset by the wedge watchdog during this prompt's "
            "slot insert"))

    def _collect_teardown_locked(self):
        """Under ``self._cond``: collect EVERY unfinished sequence —
        pending, slotted, and the popped-but-unregistered in-flight one
        — clear the slot table and the armed entry, and return
        ``(seqs, slotted_ids)``. One copy of the ledger-critical sweep
        shared by the crash barrier and close(): the release-vs-unqueue
        split and the slot-nulling must never diverge between them."""
        dead = list(self._pending) + [s for s in self._slots
                                      if s is not None]
        slotted = {id(s) for s in self._slots if s is not None}
        if self._inflight_seq is not None:
            dead.append(self._inflight_seq)
            self._inflight_seq = None
        self._pending.clear()
        for s in dead:
            # a later scan/harvest must never see a freed sequence as
            # still slotted (double-release, negative live count)
            s.slot = None
        self._slots = [None] * self._capacity
        self._live = 0
        if self._armed is not None:
            self._armed["abandoned"] = True
            self._armed = None
        if self._prefill_armed is not None:
            self._prefill_armed["abandoned"] = True
            self._prefill_armed = None
        # a late write-back / slot registration / done-at-insert from a
        # thread that resumes after this teardown must see the carry as
        # superseded — the sequences it would touch are failed HERE
        self._carry_gen += 1
        if self._pt:
            # the prefix cache's pins die with the cohort: the teardown
            # invalidated the device pages they point at, and a stale
            # entry surviving here would hand a future prompt garbage KV
            if self._prefix is not None:
                for pid in self._prefix.drain():
                    self._decref_locked(pid)
            self._ptab[:, :] = 0
            self._page_gauges_locked()
        self._cond.notify_all()
        return dead, slotted

    def _fail_collected(self, dead, slotted, err):
        for seq in dead:
            if seq.future.done():
                continue  # e.g. the in-flight seq a racing path handled
            self._free_seq_ledger(seq, id(seq) in slotted)
            self._fail(seq, err)

    # ------------------------------------------------------- wedge watchdog
    def _check_probation(self, now):
        """After a wedge trip in THREADED mode the loop thread may be
        genuinely blocked inside the wedged device call — the one thread
        that serves the queue. Probation gives it one full timeout window
        to make loop progress; no progress means blocked-forever, and
        shed-never-hang demands the crash barrier: fail the pending
        queue loud, refuse new submits. (An injected wedge's loop thread
        keeps cycling, so probation clears and serving resumes.)"""
        with self._cond:
            prob = self._probation
            if prob is None:
                return
            deadline, cycles0 = prob
            if self._cycles != cycles0:
                self._probation = None   # loop progressed: recovered
                return
            if now < deadline:
                return
            self._probation = None
        self._worker_crashed(RuntimeError(
            "decode loop made no progress for %.0f ms after a wedge "
            "trip — blocked inside the wedged device call"
            % (self._timeout_s * 1e3)))

    @staticmethod
    def _entry_due(entry, now):
        return entry is not None and not entry["done"] \
            and not entry["abandoned"] and now >= entry["deadline"]

    def _scan_wedges(self, now):
        """A dispatch with no answer past the timeout is a wedged device:
        a STEP wedge kills its slot cohort, a PREFILL/insert wedge kills
        the in-flight prompt (and, since the same device carries the
        cohort, everything slotted falls to the straggler sweep below).
        Either way the stuck sequences fail LOUD (their futures raise,
        their trace_ids land in the ``decode_wedge`` flight artifact) and
        the carry re-allocates — the device state that never answered is
        unrecoverable, the queue is not."""
        self._check_probation(now)
        with self._cond:
            entry = self._armed
            if self._entry_due(entry, now):
                entry["abandoned"] = True
                self._armed = None
                kind, idx = "step", entry["idx"]
                stuck = list(entry["live"])    # slotted: acct release
                queued_stuck = []
            else:
                entry = self._prefill_armed
                if not self._entry_due(entry, now):
                    return
                entry["abandoned"] = True
                self._prefill_armed = None
                kind, idx = "prefill", -1
                stuck = []
                queued_stuck = [entry["seq"]]  # never slotted: unqueue
                # settle the casualty ATOMICALLY with the abandonment: a
                # late-completing prefill on the loop thread checks
                # future.done() under this same lock, so the ledger
                # moves exactly once (failing it after the flight IO
                # below would leave a window to register/deliver AND be
                # unqueued — a double decrement)
                seq = entry["seq"]
                if not seq.future.done():
                    self._free_seq_ledger(seq, slotted=False)
                    self._fail(seq, DeadlineExceeded(
                        "decode prefill dispatch wedged: no device "
                        "answer within %.0f ms" % (self._timeout_s * 1e3)))
            for seq in stuck:
                if seq.slot is not None:
                    self._slots[seq.slot] = None
                    seq.slot = None
                    self._live -= 1
            telemetry.gauge("serving.decode.slots", self._live)
        telemetry.inc("serving.decode.wedges")
        _log.warning(
            "serving: decode %s dispatch %d wedged (no answer in %.0f ms)"
            " — failing %d stuck sequence(s), resetting the cohort carry",
            kind, idx, self._timeout_s * 1e3,
            len(stuck) + len(queued_stuck))
        telemetry.flight_record(
            "decode_wedge",
            trace_ids=[s.trace.trace_id for s in stuck + queued_stuck
                       if s.trace is not None],
            extra={"kind": kind, "step": idx, "engine": self._name,
                   "stuck": len(stuck) + len(queued_stuck),
                   "timeout_ms": self._timeout_s * 1e3})
        err = DeadlineExceeded(
            "decode %s dispatch wedged: no device answer within %.0f ms"
            % (kind, self._timeout_s * 1e3))
        for seq in stuck:
            telemetry.trace_mark(seq.trace, "serving.wedged")
            self._free_seq_ledger(seq, slotted=True)
            self._fail(seq, err)
        for seq in queued_stuck:
            telemetry.trace_mark(seq.trace, "serving.wedged")
            if not seq.future.done():
                self._free_seq_ledger(seq, slotted=False)
                self._fail(seq, err)
        with self._cond:
            # the reset kills the WHOLE cohort device state: any live
            # slot not in the armed entry (none under the single-driver
            # model, but defensive) loses its KV too — fail it rather
            # than leave it silently pointing at zeroed cache
            stragglers = [s for s in self._slots if s is not None]
            self._slots = [None] * self._capacity
            self._live = 0
            telemetry.gauge("serving.decode.slots", 0)
            self._carry = self._alloc_carry()
            self._carry_gen += 1
            if self._pt:
                # the fresh carry's pages are zeroed device-side: drop
                # the prefix cache's pins (stale KV must never be shared
                # into a future prompt) and unmap every table row; the
                # stuck/straggler sequences still hold their page refs —
                # each _free_seq_ledger below returns them, so the free
                # list balances without a wholesale rebuild
                if self._prefix is not None:
                    for pid in self._prefix.drain():
                        self._decref_locked(pid)
                self._ptab[:, :] = 0
                self._page_gauges_locked()
            if self._thread is not None and self._thread.is_alive():
                # threaded mode: the loop thread may be BLOCKED in the
                # wedged device call — give it one timeout window to
                # prove otherwise (see _check_probation)
                self._probation = (now + self._timeout_s, self._cycles)
            self._cond.notify_all()
        for seq in stragglers:
            self._free_seq_ledger(seq, slotted=True)
            self._fail(seq, err)

    # ---------------------------------------------------------------- worker
    def start(self):
        """Run the engine on a background loop thread + wedge monitor
        (the threaded twin of :meth:`poll`). Returns self."""
        if self._thread is not None:
            return self
        if self._kv_layout is None:
            raise MXNetError("DecodeEngine.start on a cold engine: "
                             "warmup() first (AOT replay needs its "
                             "executables before traffic)")
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="mxtpu-serving-decode")
        self._thread.start()
        interval = max(0.005, min(0.25, self._timeout_s / 4))
        self._monitor = threading.Thread(
            target=self._monitor_loop, args=(interval,), daemon=True,
            name="mxtpu-serving-decode-monitor")
        self._monitor.start()
        return self

    def _loop(self):
        try:
            while True:
                with self._cond:
                    while not self._pending and self._live == 0 \
                            and not self._closed:
                        self._cond.wait(0.25)
                    if self._closed and not self._pending \
                            and self._live == 0:
                        return
                self._admit_pending()
                maybe_oom()  # fault kind 'oom': the decode-loop OOM site
                stepped = self._step_once()
                with self._cond:
                    # loop-progress heartbeat: what probation watches to
                    # tell a cycling thread from one blocked in a wedged
                    # device call
                    self._cycles += 1
                    if not stepped and self._live > 0:
                        # live cohort but no step ran (unresolved armed
                        # entry): park briefly instead of spinning until
                        # the watchdog resolves it
                        self._cond.wait(0.005)
        except Exception as e:  # noqa: BLE001 — crash barrier (PR-8)
            # HBM exhaustion in the decode loop: artifact (ledger +
            # per-device memory stats + accountant view) first, then the
            # crash barrier fails every pending future LOUD (no hangs)
            self._flight_if_oom(e)
            self._worker_crashed(e)

    def _monitor_loop(self, interval):
        while not self._stop.is_set():
            self._scan_wedges(self._clock())
            with self._cond:
                if self._closed and not self._pending and self._live == 0:
                    return
            self._stop.wait(interval)

    def _worker_crashed(self, exc):
        """The decode loop died on an unexpected exception: fail every
        pending and live future loud (their worker is gone) and refuse
        new submits — the MicroBatcher crash-barrier discipline."""
        telemetry.inc("serving.worker_crashes")
        _log.error("serving decode loop crashed (%s: %s) — failing queued "
                   "futures and refusing new submits",
                   type(exc).__name__, exc)
        err = MXNetError("serving decode loop crashed: %s: %s"
                         % (type(exc).__name__, exc))
        with self._cond:
            self._crashed = True
            dead, slotted = self._collect_teardown_locked()
        telemetry.flight_record(
            "worker_crash",
            trace_ids=[s.trace.trace_id for s in dead
                       if s.trace is not None],
            extra={"engine": self._name,
                   "error": "%s: %s" % (type(exc).__name__, exc)})
        self._fail_collected(dead, slotted, err)

    def drain(self, timeout=None):
        """Stop admitting (submits shed ``draining``), finish pending +
        live sequences. With no loop thread, outstanding work drains
        synchronously through :meth:`poll` (deadline measured on the
        injected clock). Returns True when empty."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()
        deadline = None if timeout is None else self._clock() + timeout
        while True:
            alive = self._thread is not None and self._thread.is_alive()
            if not alive:
                while self.poll():
                    pass
                self._admit_pending()
            with self._cond:
                if not self._pending and self._live == 0 \
                        and self._inflight_seq is None:
                    return True
                if deadline is not None and self._clock() > deadline:
                    return False
                if not alive:
                    return False
                self._cond.wait(0.05)

    def close(self, timeout=5.0):
        """Drain, then stop the loop + monitor threads. Anything still
        pending after the drain deadline fails loud rather than hanging
        its callers."""
        self.drain(timeout=timeout)
        with self._cond:
            self._closed = True
            self._draining = True
            self._cond.notify_all()
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
        if self._monitor is not None:
            self._monitor.join(timeout)
        # sweep AFTER the joins: only then can no loop iteration race the
        # collection, and a popped-but-unregistered in-flight sequence (a
        # loop thread killed mid-prefill) is caught too instead of
        # leaving its future hanging forever
        with self._cond:
            leftovers, slotted = self._collect_teardown_locked()
        self._fail_collected(leftovers, slotted,
                             DeadlineExceeded("engine closed before "
                                              "completion"))
        return self

    # ------------------------------------------------------------ diagnostics
    def prefill_logits(self, prompt):
        """Diagnostic: the prompt's last-position logits as numpy — the
        int8-vs-f32 logits-parity gate's probe (serve_bench decode mode,
        tests). NOT a serving path: it fetches device output directly."""
        prompt = np.asarray(prompt, np.int32)
        flat, _fmt, _b = self._pred.predict_flat((prompt[None, :],))
        return np.asarray(flat[0]._data[0, prompt.size - 1])

    def step_logits_probe(self, prompt):
        """Diagnostic: prefill + insert into slot of a FRESH probe engine
        state, run one decode step, and return that step's logits row —
        the KV-path half of the int8 parity gate. Uses the engine's real
        executables (the loop's own ``_last_logits`` output, which the
        serving path never fetches), so the probe measures exactly what
        production replays. Serialized against the loop: do not call
        under live traffic."""
        fut = self.submit(prompt, max_new=2)
        for _ in range(64):
            if fut.done():
                break
            self.poll()
        if self._last_logits is None:
            raise MXNetError("step_logits_probe: no decode step ran "
                             "(prompt finished at insert?)")
        out = np.asarray(self._last_logits[0])
        fut.result(timeout=5.0)
        return out
