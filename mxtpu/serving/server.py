"""Load-shedding HTTP model server over the Predictor + MicroBatcher.

The reference's deployment story ends at the C predict API; the ROADMAP's
north star is "serves heavy traffic from millions of users", which needs
the three behaviours every production front has and no notebook loop
does:

* **admission control** — a full queue answers 503 *now* (the
  ``serving.shed`` counter, by reason) instead of letting tail latency
  grow without bound;
* **observability** — ``/metrics`` returns ``telemetry.snapshot()``
  (counters, latency histograms, the ``serving.predict`` retrace-watchdog
  state) so the box is debuggable from the outside;
* **graceful drain** — SIGTERM (the preemption signal, same discipline
  as :class:`mxtpu.resilience.ResilientLoop`) flips the server to
  draining: new work is rejected with 503, queued + in-flight batches
  finish and deliver their responses, then the listener can be closed.

Stdlib-threaded (``http.server.ThreadingHTTPServer``) on purpose: one
request-handler thread parks per in-flight request while the single
batcher worker owns all device dispatch, so concurrency never reaches
jax. JSON in/out; this is the reference-quality front (and the thing
load-balancers health-check), not a gRPC replacement.

Endpoints::

    POST /predict   {"data": [[...], ...], "deadline_ms": 250,
                     "priority": "interactive"|"batch"}
                    -> 200 {"outputs": [...], "n": k, "trace_id": ...,
                            "e2e_ms": ..., "breakdown_ms": {stage: ms}}
                       (trace fields present while MXTPU_TRACE is on; the
                       stages sum to ~e2e_ms — queue wait vs pad vs device
                       vs fetch attribution per request)
                    -> 503 shed/draining (+ a Retry-After header from the
                       controller's predicted drain time when the SLO
                       control plane is attached), 504 deadline, 400 bad
                       request
    GET  /healthz   {"status": "ok"|"degraded"|"unhealthy"|"draining",
                     "queue_depth": d, "replicas": [...],
                     "controller": {...}}  (replica fields only when
                    serving through a ReplicaDispatcher; the controller
                    block — replica target vs actual, per-class queue
                    depths, last scale decision + reason — only with a
                    ServingController attached)
    GET  /metrics   telemetry.snapshot() as JSON; with ``Accept:
                    text/plain`` (a stock Prometheus scraper) the same
                    registry in Prometheus text exposition format
"""
from __future__ import annotations

import json
import logging
import signal
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from .. import telemetry
from .batcher import DeadlineExceeded, MicroBatcher, QueueFull
from .replicas import ReplicaDispatcher, ReplicaSet

__all__ = ["ModelServer"]

_log = logging.getLogger("mxtpu.serving")


class ModelServer:
    """HTTP front for a :class:`~mxtpu.serving.batcher.MicroBatcher` (or a
    bare Predictor, which gets a default batcher). ``port=0`` picks a free
    port (tests); ``server.address`` is the bound (host, port)."""

    def __init__(self, batcher, host="127.0.0.1", port=0,
                 request_timeout_s=30.0):
        from .zoo import ZooScheduler
        self._zoo = None
        if isinstance(batcher, ZooScheduler):
            # multi-model front: requests route by the body's "model"
            # field through the zoo's placement/canary machinery
            self._zoo = batcher
        elif isinstance(batcher, ReplicaSet):
            batcher = ReplicaDispatcher(batcher)
        elif not isinstance(batcher, MicroBatcher):
            batcher = MicroBatcher(batcher)
        self._batcher = batcher
        self._timeout = float(request_timeout_s)
        self._httpd = ThreadingHTTPServer((host, port), _make_handler(self))
        self._httpd.daemon_threads = True
        self._thread = None
        self._drain_thread = None
        self._prev_handlers = {}
        self.draining = False

    @property
    def address(self):
        return self._httpd.server_address

    @property
    def batcher(self):
        return self._batcher

    # ---------------------------------------------------------------- running
    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, kwargs={"poll_interval": 0.05},
                daemon=True, name="mxtpu-serving-http")
            self._thread.start()
        return self

    def serve_forever(self):
        """Foreground mode (a real deployment's main thread)."""
        self.install_signal_handlers()
        self._httpd.serve_forever(poll_interval=0.05)

    # ------------------------------------------------------------------ drain
    def install_signal_handlers(self, signals=(signal.SIGTERM,)):
        """SIGTERM -> graceful drain (main thread only; off it python
        refuses handlers — call :meth:`begin_drain` yourself there, the
        ResilientLoop degradation)."""
        try:
            for sig in signals:
                self._prev_handlers[sig] = signal.signal(sig, self._on_signal)
        except ValueError:
            _log.warning("ModelServer: cannot install signal handlers off "
                         "the main thread; call begin_drain() on shutdown")
        return self

    def uninstall_signal_handlers(self):
        for sig, prev in self._prev_handlers.items():
            signal.signal(sig, prev)
        self._prev_handlers = {}

    def _on_signal(self, signum, frame):
        # the handler does the MINIMUM: flip the flag, hand the actual
        # drain (IO, locks, device syncs) to a worker thread
        self.draining = True
        telemetry.inc("serving.drains")
        t = threading.Thread(target=self._drain_with_flight, daemon=True,
                             name="mxtpu-serving-drain")
        self._drain_thread = t
        t.start()

    def _drain_with_flight(self):
        # SIGTERM is a flight-recorder trigger: snapshot the in-flight
        # traces + thread stacks BEFORE the drain tears the state down
        # (the dump is on this worker thread — the signal handler itself
        # stays IO-free). No-op unless MXTPU_FLIGHT_DIR is set.
        telemetry.flight_record("sigterm",
                                extra={"queue_depth":
                                       self._batcher.queue_depth})
        # final-flush guarantee (ISSUE 19): a SIGTERM'd server's last
        # buffered telemetry window must reach the JSONL sink before the
        # drain tears everything down
        telemetry.flush()
        self.begin_drain()

    def begin_drain(self, timeout=None):
        """Reject new work, finish queued + in-flight batches. The
        listener stays up (503 + ``/healthz`` "draining") until
        :meth:`close` — load balancers need the endpoint alive to observe
        the drain. Returns True when fully drained."""
        self.draining = True
        return self._batcher.drain(timeout=timeout)

    def close(self, timeout=5.0):
        """Drain, stop the batcher worker, stop the listener."""
        self.begin_drain(timeout=timeout)
        self._batcher.close(timeout=timeout)
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        self.uninstall_signal_handlers()
        return self

    # ---------------------------------------------------------------- request
    def _retry_after(self):
        """503 ``Retry-After`` seconds: the attached controller's
        predicted queue-drain time (the per-bucket latency model), 1 s
        when serving without a control plane — a shed response always
        tells the client WHEN to come back, never just that it failed."""
        ctrl = getattr(self._batcher, "_controller", None)
        if ctrl is not None:
            try:
                return ctrl.retry_after_s()
            except Exception:  # noqa: BLE001 — a header, not control flow
                pass
        return 1

    def _handle_predict(self, body):
        """Returns (status, payload-dict, extra-headers-or-None). Runs on
        the handler thread — it parks on the future while the batcher
        coalesces."""
        from ..base import MXNetError
        if self.draining:
            telemetry.inc("serving.shed", tag="draining")
            return 503, {"error": "draining"}, \
                {"Retry-After": str(self._retry_after())}
        raw = body.get("inputs")
        if raw is None:
            raw = [body.get("data")]
        if not raw or raw[0] is None:
            return 400, {"error": "missing 'data' (or 'inputs') field"}, None
        model = version = None
        if self._zoo is not None:
            # multi-model routing: the body names the model (404 with
            # the registry's known names — a typo'd model must read as
            # "no such model", never as a server fault) and optionally
            # pins a version (404 with that model's known versions)
            reg = self._zoo.registry
            model = body.get("model")
            if not model:
                return 400, {"error": "missing 'model' field",
                             "known_models": reg.models()}, None
            if model not in reg.models():
                return 404, {"error": "unknown model %r" % model,
                             "known_models": reg.models()}, None
            version = body.get("version")
            if version is not None and version not in reg.versions(model):
                return 404, {"error": "unknown version %r of model %r"
                             % (version, model),
                             "known_versions": reg.versions(model)}, None
            templates = self._zoo.input_templates(model)
        else:
            templates = getattr(self._batcher._pred, "input_templates",
                                None)
        arrays = []
        for i, a in enumerate(raw):
            dtype = None
            if templates is not None and i < len(templates):
                dtype = templates[i][1]
            try:
                arrays.append(np.asarray(a, dtype=dtype))
            except (ValueError, TypeError) as e:  # ragged/unconvertible JSON
                return 400, {"error": "input %d not array-shaped: %s"
                             % (i, e)}, None
        try:
            # default the batcher deadline to the handler timeout: once the
            # handler answers 504 and walks away, the queued request would
            # otherwise still dispatch and burn a device slot on an answer
            # nobody is waiting for — exactly under the overload that made
            # it time out
            deadline_ms = body.get("deadline_ms", self._timeout * 1e3)
            if self._zoo is not None:
                fut = self._zoo.submit(model, tuple(arrays),
                                       tenant=body.get("tenant"),
                                       deadline_ms=deadline_ms,
                                       priority=body.get("priority"),
                                       version=version)
            else:
                fut = self._batcher.submit(tuple(arrays),
                                           deadline_ms=deadline_ms,
                                           priority=body.get(
                                               "priority", "interactive"))
            out = fut.result(timeout=self._timeout)
        except QueueFull as e:
            # the shed path tells the client when to retry: the
            # controller's estimated drain time (predictive model), not
            # a bare error
            return 503, {"error": str(e)}, \
                {"Retry-After": str(self._retry_after())}
        except DeadlineExceeded as e:
            return 504, {"error": str(e)}, None
        except MXNetError as e:
            # submit's request-shape refusals (empty batch, > max_batch,
            # seq past the largest bucket, unknown priority): the
            # CLIENT's fault, not a 500 — monitoring treats 5xx as server
            # faults and would page/eject a healthy instance over one
            # misbehaving caller
            return 400, {"error": str(e)}, None
        outs = list(out) if isinstance(out, tuple) else [out]
        payload = {"outputs": [o.tolist() for o in outs],
                   "n": int(arrays[0].shape[0])}
        if fut.trace_id is not None:
            # the request's causal identity + latency attribution: stages
            # sum to ~e2e_ms (serve_bench's closed-loop 5% gate), and the
            # trace_id matches the flight-recorder artifact should this
            # request's dispatch have wedged
            payload["trace_id"] = fut.trace_id
            payload["e2e_ms"] = round(fut.e2e_s * 1e3, 3)
            payload["breakdown_ms"] = {
                k: round(v * 1e3, 4)
                for k, v in sorted(fut.breakdown.items())}
        return 200, payload, None


def _make_handler(srv):
    class Handler(BaseHTTPRequestHandler):
        server_version = "mxtpu-serving/1"
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # stdout silence; debug-level log
            _log.debug("http %s", fmt % args)

        def _reply(self, code, payload, headers=None):
            body = json.dumps(payload, default=str).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                payload = {
                    "status": "draining" if srv.draining else "ok",
                    "queue_depth": srv._batcher.queue_depth}
                states = getattr(srv._batcher, "replica_states", None)
                if callable(states):
                    # replicated serving: per-replica health so a load
                    # balancer (and a human) can see partial capacity —
                    # "degraded" = serving, but with quarantined replicas
                    reps = states()
                    payload["replicas"] = reps
                    healthy = sum(1 for r in reps
                                  if r["state"] == "healthy")
                    payload["healthy_replicas"] = healthy
                    if not srv.draining and healthy < len(reps):
                        payload["status"] = ("degraded" if healthy
                                             else "unhealthy")
                acct = getattr(getattr(srv._batcher, "replica_set", None),
                               "accountant", None)
                if acct is not None:
                    # KV residency per replica pool: the signal a fleet
                    # dispatcher routes/sheds on (docs/serving.md decode)
                    payload["kv"] = acct.snapshot()
                if srv._zoo is not None:
                    # the zoo block: per-model residency, live versions,
                    # canary state, per-tenant attainment — the
                    # operator's one-look answer to "what is resident
                    # where, and how is each tenant doing"
                    payload["zoo"] = srv._zoo.view()
                ctrl = getattr(srv._batcher, "_controller", None)
                if ctrl is not None:
                    # the control-plane view: replica target vs actual,
                    # per-class queue depths, last scale decision +
                    # reason — the operator's one-look answer to "what
                    # is the autoscaler doing and why"
                    payload["controller"] = ctrl.view()
                self._reply(200, payload)
            elif self.path == "/metrics":
                accept = self.headers.get("Accept", "")
                if "text/plain" in accept or "openmetrics" in accept:
                    # content-negotiated Prometheus text exposition: a
                    # stock scraper (which sends text/plain in Accept)
                    # gets the standard format; everything else keeps
                    # the structured JSON snapshot
                    body = telemetry.prometheus().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4; "
                                     "charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self._reply(200, telemetry.snapshot())
            else:
                self._reply(404, {"error": "unknown path %s" % self.path})

        def do_POST(self):
            if self.path != "/predict":
                self._reply(404, {"error": "unknown path %s" % self.path})
                return
            try:
                length = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(length) or b"{}")
            except (ValueError, TypeError) as e:
                self._reply(400, {"error": "bad json: %s" % e})
                return
            try:
                code, payload, headers = srv._handle_predict(body)
            except Exception as e:  # noqa: BLE001 — a handler crash must
                _log.exception("predict handler failed")  # answer, not hang
                code, payload, headers = 500, {"error": "%s: %s"
                                               % (type(e).__name__, e)}, None
            self._reply(code, payload, headers)

    return Handler
