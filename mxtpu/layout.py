"""Global convolution-layout scope: run any model channels-last with one line.

The reference is NCHW-only (src/operator/nn/convolution.cc checks layout
kNCW/kNCHW/kNCDHW); every gluon layer and model-zoo net hardcodes that
default. On TPU the preferred layout is channels-last — the C dimension
vectorizes onto the 8x128 VPU lanes and feeds the MXU without relayouts —
so instead of threading a ``layout=`` kwarg through every zoo constructor
(invasive, and the reference API has no such parameter), mxtpu provides a
scope that flips the *default* layout read by Conv/Pool/BatchNorm layers at
construction time:

    with mx.layout("NHWC"):
        net = vision.resnet50_v1()
    net.initialize()
    net(x_nhwc)

Explicit ``layout=``/``axis=`` arguments always win over the scope. The
scope affects layer construction only — an already-built block is fixed.
Parameters are stored in the layout-native shape (HWIO for channels-last
convs), which is also what feeds ``lax.conv_general_dilated`` with zero
relayout ops.
"""
from __future__ import annotations

import threading

from .base import MXNetError

__all__ = ["layout", "current_layout", "conv_layout", "channel_axis",
           "is_channels_last"]

_state = threading.local()

_CHANNELS_LAST = {1: "NWC", 2: "NHWC", 3: "NDHWC"}
_CHANNELS_FIRST = {1: "NCW", 2: "NCHW", 3: "NCDHW"}


class layout:
    """Context manager / global setter for the default conv-family layout.

    ``with layout("NHWC"): ...`` makes channels-last the default for every
    Conv*/Pool*/BatchNorm constructed in the scope and restores the previous
    default on exit; a bare ``layout("NHWC")`` call sets it globally (like
    the reference's process-wide env toggles). ``"NCHW"`` /
    ``"channels_first"`` restores the reference default. Any family name
    (NWC/NHWC/NDHWC) selects the whole channels-last family — a Conv1D
    built under ``layout("NHWC")`` is NWC.
    """

    def __init__(self, name):
        name = str(name)
        if name in ("channels_last",) or name in _CHANNELS_LAST.values():
            last = True
        elif name in ("channels_first",) or name in _CHANNELS_FIRST.values():
            last = False
        else:
            raise MXNetError(
                "unknown layout %r; expected one of %s / %s or "
                "channels_first / channels_last"
                % (name, sorted(_CHANNELS_FIRST.values()),
                   sorted(_CHANNELS_LAST.values())))
        # applied immediately so a bare call is a global set; entering the
        # context only arms the restore
        self._prev = getattr(_state, "channels_last", False)
        _state.channels_last = last

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        _state.channels_last = self._prev
        return False


def is_channels_last():
    """True when the current default layout family is channels-last."""
    return getattr(_state, "channels_last", False)


def current_layout(ndim=2):
    """The current default data layout string for an ndim-spatial conv."""
    table = _CHANNELS_LAST if is_channels_last() else _CHANNELS_FIRST
    if ndim not in table:
        raise MXNetError("unsupported spatial ndim %d" % ndim)
    return table[ndim]


def conv_layout(explicit, ndim):
    """Resolve a layer's layout argument: explicit value wins, else scope."""
    if explicit is not None:
        return explicit
    return current_layout(ndim)


def channel_axis(layout_str):
    """Channel axis index for a layout string ('NCHW' -> 1, 'NHWC' -> -1)."""
    if layout_str is None:
        return -1 if is_channels_last() else 1
    return -1 if layout_str.endswith("C") and layout_str[1] != "C" else 1
