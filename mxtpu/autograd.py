"""Imperative autograd for eager NDArray code.

Reference design: ``src/imperative/imperative.cc`` (RecordOp :183 tapes each op as an
nnvm node carrying AGInfo; Backward :270 builds a gradient graph with
``nnvm::pass::Gradient`` and executes it imperatively) and the Python surface
``python/mxnet/autograd.py:93-509``.

TPU-native re-design: instead of an nnvm graph + per-op FGradient registry, the tape
records each invoked op as ``(pure_fn, input snapshots)`` and backward computes per-node
cotangents with ``jax.vjp`` — XLA builds the transposed computation, so no hand-written
gradient kernels are needed. Residuals are traded for recompute (forward is re-traced
inside vjp), which is usually HBM-bandwidth-favourable on TPU; the *fast* training path
is ``HybridBlock.hybridize()`` where the whole step is one jitted ``jax.grad``
(mxtpu/cached_op.py).

Dataflow is tracked with (node, output-index) *entries*, the analog of
``nnvm::NodeEntry``: an NDArray points at the entry that produced its current value, so
in-place mutation (``x += y`` while recording) simply rebinds the array to a new entry
and old entries stay valid — the reference achieves the same with engine var versioning
(include/mxnet/engine.h:45-62). Recorded snapshots are immutable ``jax.Array`` values.
"""
from __future__ import annotations

import threading
from typing import Callable, List, Sequence

import jax

from .base import MXNetError

__all__ = [
    "record", "pause", "train_mode", "predict_mode", "is_recording", "is_training",
    "set_recording", "set_training", "mark_variables", "backward", "grad", "Function",
]


class _AGState(threading.local):
    def __init__(self):
        self.recording = False
        self.training = False


_STATE = _AGState()


def is_recording() -> bool:
    return _STATE.recording


def is_training() -> bool:
    return _STATE.training


def set_recording(flag: bool) -> bool:
    prev, _STATE.recording = _STATE.recording, flag
    return prev


def set_training(flag: bool) -> bool:
    prev, _STATE.training = _STATE.training, flag
    return prev


class _Scope:
    def __init__(self, recording=None, training=None):
        self._rec, self._train = recording, training

    def __enter__(self):
        if self._rec is not None:
            self._prev_rec = set_recording(self._rec)
        if self._train is not None:
            self._prev_train = set_training(self._train)
        return self

    def __exit__(self, *a):
        if self._rec is not None:
            set_recording(self._prev_rec)
        if self._train is not None:
            set_training(self._prev_train)


def record(train_mode: bool = True):  # noqa: A002 - mirror reference name
    """Scope enabling taping (ref: python/mxnet/autograd.py:record)."""
    return _Scope(recording=True, training=train_mode)


def pause(train_mode: bool = False):
    return _Scope(recording=False, training=train_mode)


def train_mode():
    return _Scope(training=True)


def predict_mode():
    return _Scope(training=False)


class _Entry:
    """A dataflow edge: (producer node, output index) — nnvm::NodeEntry analog.

    ``array`` is the NDArray that held this value when the entry was live; kept so
    backward can write leaf gradients into attached grad buffers.
    """

    __slots__ = ("node", "index", "array")

    def __init__(self, node, index, array):
        self.node = node
        self.index = index
        self.array = array


class _Node:
    """One taped op invocation. ``fn(*in_data) -> out_data(s)`` is pure and
    jax-traceable; non-differentiable inputs/attrs are closed over."""

    __slots__ = ("fn", "in_entries", "in_data", "out_entries", "name", "vjp",
                 "primals_out")

    def __init__(self, fn, in_entries, in_data, name="", vjp=None, primals_out=None):
        self.fn = fn
        self.in_entries = in_entries
        self.in_data = in_data
        self.out_entries = []
        self.name = name
        # optional precomputed (primals_out, vjp_fn) from jax.vjp at forward time —
        # used by CachedOp so training does not recompute the forward in backward
        self.vjp = vjp
        self.primals_out = primals_out


def _entry_of(x) -> _Entry:
    e = getattr(x, "_ag_entry", None)
    if e is None:
        e = _Entry(None, 0, x)
        x._ag_entry = e
    return e


def record_op(fn: Callable, inputs: Sequence, outputs: Sequence, name: str = "",
              vjp=None, primals_out=None) -> None:
    """Tape an op call (ref: Imperative::RecordOp, src/imperative/imperative.cc:183)."""
    node = _Node(fn, [_entry_of(x) for x in inputs], [x._data for x in inputs], name,
                 vjp=vjp, primals_out=primals_out)
    for i, o in enumerate(outputs):
        e = _Entry(node, i, o)
        o._ag_entry = e
        node.out_entries.append(e)


def mark_variables(variables, gradients, grad_reqs="write"):
    """Attach gradient buffers to leaves (ref: autograd.py:mark_variables)."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._ag_entry = None
        v._grad = g
        v._grad_req = req


def _topo_nodes(head_entries) -> List[_Node]:
    order: List[_Node] = []
    seen = set()

    def visit(node):
        if node is None or id(node) in seen:
            return
        seen.add(id(node))
        for e in node.in_entries:
            visit(e.node)
        order.append(node)

    for e in head_entries:
        visit(e.node)
    return order


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):  # noqa: A002
    """Reverse-mode through the tape (ref: Imperative::Backward,
    src/imperative/imperative.cc:270-519). Gradients land in ``x.grad`` for every
    array with an attached grad buffer (``attach_grad``/``mark_variables``)."""
    from . import telemetry
    with telemetry.span("gluon.backward"):
        return _backward_impl(heads, head_grads=head_grads,
                              retain_graph=retain_graph,
                              train_mode=train_mode)


def _backward_impl(heads, head_grads=None, retain_graph=False,
                   train_mode=True):
    from .ndarray import NDArray  # late import (cycle)
    import jax.numpy as jnp

    if isinstance(heads, NDArray):
        heads = [heads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif isinstance(head_grads, NDArray):
        head_grads = [head_grads]

    head_entries = []
    cots = {}  # id(_Entry) -> accumulated cotangent (jax array)
    for h, hg in zip(heads, head_grads):
        e = getattr(h, "_ag_entry", None)
        if e is None or e.node is None:
            if getattr(h, "_grad_req", "null") == "null":
                raise MXNetError(
                    "head array is not part of a recorded computation "
                    "(run inside autograd.record())"
                )
            continue
        head_entries.append(e)
        g = hg._data if hg is not None else jnp.ones(h.shape, dtype=h._data.dtype)
        cots[id(e)] = cots.get(id(e), 0) + g

    order = _topo_nodes(head_entries)
    leaf_entries = {}
    for node in reversed(order):
        # All consumers of this node's outputs are later in topo order, so output
        # cotangents are fully accumulated by the time we visit it (the tape analog
        # of the engine's dependency wait-counters).
        any_set = any(id(e) in cots for e in node.out_entries)
        if not any_set:
            continue
        if node.vjp is not None:
            primals_out, vjp_fn = node.primals_out, node.vjp
        else:
            primals_out, vjp_fn = jax.vjp(node.fn, *node.in_data)
        num_out = len(node.out_entries)
        primals_list = [primals_out] if num_out == 1 else list(primals_out)
        out_cots = []
        for i, e in enumerate(node.out_entries):
            c = cots.pop(id(e), None)
            if c is None:
                c = jnp.zeros(primals_list[i].shape, dtype=primals_list[i].dtype)
            else:
                c = jnp.asarray(c, dtype=primals_list[i].dtype)
            out_cots.append(c)
        in_cots = vjp_fn(out_cots[0] if num_out == 1 else tuple(out_cots))
        for e, c in zip(node.in_entries, in_cots):
            if c is None or getattr(c, "dtype", None) == jax.dtypes.float0:
                continue  # non-differentiable (integer) input
            cots[id(e)] = cots.get(id(e), 0) + c
            if e.node is None:
                leaf_entries[id(e)] = e

    # write accumulated cotangents into attached grad buffers
    for eid, e in leaf_entries.items():
        x = e.array
        req = getattr(x, "_grad_req", "null")
        if req != "null" and getattr(x, "_grad", None) is not None and eid in cots:
            if req == "add":
                x._grad._set_data(x._grad._data + cots[eid])
            else:
                x._grad._set_data(jnp.asarray(cots[eid], dtype=x._data.dtype))

    if not retain_graph:
        # free the tape (ref: AGInfo::Clear) so snapshots can be GC'd
        for node in order:
            node.in_data = None
            node.fn = None
            for e in node.out_entries:
                if getattr(e.array, "_ag_entry", None) is e:
                    e.array._ag_entry = None
            node.in_entries = []
            node.out_entries = []


def grad(heads, variables, head_grads=None, retain_graph=None, create_graph=False,
         train_mode=True):  # noqa: A002
    """Functional gradient interface (ref: python/mxnet/autograd.py:grad)."""
    from .ndarray import NDArray, array as _array
    import jax.numpy as jnp

    if isinstance(variables, NDArray):
        variables = [variables]
        single = True
    else:
        single = False
    saved = [(getattr(v, "_grad", None), getattr(v, "_grad_req", "null")) for v in variables]
    for v in variables:
        if getattr(v, "_ag_entry", None) is None:
            raise MXNetError("variables passed to grad() must be used in the recorded graph")
        v._grad = _array(jnp.zeros(v.shape, v._data.dtype))
        v._grad_req = "add"
        # mark the entry array so backward writes into the buffer
        v._ag_entry.array = v
    try:
        backward(heads, head_grads, retain_graph=bool(retain_graph), train_mode=train_mode)
        out = [v._grad for v in variables]
    finally:
        for v, (g, r) in zip(variables, saved):
            v._grad, v._grad_req = g, r
    return out[0] if single else out


class Function:
    """Custom differentiable function (ref: python/mxnet/autograd.py:Function,
    src/c_api/c_api_function.cc). Subclass and implement ``forward``/``backward``."""

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *args):
        self._saved = args

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):  # pragma: no cover - abstract
        raise NotImplementedError

    def backward(self, *out_grads):  # pragma: no cover - abstract
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray import NDArray, array as _array

        with pause():
            outputs = self.forward(*inputs)
        single = not isinstance(outputs, (list, tuple))
        out_list = [outputs] if single else list(outputs)
        if is_recording():
            func = self
            out_data = [o._data for o in out_list]

            @jax.custom_vjp
            def fwd(*in_data):
                return out_data[0] if single else tuple(out_data)

            def fwd_fwd(*in_data):
                return fwd(*in_data), None

            def fwd_bwd(_, g):
                gs = [g] if single else list(g)
                with pause():
                    in_gs = func.backward(*[_array(x) for x in gs])
                if isinstance(in_gs, NDArray):
                    in_gs = [in_gs]
                return tuple(x._data for x in in_gs)

            fwd.defvjp(fwd_fwd, fwd_bwd)
            record_op(fwd, list(inputs), out_list, name=type(self).__name__)
        return out_list[0] if single else out_list
