"""SequentialModule: chain modules so each consumes the previous outputs.

Reference: ``python/mxnet/module/sequential_module.py:28-441`` — an
imperative container (less efficient than one symbolic graph there; here
each inner Module is its own jit-compiled executor, so the chain costs one
dispatch per stage rather than one fused program — the honest TPU analog
of the reference's "handy utility, not the fast path" caveat).
"""
from __future__ import annotations

import copy
import logging

from .base_module import BaseModule

__all__ = ["SequentialModule"]


class SequentialModule(BaseModule):
    """Container chaining multiple modules; data flows module→module, the
    SAME labels from the original batch go to every ``take_labels`` module.
    """

    META_TAKE_LABELS = "take_labels"
    META_AUTO_WIRING = "auto_wiring"

    def __init__(self, logger=logging):
        super().__init__(logger=logger)
        self._modules = []
        self._metas = []
        self._label_shapes = None
        self._data_shapes = None
        self.inputs_need_grad = False
        self._meta_keys = {getattr(SequentialModule, x)
                           for x in dir(SequentialModule)
                           if x.startswith("META_")}

    def add(self, module, **kwargs):
        """Append a module; meta kwargs: ``take_labels`` (module receives
        the chain's labels), ``auto_wiring`` (rename incoming data to the
        module's own data_names). Returns self for chaining
        (ref: sequential_module.py:52-94)."""
        self._modules.append(module)
        for key in kwargs:
            assert key in self._meta_keys, 'Unknown meta "%s", a typo?' % key
        self._metas.append(kwargs)
        # adding resets to raw state: must re-bind / re-init
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False
        return self

    # ------------------------------------------------------------- shapes
    @property
    def data_names(self):
        return self._modules[0].data_names if self._modules else []

    @property
    def output_names(self):
        return self._modules[-1].output_names if self._modules else []

    @property
    def data_shapes(self):
        assert self.binded
        return self._modules[0].data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._modules[-1].output_shapes

    # --------------------------------------------------------------- params
    def get_params(self):
        assert self.binded and self.params_initialized
        arg_params, aux_params = {}, {}
        for module in self._modules:
            arg, aux = module.get_params()
            arg_params.update(arg)
            aux_params.update(aux)
        return arg_params, aux_params

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before initializing the parameters"
        for module in self._modules:
            module.init_params(initializer=initializer, arg_params=arg_params,
                               aux_params=aux_params,
                               allow_missing=allow_missing,
                               force_init=force_init)

        # no duplicated parameter names across layers
        # (ref: sequential_module.py:206-221 _check_name)
        known = {}
        for i_layer, module in enumerate(self._modules):
            arg, aux = module.get_params()
            for name in list(arg) + list(aux):
                assert name not in known, (
                    'Duplicated parameter name "%s": layer %d (%s) and layer '
                    "%d (%s)" % (name, i_layer, type(module).__name__,
                                 known[name][0], known[name][1]))
                known[name] = (i_layer, type(module).__name__)
        self.params_initialized = True

    # ---------------------------------------------------------------- bind
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        """Bind each module in sequence: module i+1's data shapes are
        module i's output shapes; interior modules get inputs_need_grad so
        the backward chain can flow (ref: sequential_module.py:224-296)."""
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        if inputs_need_grad:
            assert for_training
        assert shared_module is None, "Shared module is not supported"
        assert self._modules, "Attempting to bind an empty SequentialModule"
        self.binded = True
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._label_shapes = label_shapes

        my_data_shapes = data_shapes
        anybody_ever_needs_label = False
        for i_layer, module in enumerate(self._modules):
            meta = self._metas[i_layer]
            if meta.get(SequentialModule.META_TAKE_LABELS, False):
                my_label_shapes = label_shapes
                anybody_ever_needs_label = True
            else:
                my_label_shapes = None
            my_inputs_need_grad = bool(inputs_need_grad
                                       or (for_training and i_layer > 0))
            if meta.get(SequentialModule.META_AUTO_WIRING, False):
                data_names = module.data_names
                assert len(data_names) == len(my_data_shapes)
                # entries may be DataDesc namedtuples (4 fields) at layer 0
                # or plain (name, shape) pairs from output_shapes after
                my_data_shapes = [
                    (new_name, tuple(d.shape) if hasattr(d, "shape")
                     else tuple(d[1]))
                    for new_name, d in zip(data_names, my_data_shapes)]
            module.bind(data_shapes=my_data_shapes,
                        label_shapes=my_label_shapes,
                        for_training=for_training,
                        inputs_need_grad=my_inputs_need_grad,
                        force_rebind=force_rebind, shared_module=None,
                        grad_req=grad_req)
            my_data_shapes = module.output_shapes
        if not anybody_ever_needs_label:
            self._label_shapes = None

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring.")
            return
        for module in self._modules:
            module.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                                  optimizer_params=optimizer_params,
                                  force_init=force_init)
        self.optimizer_initialized = True

    # ------------------------------------------------------------- running
    def forward(self, data_batch, is_train=None):
        """(ref: sequential_module.py:326-356)"""
        assert self.binded and self.params_initialized
        data_batch = copy.copy(data_batch)
        for i_layer, module in enumerate(self._modules):
            module.forward(data_batch, is_train=is_train)
            if i_layer + 1 == len(self._modules):
                break
            data_batch.data = module.get_outputs()
            if getattr(data_batch, "provide_data", None) is not None:
                data_names = [x[0] for x in module.output_shapes]
                data_batch.provide_data = [
                    (name, x.shape)
                    for name, x in zip(data_names, data_batch.data)]

    def backward(self, out_grads=None):
        """Reverse chain: each module's input grads feed the previous
        module's out_grads (ref: sequential_module.py:357-367)."""
        assert self.binded and self.params_initialized
        for i_layer in reversed(range(len(self._modules))):
            module = self._modules[i_layer]
            module.backward(out_grads=out_grads)
            if i_layer == 0:
                break
            out_grads = module.get_input_grads()

    def update(self):
        assert self.binded and self.params_initialized \
            and self.optimizer_initialized
        for module in self._modules:
            module.update()

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._modules[-1].get_outputs(
            merge_multi_context=merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized \
            and self.inputs_need_grad
        return self._modules[0].get_input_grads(
            merge_multi_context=merge_multi_context)

    def update_metric(self, eval_metric, labels):
        assert self.binded and self.params_initialized
        for meta, module in zip(self._metas, self._modules):
            if meta.get(SequentialModule.META_TAKE_LABELS, False):
                module.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        assert self.binded
        for module in self._modules:
            module.install_monitor(mon)
