"""mx.mod: the classic symbolic training API.

Reference: ``python/mxnet/module/`` — BaseModule.fit training template
(base_module.py:410-528), Module over DataParallelExecutorGroup (module.py),
BucketingModule for variable-length inputs (bucketing_module.py).
"""
from .base_module import BaseModule
from .module import Module
from .bucketing_module import BucketingModule
from .sequential_module import SequentialModule
from .python_module import PythonModule, PythonLossModule

__all__ = ["BaseModule", "Module", "BucketingModule", "SequentialModule",
           "PythonModule", "PythonLossModule"]
