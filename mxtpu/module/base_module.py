"""BaseModule: the fit/score/predict training template.

Reference: ``python/mxnet/module/base_module.py`` — ``fit`` (:410-528) runs
forward_backward + update + metric per batch, eval + checkpoint per epoch.
"""
from __future__ import annotations

import logging

import numpy as np

from .. import metric as metric_mod
from ..base import MXNetError
from ..model import BatchEndParam

__all__ = ["BaseModule"]


def _as_metric(m):
    return m if isinstance(m, metric_mod.EvalMetric) else metric_mod.create(m)


class BaseModule:
    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None

    # ----------------------------------------------------- high-level API
    def forward_backward(self, data_batch):
        """(ref: base_module.py:194)"""
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, reset=True, epoch=0):
        """Evaluate on a data iterator (ref: base_module.py:score)."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        eval_metric = _as_metric(eval_metric)
        eval_metric.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                param = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                      eval_metric=eval_metric, locals=locals())
                for cb in _as_list(batch_end_callback):
                    cb(param)
        return eval_metric.get_name_value()

    def _bound_batch_size(self):
        """The batch size this module's executables were compiled for
        (first dim of the first bound data shape; None when unbound)."""
        shapes = getattr(self, "data_shapes", None)
        if not shapes:
            return None
        first = shapes[0]
        shape = first.shape if hasattr(first, "shape") else first[1]
        return shape[0] if shape else None

    def _pad_batch_to_bound(self, batch):
        """Ragged batch -> the bound batch size, via the serving
        pad-to-bucket helper: a final batch of n < bound rows pads
        device-side up to bound (``pad`` bumped so output slicing drops
        the filler) and reuses the existing compiled executable instead
        of tracing a fresh one per ragged size — the `retrace` telemetry
        at site ``executor`` stays flat across ragged tails."""
        bound = self._bound_batch_size()
        if bound is None or not getattr(batch, "data", None):
            return batch
        n = batch.data[0].shape[0]
        if n >= bound:
            return batch
        from ..io import DataBatch
        from ..serving.engine import pad_nd
        data = [pad_nd(d, bound) for d in batch.data]
        label = [pad_nd(l, bound) for l in batch.label] \
            if batch.label else batch.label
        return DataBatch(data=data, label=label,
                         pad=batch.pad + (bound - n), index=batch.index,
                         bucket_key=getattr(batch, "bucket_key", None),
                         provide_data=getattr(batch, "provide_data", None),
                         provide_label=getattr(batch, "provide_label", None))

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False):
        """Collect outputs over an iterator (ref: base_module.py:predict).
        Ragged batches route through the serving pad-to-bucket helper so
        they reuse the bound-batch executable (see _pad_batch_to_bound)."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            eval_batch = self._pad_batch_to_bound(eval_batch)
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outs = [o[0:o.shape[0] - pad] for o in self.get_outputs()]
            output_list.append(outs)
        if not output_list:
            return output_list
        if merge_batches:
            num_outputs = len(output_list[0])
            from ..ndarray import concat
            merged = [concat(*[o[i] for o in output_list], dim=0)
                      for i in range(num_outputs)]
            if num_outputs == 1 and not always_output_list:
                return merged[0]
            return merged
        return output_list

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None):
        """Full training loop (ref: base_module.py:410-528)."""
        assert num_epoch is not None, "please specify number of epochs"
        from ..initializer import Uniform
        if initializer is None:
            initializer = Uniform(0.01)

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        if validation_metric is None:
            validation_metric = eval_metric
        eval_metric = _as_metric(eval_metric)

        for epoch in range(begin_epoch, num_epoch):
            eval_metric.reset()
            nbatch = 0
            train_data.reset()
            for data_batch in train_data:
                if monitor is not None:
                    monitor.tic()
                self.forward_backward(data_batch)
                self.update()
                self.update_metric(eval_metric, data_batch.label)
                if monitor is not None:
                    monitor.toc_print()
                if batch_end_callback is not None:
                    param = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                          eval_metric=eval_metric,
                                          locals=locals())
                    for cb in _as_list(batch_end_callback):
                        cb(param)
                nbatch += 1

            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)

            arg_p, aux_p = self.get_params()
            self.set_params(arg_p, aux_p)
            if epoch_end_callback is not None:
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg_p, aux_p)

            if eval_data is not None:
                vmetric = _as_metric(validation_metric)
                res = self.score(eval_data, vmetric,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                if eval_end_callback is not None:
                    param = BatchEndParam(epoch=epoch, nbatch=0,
                                          eval_metric=vmetric,
                                          locals=locals())
                    for cb in _as_list(eval_end_callback):
                        cb(param)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f",
                                     epoch, name, val)

    # --------------------------------------------------------- interfaces
    @property
    def symbol(self):
        return self._symbol

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def get_outputs(self):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        raise NotImplementedError

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False):
        raise NotImplementedError

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        raise NotImplementedError

    def get_params(self):
        raise NotImplementedError

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)

    def install_monitor(self, mon):
        raise NotImplementedError


def _as_list(obj):
    if obj is None:
        return []
    return obj if isinstance(obj, (list, tuple)) else [obj]
