"""Module: bind a Symbol to data shapes and train it.

Reference: ``python/mxnet/module/module.py:40-642`` — binds a
DataParallelExecutorGroup (per-device executors + batch slicing,
executor_group.py:281) and reduces gradients through KVStore.

TPU-native re-design: ONE executor over the whole (possibly mesh-sharded)
program — the reference's per-GPU executor group + kvstore reduce collapse
into XLA GSPMD (SURVEY §2.3). The optimizer runs host-side through the same
Updater machinery as the reference (update_on_kvstore semantics preserved via
mx.kv)."""
from __future__ import annotations

import logging

import numpy as np

from .. import optimizer as opt_mod
from .. import telemetry
from ..base import MXNetError
from ..initializer import InitDesc
from ..model import load_checkpoint, save_checkpoint
from ..ndarray import NDArray, zeros
from .base_module import BaseModule

__all__ = ["Module"]


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 logger=logging, context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None, group2ctxs=None,
                 compression_params=None):
        super().__init__(logger=logger)
        self._symbol = symbol
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._fixed_param_names = list(fixed_param_names or [])
        self._context = context
        if group2ctxs is not None:
            # the reference's manual model-parallel placement
            # (PlaceDevice pass via __ctx_group__). The TPU-native answer is
            # GSPMD sharding (ShardedTrainStep param_specs) — accepting and
            # ignoring this would silently drop the user's placement intent.
            raise MXNetError(
                "group2ctxs manual device placement is not supported: use a "
                "jax.sharding.Mesh context plus ShardedTrainStep "
                "param_specs (GSPMD) for model parallelism")

        arg_names = symbol.list_arguments()
        input_names = self._data_names + self._label_names
        self._param_names = [n for n in arg_names if n not in input_names]
        self._aux_names = symbol.list_auxiliary_states()

        self._exec = None
        self._optimizer = None
        self._updater = None
        self._kvstore = None
        self._update_on_kvstore = False
        self._data_shapes = None
        self._label_shapes = None
        self._grad_req = "write"
        self._loss_scaler = None
        self.last_step_ok = None  # device verdict of the latest guarded update

    # ------------------------------------------------------------- binding
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    @property
    def output_shapes(self):
        if self._exec is not None and self._exec.outputs:
            return [(n, tuple(o.shape))
                    for n, o in zip(self.output_names, self._exec.outputs)]
        # before the first forward no output buffers exist (XLA allocates
        # at dispatch, unlike the reference's bind-time output arrays) —
        # infer symbolically from the bound input shapes so chained
        # binding (SequentialModule) can wire shapes ahead of execution
        assert self.binded, "bind first"
        hints = dict(self._data_shapes + (self._label_shapes or []))
        _args, outs, _auxs = self._symbol.infer_shape(**hints)
        return list(zip(self.output_names, [tuple(s) for s in outs]))

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        """(ref: module.py:bind)"""
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self.for_training = for_training
        self._grad_req = grad_req

        shapes = {}
        for desc in data_shapes:
            name, shape = (desc.name, desc.shape) if hasattr(desc, "name") \
                else (desc[0], desc[1])
            shapes[name] = tuple(shape)
        if label_shapes:
            for desc in label_shapes:
                name, shape = (desc.name, desc.shape) if hasattr(desc, "name") \
                    else (desc[0], desc[1])
                shapes[name] = tuple(shape)
        self._data_shapes = [(n, shapes[n]) for n in self._data_names]
        self._label_shapes = [(n, shapes[n]) for n in self._label_names
                              if n in shapes]

        req = {}
        for n in self._symbol.list_arguments():
            if n in self._data_names or n in self._label_names \
                    or n in self._fixed_param_names:
                req[n] = "null" if not inputs_need_grad \
                    or n not in self._data_names else grad_req
            else:
                req[n] = grad_req if for_training else "null"
        if shared_module is not None and shared_module._exec is not None:
            # share parameter arrays (BucketingModule path)
            exe = self._symbol.simple_bind(ctx=self._context, grad_req=req,
                                           **shapes)
            for n in self._param_names:
                if n in shared_module._exec.arg_dict:
                    exe.arg_dict[n] = shared_module._exec.arg_dict[n]
                    exe.arg_arrays = [exe.arg_dict[a]
                                      for a in self._symbol.list_arguments()]
                    if n in shared_module._exec.grad_dict:
                        exe.grad_dict[n] = shared_module._exec.grad_dict[n]
            for n in self._aux_names:
                if n in shared_module._exec.aux_dict:
                    exe.aux_dict[n] = shared_module._exec.aux_dict[n]
            self._exec = exe
        else:
            self._exec = self._symbol.simple_bind(ctx=self._context,
                                                  grad_req=req, **shapes)
        self.binded = True

    # ---------------------------------------------------------- parameters
    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False):
        """(ref: module.py:init_params)"""
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before init_params"
        for name in self._param_names:
            arr = self._exec.arg_dict[name]
            if arg_params is not None and name in arg_params:
                arr._set_data(arg_params[name].astype(arr.dtype)._data)
            elif initializer is not None:
                initializer(InitDesc(name), arr)
            elif not allow_missing and arg_params is not None:
                raise MXNetError("%s not initialized" % name)
        for name in self._aux_names:
            arr = self._exec.aux_dict[name]
            if aux_params is not None and name in aux_params:
                arr._set_data(aux_params[name].astype(arr.dtype)._data)
            elif initializer is not None:
                initializer(InitDesc(name), arr)
        self.params_initialized = True

    def get_params(self):
        return ({n: self._exec.arg_dict[n].copy() for n in self._param_names},
                {n: self._exec.aux_dict[n].copy() for n in self._aux_names})

    # ----------------------------------------------------------- optimizer
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False, loss_scaler=None):
        """(ref: module.py:init_optimizer; kvstore plumbing model.py
        _create_kvstore)

        ``loss_scaler``: optional :class:`mxtpu.resilience.DynamicLossScaler`
        — wires the in-jit numerics sentinel + dynamic loss scaling through
        ``update()`` (non-finite steps skip; ``self.last_step_ok`` carries
        the async verdict). ``backward()`` seeds the head gradients with
        the live scale; heads that IGNORE output gradients (SoftmaxOutput-
        style fused losses) need their own grad_scale instead — see
        docs/resilience.md."""
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        if isinstance(optimizer, str):
            idx2name = {i: n for i, n in enumerate(self._param_names)}
            opt_kw = dict(optimizer_params or {})
            # default rescale_grad = 1/batch (ref: module.py init_optimizer —
            # loss-layer grads like SoftmaxOutput are per-sample sums)
            if "rescale_grad" not in opt_kw and self._data_shapes:
                opt_kw["rescale_grad"] = 1.0 / self._data_shapes[0][1][0]
            optimizer = opt_mod.create(
                optimizer, param_idx2name=idx2name, sym=self._symbol,
                **opt_kw)
        self._optimizer = optimizer
        self._updater = opt_mod.get_updater(optimizer)
        self._loss_scaler = loss_scaler
        if loss_scaler is not None:
            self._updater.scaler = loss_scaler
        if kvstore:
            from .. import kvstore as kv_mod
            kv = kv_mod.create(kvstore) if isinstance(kvstore, str) else kvstore
            self._kvstore = kv
            self._update_on_kvstore = "dist" in kv.type
            for i, name in enumerate(self._param_names):
                kv.init(i, self._exec.arg_dict[name])
            if self._update_on_kvstore:
                kv.set_optimizer(self._optimizer)
                if loss_scaler is not None and \
                        getattr(kv, "_updater", None) is not None:
                    kv._updater.scaler = loss_scaler
        self.optimizer_initialized = True

    # ------------------------------------------------------------- running
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        if is_train is None:
            is_train = self.for_training
        feed = {}
        for name, arr in zip(self._data_names, data_batch.data):
            feed[name] = arr
        if data_batch.label is not None:
            for name, arr in zip(self._label_names, data_batch.label):
                feed[name] = arr
        with telemetry.span("module.forward"):
            self._exec.forward(is_train=is_train, **feed)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        if self._loss_scaler is not None:
            # loss scaling: head gradients are multiplied by the LIVE scale
            # (an async device scalar — no sync, no recompile), default
            # seeds and user-passed out_grads alike — the guarded updater
            # unconditionally divides the scale back out in the fused
            # update jit, so unscaled head grads here would silently
            # shrink every update by the scale factor
            import jax.numpy as jnp
            s = self._loss_scaler.scale_array()
            for o in self._exec.outputs:
                dt = o._data.dtype
                if jnp.issubdtype(dt, jnp.floating) and \
                        self._loss_scaler.max_scale > \
                        float(jnp.finfo(dt).max):
                    # fail fast (statically — no device sync): once the
                    # scale grows past the head dtype's max, the seed casts
                    # to inf and every step is skipped — an invisible
                    # scale ceiling. scale() avoids this by staying in f32;
                    # seeds cannot (jax vjp needs cotangent dtype == primal)
                    raise MXNetError(
                        "loss scaler max_scale=%g exceeds %s's max (%g): "
                        "construct DynamicLossScaler(max_scale=...) within "
                        "the head dtype's range for Module training"
                        % (self._loss_scaler.max_scale, dt,
                           float(jnp.finfo(dt).max)))
            if out_grads is None:
                out_grads = [NDArray(jnp.broadcast_to(
                    s.astype(o._data.dtype), o._data.shape))
                    for o in self._exec.outputs]
            else:
                out_grads = [NDArray(o._data * s.astype(o._data.dtype))
                             for o in out_grads]
        with telemetry.span("module.backward"):
            self._exec.backward(out_grads=out_grads)

    def update(self):
        """Optimizer step on accumulated grads (ref: module.py:update →
        _update_params / _update_params_on_kvstore, model.py)."""
        assert self.binded and self.params_initialized \
            and self.optimizer_initialized
        # grouped keys + ONE batched updater call: the kvstore fuses grouped
        # pushes into one reduce, and FusedUpdater compiles the whole update
        # into one donated jit (mxtpu/optimizer_fused.py)
        keys, grads, weights = [], [], []
        for i, name in enumerate(self._param_names):
            g = self._exec.grad_dict.get(name)
            if g is None:
                continue
            keys.append(i)
            grads.append(g)
            weights.append(self._exec.arg_dict[name])
        if not keys:
            return
        with telemetry.span("module.update", d2h=True):
            if self._kvstore is not None:
                if self._update_on_kvstore:
                    self._kvstore.push(keys, grads)
                    self._kvstore.pull(keys, weights)
                else:
                    self._kvstore.push(keys, grads)
                    self._kvstore.pull(keys, grads)
                    self._updater.update_batch(keys, grads, weights)
            else:
                self._updater.update_batch(keys, grads, weights)
        upd = self._kvstore._updater if self._update_on_kvstore \
            else self._updater
        self.last_step_ok = getattr(upd, "last_step_ok", None)

    def get_outputs(self, merge_multi_context=True):
        return self._exec.outputs

    def get_input_grads(self, merge_multi_context=True):
        return [self._exec.grad_dict.get(n) for n in self._data_names]

    def update_metric(self, eval_metric, labels):
        eval_metric.update(labels, self.get_outputs())

    def install_monitor(self, mon):
        mon.install(self._exec)

    # ---------------------------------------------------------- checkpoint
    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        arg, aux = self.get_params()
        save_checkpoint(prefix, epoch, self._symbol, arg, aux)
        if save_optimizer_states:
            with open("%s-%04d.states" % (prefix, epoch), "wb") as f:
                f.write(self._updater.get_states())

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._preloaded = (args, auxs)
        orig_init = mod.init_params

        def init_params(initializer=None, arg_params=None, aux_params=None,
                        allow_missing=False, force_init=False):
            orig_init(initializer=initializer,
                      arg_params=arg_params or args,
                      aux_params=aux_params or auxs,
                      allow_missing=allow_missing, force_init=force_init)
        mod.init_params = init_params
        if load_optimizer_states:
            mod._preload_opt_states = "%s-%04d.states" % (prefix, epoch)
        return mod
