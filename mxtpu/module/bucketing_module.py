"""BucketingModule: per-bucket executors sharing parameters.

Reference: ``python/mxnet/module/bucketing_module.py`` — the variable-length
RNN answer (docs/faq/bucketing.md): one Module per bucket key, parameters
shared across buckets.

TPU-native note (SURVEY §7 hard-part 1): this IS the shape-bucketing answer
to XLA recompilation — each bucket key compiles once and is cached; shared
parameter arrays make the buckets one logical model.
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from .base_module import BaseModule
from .module import Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None, compression_params=None):
        super().__init__(logger=logger)
        assert default_bucket_key is not None
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._fixed_param_names = fixed_param_names
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._init_args = None

    @property
    def default_bucket_key(self):
        return self._default_bucket_key

    @property
    def symbol(self):
        return self._curr_module.symbol

    @property
    def data_shapes(self):
        return self._curr_module.data_shapes

    @property
    def label_shapes(self):
        return self._curr_module.label_shapes

    @property
    def output_names(self):
        return self._curr_module.output_names

    def _gen_module(self, bucket_key):
        sym, data_names, label_names = self._sym_gen(bucket_key)
        return Module(sym, data_names=data_names, label_names=label_names,
                      logger=self.logger, context=self._context,
                      fixed_param_names=self._fixed_param_names)

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        """Bind the default bucket (ref: bucketing_module.py:bind)."""
        if self.binded and not force_rebind:
            return
        self.for_training = for_training
        module = self._gen_module(self._default_bucket_key)
        module.bind(data_shapes, label_shapes, for_training, inputs_need_grad,
                    force_rebind=False, shared_module=None, grad_req=grad_req)
        self._curr_module = module
        self._curr_bucket_key = self._default_bucket_key
        self._buckets[self._default_bucket_key] = module
        self.binded = True

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """(ref: bucketing_module.py:switch_bucket) — compile-on-first-use per
        bucket, parameters shared with the default bucket's module."""
        assert self.binded, "call bind before switching bucket"
        if bucket_key not in self._buckets:
            module = self._gen_module(bucket_key)
            module.bind(data_shapes, label_shapes, self._curr_module.for_training,
                        False, force_rebind=False,
                        shared_module=self._buckets[self._default_bucket_key])
            module.params_initialized = True
            module.optimizer_initialized = \
                self._buckets[self._default_bucket_key].optimizer_initialized
            module._optimizer = \
                self._buckets[self._default_bucket_key]._optimizer
            module._updater = self._buckets[self._default_bucket_key]._updater
            self._buckets[bucket_key] = module
        self._curr_module = self._buckets[bucket_key]
        self._curr_bucket_key = bucket_key

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False):
        self._curr_module.init_params(initializer, arg_params, aux_params,
                                      allow_missing, force_init)
        self.params_initialized = True

    def get_params(self):
        return self._buckets[self._default_bucket_key].get_params()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        self._buckets[self._default_bucket_key].init_optimizer(
            kvstore, optimizer, optimizer_params, force_init)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        key = data_batch.bucket_key
        if key is None:
            key = self._default_bucket_key
        self.switch_bucket(key, data_batch.provide_data,
                           data_batch.provide_label)
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads)

    def update(self):
        # grads live in the CURRENT bucket's executor; parameters are shared
        self._curr_module._optimizer = \
            self._buckets[self._default_bucket_key]._optimizer
        self._curr_module._updater = \
            self._buckets[self._default_bucket_key]._updater
        self._curr_module.optimizer_initialized = True
        self._curr_module.update()

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        self._curr_module.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        for module in self._buckets.values():
            module.install_monitor(mon)
