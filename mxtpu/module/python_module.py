"""PythonModule / PythonLossModule: host-side modules in a module chain.

Reference: ``python/mxnet/module/python_module.py:28-360`` — modules whose
computation is arbitrary Python (typically a custom loss) rather than a
bound symbol. Here they are genuinely host-side: scores/labels arrive as
NDArrays whose buffers live on device; a grad_func may compute with
mx.nd ops (stays on device) or numpy (host round-trip at the sync point —
the same deferred-fetch semantics as the reference's engine).
"""
from __future__ import annotations

import logging

from ..ndarray import NDArray, array
from .base_module import BaseModule

__all__ = ["PythonModule", "PythonLossModule"]


class PythonModule(BaseModule):
    """Implements most module APIs as no-ops so subclasses override only
    what they need (ref: python_module.py:28)."""

    def __init__(self, data_names, label_names, output_names, logger=logging):
        super().__init__(logger=logger)
        self._data_names = list(data_names)
        self._label_names = list(label_names) if label_names is not None \
            else None
        self._output_names = list(output_names)
        self._data_shapes = None
        self._label_shapes = None
        self._output_shapes = None
        self.inputs_need_grad = False

    # ------------------------------------------------------------- shapes
    @property
    def data_names(self):
        return self._data_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return self._output_shapes

    # ----------------------------------------------- params (none by default)
    def get_params(self):
        return {}, {}

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        self.params_initialized = True

    def update(self):
        pass

    def update_metric(self, eval_metric, labels):
        """By default outputs are scores evaluable against labels
        (ref: python_module.py:141-163)."""
        if self._label_shapes is None:
            return
        eval_metric.update(labels, self.get_outputs())

    # ---------------------------------------------------------------- bind
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        """(ref: python_module.py:165-214)"""
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        assert grad_req == "write", "Python module only supports write gradient"
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True

        norm = [(d.name, tuple(d.shape)) if hasattr(d, "name")
                else (d[0], tuple(d[1])) for d in data_shapes]
        assert len(norm) == len(self._data_names)
        assert [x[0] for x in norm] == self._data_names
        self._data_shapes = norm
        if label_shapes is not None:
            lnorm = [(d.name, tuple(d.shape)) if hasattr(d, "name")
                     else (d[0], tuple(d[1])) for d in label_shapes]
            assert self._label_names is not None
            assert len(self._label_names) == len(lnorm)
            self._label_shapes = lnorm
        else:
            self._label_shapes = None
        self._output_shapes = self._compute_output_shapes()

    def _compute_output_shapes(self):
        """Subclass computes output shapes from the bound data/label shapes."""
        raise NotImplementedError()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        self.optimizer_initialized = True


class PythonLossModule(PythonModule):
    """Terminal loss stage: forward passes scores through; backward calls
    ``grad_func(scores, labels) -> d(loss)/d(scores)``
    (ref: python_module.py:243-360)."""

    def __init__(self, name="pyloss", data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 grad_func=None):
        super().__init__(data_names, label_names, [name + "_output"],
                         logger=logger)
        self._name = name
        assert len(data_names) == 1
        assert len(label_names) == 1
        self._scores = None
        self._labels = None
        self._scores_grad = None
        if grad_func is not None:
            assert callable(grad_func)
        self._grad_func = grad_func

    def _compute_output_shapes(self):
        return [(self._name + "_output", self._data_shapes[0][1])]

    def forward(self, data_batch, is_train=None):
        self._scores = data_batch.data[0]
        if is_train is None:
            is_train = self.for_training
        if is_train:
            self._labels = data_batch.label[0]

    def get_outputs(self, merge_multi_context=True):
        assert merge_multi_context is True
        return [self._scores]

    def backward(self, out_grads=None):
        assert out_grads is None, "For a loss module, out_grads should be None"
        assert self.for_training
        self._backward_impl()

    def _backward_impl(self):
        if self._grad_func is not None:
            grad = self._grad_func(self._scores, self._labels)
            if not isinstance(grad, NDArray):
                grad = array(grad)
            self._scores_grad = grad
        else:
            raise NotImplementedError()

    def get_input_grads(self, merge_multi_context=True):
        assert merge_multi_context is True
        return [self._scores_grad]

    def install_monitor(self, mon):
        raise NotImplementedError()
