"""mx.profiler: op-level profiling with chrome://tracing output.

Reference: ``python/mxnet/profiler.py:33-291`` over the C++ profiler
(src/profiler/profiler.h — per-op events incl. engine queue time, chrome-trace
JSON dump, aggregate stats tables).

TPU-native re-design: eager op events are timed at the dispatch boundary
(ndarray._apply); compiled regions are one event per executable call — the
inside of a jit step is XLA's domain, so ``profile_xla=True`` additionally
starts the JAX/XLA profiler (TensorBoard trace with per-HLO timing), replacing
the reference's engine-level instrumentation. Dump format is chrome://tracing
JSON, same as the reference, plus ``aggregate_stats`` tables.
"""
from __future__ import annotations

import json
import os
import signal
import threading
import time
from collections import defaultdict

__all__ = ["set_config", "start", "stop", "pause", "resume", "dump", "dumps",
           "ProfileTask", "ProfileFrame", "ProfileEvent", "ProfileScope",
           "scope"]

class _Profiler:
    def __init__(self):
        self.active = False
        self.events = []          # (name, cat, ts_us, dur_us, tid)
        self.clear_gen = 0        # bumped whenever events are cleared
        self.lock = threading.Lock()
        self.filename = "profile.json"
        self.aggregate = True
        self.profile_xla = False
        self._xla_dir = None
        self._xla_tracing = False       # a jax device trace is live
        self._xla_max_s = 120.0         # hard bound on any device capture
        self._xla_watchdog = None
        self._xla_guard_installed = False
        self._xla_last_error = None     # last swallowed stop_trace error
        # profiled-window bounds (us, perf_counter clock) — dump() scopes
        # the always-on telemetry event ring to these
        self.window_start_us = None
        self.window_stop_us = None


_PROF = _Profiler()


def set_config(filename="profile.json", profile_all=False,
               profile_symbolic=True, profile_imperative=True,
               profile_memory=False, profile_api=False, aggregate_stats=True,
               profile_xla=False, xla_trace_dir=None, xla_trace_max_s=None,
               **_kwargs):
    """(ref: profiler.py:set_config — continuous_dump etc accepted via kwargs)"""
    _PROF.filename = filename
    _PROF.aggregate = aggregate_stats
    _PROF.profile_xla = profile_xla
    _PROF._xla_dir = xla_trace_dir or (filename + ".xla")
    # reset like every other field — a sticky bound from a previous
    # set_config would silently truncate later captures
    _PROF._xla_max_s = (120.0 if xla_trace_max_s is None
                        else float(xla_trace_max_s))


def _stop_xla_trace():
    """Idempotent device-trace stop, safe from any thread/signal context.

    A device trace left running when the client dies can wedge a remote
    TPU server-side for hours (every later dispatch from any process
    hangs). The reference's profiler is always-stoppable
    (src/profiler/profiler.h:256-437); this is the analog for the
    XLA-capture path: every exit route — normal stop(), atexit, SIGTERM/
    SIGINT, or the bounded-duration watchdog — funnels here, and only the
    first caller actually stops.
    """
    if not _PROF._xla_tracing:
        return
    _PROF._xla_tracing = False
    try:
        import jax
        jax.profiler.stop_trace()
        _PROF._xla_last_error = None
    except Exception as e:  # noqa: BLE001 — a stop must never raise, but
        # the swallowed reason stays inspectable (a failed stop usually
        # means no xplane dump was written)
        _PROF._xla_last_error = e


def _install_xla_guards():
    """atexit + SIGTERM/SIGINT hooks so an interrupted capture still sends
    stop_trace. SIGKILL cannot be caught — for watchdog-supervised runs use
    tools/safe_trace.py, which runs the capture in a child that also stops
    the trace when its parent disappears."""
    if _PROF._xla_guard_installed:
        return
    _PROF._xla_guard_installed = True
    import atexit
    atexit.register(_stop_xla_trace)
    if threading.current_thread() is not threading.main_thread():
        return  # signal handlers only installable from the main thread
    for signum in (signal.SIGTERM, signal.SIGINT):
        prev = signal.getsignal(signum)

        def handler(sig, frame, _prev=prev):
            _stop_xla_trace()
            if callable(_prev):
                _prev(sig, frame)
            elif _prev is signal.SIG_IGN:
                return  # the signal was deliberately ignored; keep it so
            else:
                signal.signal(sig, signal.SIG_DFL)
                os.kill(os.getpid(), sig)

        signal.signal(signum, handler)


def start():
    """(ref: profiler.py:set_state('run'))"""
    _PROF.active = True
    _PROF.window_start_us = time.perf_counter_ns() // 1000
    _PROF.window_stop_us = None
    if _PROF.profile_xla:
        import jax
        _install_xla_guards()
        jax.profiler.start_trace(_PROF._xla_dir)
        _PROF._xla_tracing = True
        # bounded duration: even if the profiled workload hangs (so the
        # user's own stop() is never reached), the capture ends and the
        # chip is released before any external watchdog resorts to SIGKILL
        t = threading.Timer(_PROF._xla_max_s, _stop_xla_trace)
        t.daemon = True
        t.start()
        _PROF._xla_watchdog = t


def stop():
    _PROF.active = False
    _PROF.window_stop_us = time.perf_counter_ns() // 1000
    if _PROF.profile_xla:
        w = _PROF._xla_watchdog
        _PROF._xla_watchdog = None
        if w is not None:
            w.cancel()
        _stop_xla_trace()
        if w is not None and w.is_alive():
            # the watchdog may have fired and be mid-write inside
            # stop_trace (it clears _xla_tracing BEFORE the write so later
            # stoppers no-op); stop() is synchronous like the reference's
            # profiler (src/profiler/profiler.h), so wait for the dump
            w.join(30)


def install_orphan_guard(poll_s=2.0):
    """Stop any live device trace if this process is orphaned (parent
    died, e.g. the supervising tools/safe_trace.py was SIGKILLed). Child
    half of the safe-capture protocol."""
    ppid0 = os.getppid()

    def watch():
        while True:
            time.sleep(poll_s)
            if os.getppid() != ppid0:
                _stop_xla_trace()
                return

    t = threading.Thread(target=watch, daemon=True, name="mxtpu-trace-guard")
    t.start()
    return t


def pause():
    _PROF.active = False


def resume():
    _PROF.active = True


def record_event(name, cat, ts_us, dur_us):
    """Called from the op dispatch path when profiling is on."""
    tid = threading.get_ident() & 0xFFFF
    with _PROF.lock:
        _PROF.events.append((name, cat, ts_us, dur_us, tid))


def is_active():
    return _PROF.active


def dumps(reset=False):
    """Aggregate statistics table as a string (ref: profiler.py:dumps)."""
    stats = defaultdict(lambda: [0, 0.0, float("inf"), 0.0])
    with _PROF.lock:
        events = list(_PROF.events)
        if reset:
            _PROF.events.clear()
            _PROF.clear_gen += 1
    for name, _cat, _ts, dur, _tid in events:
        s = stats[name]
        s[0] += 1
        s[1] += dur
        s[2] = min(s[2], dur)
        s[3] = max(s[3], dur)
    lines = ["%-40s %10s %12s %12s %12s %12s" %
             ("Name", "Calls", "Total(us)", "Avg(us)", "Min(us)", "Max(us)")]
    for name in sorted(stats, key=lambda n: -stats[n][1]):
        cnt, total, mn, mx = stats[name]
        lines.append("%-40s %10d %12.1f %12.1f %12.1f %12.1f" %
                     (name, cnt, total, total / cnt, mn, mx))
    return "\n".join(lines)


def dump(finished=True, profile_process="worker"):
    """Write chrome://tracing JSON (ref: profiler.py:dump; C++ emitter
    src/profiler/profiler.h:256-437).

    Telemetry spans (mxtpu/telemetry.py — trainer step phases, module
    forward/backward/update, data-wait, blocking syncs) are merged in with
    the same event shape and clock (``perf_counter_ns``-derived ts/dur),
    so ONE file shows the host phase timeline alongside the op events —
    and, with ``profile_xla``, alongside the XLA device trace. Trace-tree
    causality (parent/child span edges and explicit cross-thread links,
    ``telemetry.trace_flows``) rides along as chrome flow events
    (``ph: s/f``), so the timeline shows which thread's work BELONGS to
    which request/step instead of mere temporal overlap."""
    with _PROF.lock:
        events = list(_PROF.events)
    flows = []
    try:
        from . import telemetry
        tel = telemetry.events()
        # telemetry's span ring is ALWAYS-ON (MXTPU_TELEMETRY default 1),
        # unlike the window-gated op events — scope the merge to the
        # profiled window, or a 5-step trace after a long run would carry
        # the whole process lifetime on its time axis
        lo = _PROF.window_start_us
        hi = _PROF.window_stop_us
        if lo is not None:
            tel = [e for e in tel
                   if e[2] >= lo and (hi is None or e[2] <= hi)]
        events = events + tel
        flows = telemetry.trace_flows(lo, hi)
    except Exception:  # noqa: BLE001 — the op trace must dump regardless
        pass
    trace = {"traceEvents": [
        {"ph": "X", "name": name, "cat": cat, "ts": ts, "dur": dur,
         "pid": 0, "tid": tid}
        for name, cat, ts, dur, tid in events] + flows}
    with open(_PROF.filename, "w") as f:
        json.dump(trace, f)


# ------------------------------------------------------------ user scopes
class ProfileScope:
    """Context manager timing a custom region (ref: ProfileTask/Frame/Event,
    profiler.py:287+)."""

    def __init__(self, name, cat="user"):
        self.name = name
        self.cat = cat
        self._t0 = None

    def start(self):
        # gate at START: a scope opened while profiling is OFF records
        # nothing (no unbounded event growth from always-on bracketing),
        # while a scope opened during an active window is recorded even
        # if the profiler stops before the bracket closes (teardown must
        # not silently drop an in-flight measurement)
        if is_active():
            self._t0 = time.perf_counter_ns()
            self._gen = _PROF.clear_gen
        else:
            self._t0 = None

    def stop(self):
        if self._t0 is None:
            return
        # in-flight events survive a profiler STOP, but not a window
        # CLEAR (dumps(reset=True)): an event from before the clear would
        # leak into the next, unrelated window's table
        if is_active() or self._gen == _PROF.clear_gen:
            dur = (time.perf_counter_ns() - self._t0) // 1000
            record_event(self.name, self.cat, self._t0 // 1000, dur)
        self._t0 = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *a):
        self.stop()


def _domain_name(domain, name):
    """Tasks/frames in different domains must stay distinct rows in the
    aggregate table (ref MXProfileCreateTask keeps them apart)."""
    dn = getattr(domain, "name", None)
    return "%s:%s" % (dn, name) if dn else name


class ProfileTask(ProfileScope):
    def __init__(self, name, domain=None):
        super().__init__(_domain_name(domain, name), cat="task")


class ProfileFrame(ProfileScope):
    def __init__(self, name, domain=None):
        super().__init__(_domain_name(domain, name), cat="frame")


class ProfileEvent(ProfileScope):
    def __init__(self, name):
        super().__init__(name, cat="event")


def scope(name, cat="user"):
    return ProfileScope(name, cat)
