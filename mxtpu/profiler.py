"""mx.profiler: op-level profiling with chrome://tracing output.

Reference: ``python/mxnet/profiler.py:33-291`` over the C++ profiler
(src/profiler/profiler.h — per-op events incl. engine queue time, chrome-trace
JSON dump, aggregate stats tables).

TPU-native re-design: eager op events are timed at the dispatch boundary
(ndarray._apply); compiled regions are one event per executable call — the
inside of a jit step is XLA's domain, so ``profile_xla=True`` additionally
starts the JAX/XLA profiler (TensorBoard trace with per-HLO timing), replacing
the reference's engine-level instrumentation. Dump format is chrome://tracing
JSON, same as the reference, plus ``aggregate_stats`` tables.
"""
from __future__ import annotations

import json
import threading
import time
from collections import defaultdict

__all__ = ["set_config", "start", "stop", "pause", "resume", "dump", "dumps",
           "ProfileTask", "ProfileFrame", "ProfileEvent", "ProfileScope",
           "scope"]

class _Profiler:
    def __init__(self):
        self.active = False
        self.events = []          # (name, cat, ts_us, dur_us, tid)
        self.lock = threading.Lock()
        self.filename = "profile.json"
        self.aggregate = True
        self.profile_xla = False
        self._xla_dir = None


_PROF = _Profiler()


def set_config(filename="profile.json", profile_all=False,
               profile_symbolic=True, profile_imperative=True,
               profile_memory=False, profile_api=False, aggregate_stats=True,
               profile_xla=False, xla_trace_dir=None, **_kwargs):
    """(ref: profiler.py:set_config — continuous_dump etc accepted via kwargs)"""
    _PROF.filename = filename
    _PROF.aggregate = aggregate_stats
    _PROF.profile_xla = profile_xla
    _PROF._xla_dir = xla_trace_dir or (filename + ".xla")


def start():
    """(ref: profiler.py:set_state('run'))"""
    _PROF.active = True
    if _PROF.profile_xla:
        import jax
        jax.profiler.start_trace(_PROF._xla_dir)


def stop():
    _PROF.active = False
    if _PROF.profile_xla:
        import jax
        jax.profiler.stop_trace()


def pause():
    _PROF.active = False


def resume():
    _PROF.active = True


def record_event(name, cat, ts_us, dur_us):
    """Called from the op dispatch path when profiling is on."""
    tid = threading.get_ident() & 0xFFFF
    with _PROF.lock:
        _PROF.events.append((name, cat, ts_us, dur_us, tid))


def is_active():
    return _PROF.active


def dumps(reset=False):
    """Aggregate statistics table as a string (ref: profiler.py:dumps)."""
    stats = defaultdict(lambda: [0, 0.0, float("inf"), 0.0])
    with _PROF.lock:
        events = list(_PROF.events)
        if reset:
            _PROF.events.clear()
    for name, _cat, _ts, dur, _tid in events:
        s = stats[name]
        s[0] += 1
        s[1] += dur
        s[2] = min(s[2], dur)
        s[3] = max(s[3], dur)
    lines = ["%-40s %10s %12s %12s %12s %12s" %
             ("Name", "Calls", "Total(us)", "Avg(us)", "Min(us)", "Max(us)")]
    for name in sorted(stats, key=lambda n: -stats[n][1]):
        cnt, total, mn, mx = stats[name]
        lines.append("%-40s %10d %12.1f %12.1f %12.1f %12.1f" %
                     (name, cnt, total, total / cnt, mn, mx))
    return "\n".join(lines)


def dump(finished=True, profile_process="worker"):
    """Write chrome://tracing JSON (ref: profiler.py:dump; C++ emitter
    src/profiler/profiler.h:256-437)."""
    with _PROF.lock:
        events = list(_PROF.events)
    trace = {"traceEvents": [
        {"ph": "X", "name": name, "cat": cat, "ts": ts, "dur": dur,
         "pid": 0, "tid": tid}
        for name, cat, ts, dur, tid in events]}
    with open(_PROF.filename, "w") as f:
        json.dump(trace, f)


# ------------------------------------------------------------ user scopes
class ProfileScope:
    """Context manager timing a custom region (ref: ProfileTask/Frame/Event,
    profiler.py:287+)."""

    def __init__(self, name, cat="user"):
        self.name = name
        self.cat = cat
        self._t0 = None

    def start(self):
        self._t0 = time.perf_counter_ns()

    def stop(self):
        if self._t0 is None:
            return
        dur = (time.perf_counter_ns() - self._t0) // 1000
        record_event(self.name, self.cat, self._t0 // 1000, dur)
        self._t0 = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *a):
        self.stop()


class ProfileTask(ProfileScope):
    def __init__(self, name, domain=None):
        super().__init__(name, cat="task")


class ProfileFrame(ProfileScope):
    def __init__(self, name, domain=None):
        super().__init__(name, cat="frame")


class ProfileEvent(ProfileScope):
    def __init__(self, name):
        super().__init__(name, cat="event")


def scope(name, cat="user"):
    return ProfileScope(name, cat)
