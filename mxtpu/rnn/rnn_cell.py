"""Legacy symbolic RNN cell namespace ``mx.rnn`` (ref:
python/mxnet/rnn/rnn_cell.py).

TPU-native notes: cells COMPOSE Symbol graphs exactly like the reference
(FullyConnected + activations per step), and ``unroll`` builds the
time-unrolled graph in Python — under this engine the bound executor jits
the whole graph once, so XLA sees the full unrolled program and fuses it
(the reference needed FusedRNNCell to reach one cudnn kernel; here the
fused and unfused forms compile to comparable XLA programs).
``FusedRNNCell.unroll`` still lowers to the single registered ``RNN`` op
(scan-based, ops/rnn_ops.py) with the reference's packed parameter
variable, so checkpoints using '%sparameters' blobs work.

Deviation (documented): ``begin_state`` needs an explicit ``batch_size``
when defaulting to zeros — this engine binds concrete arrays instead of
running a deferred whole-graph shape-inference pass (SURVEY §2.1: shape
propagation is per-layer and explicit). The conv cells
(ConvRNN/ConvLSTM/ConvGRU) live in ``mxtpu.gluon.contrib.cnn`` (the modern
surface); they are not mirrored here.
"""
from __future__ import annotations

from ..base import MXNetError
from .. import symbol as sym

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "DropoutCell",
           "ModifierCell", "ZoneoutCell", "ResidualCell",
           "BidirectionalCell"]


class RNNParams(object):
    """Container for holding variables shared between cells
    (ref: rnn_cell.py:RNNParams)."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **_kwargs):
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = sym.var(name)
        return self._params[name]


class BaseRNNCell(object):
    """Abstract symbolic RNN cell (ref: rnn_cell.py:BaseRNNCell)."""

    def __init__(self, prefix="", params=None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        if hasattr(self, "_cells"):
            for cell in self._cells:
                cell.reset()

    def __call__(self, inputs, states):
        raise NotImplementedError()

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def state_info(self):
        raise NotImplementedError()

    @property
    def state_shape(self):
        return [info["shape"] for info in self.state_info]

    def begin_state(self, func=None, batch_size=0, **kwargs):
        """Initial states. With no ``func``, concrete zeros of shape
        (batch_size, num_hidden) — ``batch_size`` is REQUIRED then (see
        module docstring); with ``func`` (e.g. ``mx.sym.var``) the shapes
        are the caller's problem, as in the reference."""
        assert not self._modified, \
            "After applying modifier cells the base cell cannot be called"
        states = []
        for info in self.state_info:
            self._init_counter += 1
            name = "%sbegin_state_%d" % (self._prefix, self._init_counter)
            if func is None:
                if not batch_size:
                    raise MXNetError(
                        "begin_state needs batch_size (no deferred "
                        "whole-graph shape inference in this engine)")
                shape = (batch_size,) + tuple(info["shape"][1:])
                states.append(sym.zeros(shape=shape, name=name))
            else:
                states.append(func(name=name, **kwargs))
        return states

    def unpack_weights(self, args):
        """Unpack fused weights to unfused (ref: BaseRNNCell.unpack_weights);
        plain cells keep per-gate layout already — identity."""
        return dict(args)

    def pack_weights(self, args):
        return dict(args)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Unroll for ``length`` steps (ref: BaseRNNCell.unroll). Returns
        (outputs, states); outputs merged along time when
        merge_outputs=True."""
        self.reset()
        inputs, batch_like = _normalize_sequence(length, inputs, layout,
                                                 merge=False)
        if begin_state is None:
            raise MXNetError(
                "unroll needs begin_state (build with cell.begin_state("
                "batch_size=N)); this engine binds concrete state arrays")
        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
        outputs, _ = _normalize_sequence(length, outputs, layout,
                                         merge_outputs)
        return outputs, states

    def _get_activation(self, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return sym.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)


def _normalize_sequence(length, inputs, layout, merge, in_layout=None):
    """Split a merged NTC/TNC symbol into per-step symbols, or merge a
    list back (ref: rnn_cell.py:_normalize_sequence)."""
    assert layout in ("NTC", "TNC"), "unsupported layout %s" % layout
    axis = layout.find("T")
    in_axis = in_layout.find("T") if in_layout is not None else axis
    if isinstance(inputs, (list, tuple)):
        assert len(inputs) == length
        if merge is True:
            stacked = sym.Concat(*[sym.expand_dims(x, axis=axis)
                                   for x in inputs], dim=axis)
            return stacked, axis
        return list(inputs), axis
    # merged symbol in
    if merge is False or merge is None:
        outputs = sym.SliceChannel(inputs, num_outputs=length, axis=in_axis,
                                   squeeze_axis=True)
        return [outputs[i] for i in range(length)], axis
    if in_axis != axis:
        inputs = sym.swapaxes(inputs, dim1=0, dim2=1)
    return inputs, axis


class RNNCell(BaseRNNCell):
    """Vanilla RNN cell: out = act(W_i x + b_i + W_h h + b_h)
    (ref: rnn_cell.py:RNNCell)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("",)

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = sym.FullyConnected(inputs, self._iW, self._iB,
                                 num_hidden=self._num_hidden,
                                 name="%si2h" % name)
        h2h = sym.FullyConnected(states[0], self._hW, self._hB,
                                 num_hidden=self._num_hidden,
                                 name="%sh2h" % name)
        output = self._get_activation(i2h + h2h, self._activation,
                                      name="%sout" % name)
        return output, [output]


class LSTMCell(BaseRNNCell):
    """LSTM cell (ref: rnn_cell.py:LSTMCell; gate order i, f, c, o)."""

    def __init__(self, num_hidden, prefix="lstm_", params=None,
                 forget_bias=1.0):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._forget_bias = forget_bias
        self._iW = self.params.get("i2h_weight")
        self._hW = self.params.get("h2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"},
                {"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ["_i", "_f", "_c", "_o"]

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = sym.FullyConnected(inputs, self._iW, self._iB,
                                 num_hidden=self._num_hidden * 4,
                                 name="%si2h" % name)
        h2h = sym.FullyConnected(states[0], self._hW, self._hB,
                                 num_hidden=self._num_hidden * 4,
                                 name="%sh2h" % name)
        gates = i2h + h2h
        g = sym.SliceChannel(gates, num_outputs=4, name="%sslice" % name)
        in_gate = sym.Activation(g[0], act_type="sigmoid", name="%si" % name)
        # forget_bias folds into the gate pre-activation (the reference
        # bakes it into i2h_bias via init.LSTMBias; numerically identical)
        forget_gate = sym.Activation(g[1] + self._forget_bias,
                                     act_type="sigmoid", name="%sf" % name)
        in_trans = sym.Activation(g[2], act_type="tanh", name="%sc" % name)
        out_gate = sym.Activation(g[3], act_type="sigmoid",
                                  name="%so" % name)
        next_c = forget_gate * states[1] + in_gate * in_trans
        next_h = out_gate * sym.Activation(next_c, act_type="tanh")
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """GRU cell (ref: rnn_cell.py:GRUCell; gate order r, z, o)."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ["_r", "_z", "_o"]

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        prev = states[0]
        i2h = sym.FullyConnected(inputs, self._iW, self._iB,
                                 num_hidden=self._num_hidden * 3,
                                 name="%si2h" % name)
        h2h = sym.FullyConnected(prev, self._hW, self._hB,
                                 num_hidden=self._num_hidden * 3,
                                 name="%sh2h" % name)
        ii = sym.SliceChannel(i2h, num_outputs=3, name="%si2h_slice" % name)
        hh = sym.SliceChannel(h2h, num_outputs=3, name="%sh2h_slice" % name)
        reset = sym.Activation(ii[0] + hh[0], act_type="sigmoid",
                               name="%sr_act" % name)
        update = sym.Activation(ii[1] + hh[1], act_type="sigmoid",
                                name="%sz_act" % name)
        next_h_tmp = sym.Activation(ii[2] + reset * hh[2], act_type="tanh",
                                    name="%sh_act" % name)
        next_h = (1.0 - update) * next_h_tmp + update * prev
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Multi-layer fused cell lowering to the single ``RNN`` op (ref:
    rnn_cell.py:FusedRNNCell over src/operator/rnn.cc; here the op is the
    scan-based XLA lowering, ops/rnn_ops.py). Parameters live in ONE
    packed '%sparameters' variable, same layout as the reference."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0.0, get_next_state=False,
                 forget_bias=1.0, prefix=None, params=None):
        if prefix is None:
            prefix = "%s_" % mode
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        self._forget_bias = forget_bias
        self._parameter = self.params.get("parameters")
        self._directions = ["l", "r"] if bidirectional else ["l"]

    @property
    def state_info(self):
        b = self._num_layers * (2 if self._bidirectional else 1)
        n = 2 if self._mode == "lstm" else 1
        return [{"shape": (b, 0, self._num_hidden), "__layout__": "LNC"}
                for _ in range(n)]

    @property
    def _gate_names(self):
        return {"rnn_relu": [""], "rnn_tanh": [""],
                "lstm": ["_i", "_f", "_c", "_o"],
                "gru": ["_r", "_z", "_o"]}[self._mode]

    def __call__(self, inputs, states):
        raise MXNetError("FusedRNNCell cannot be stepped one t at a time; "
                         "use unroll (ref: rnn_cell.py:641)")

    def begin_state(self, func=None, batch_size=0, **kwargs):
        assert not self._modified
        states = []
        for info in self.state_info:
            self._init_counter += 1
            name = "%sbegin_state_%d" % (self._prefix, self._init_counter)
            if func is None:
                if not batch_size:
                    raise MXNetError("begin_state needs batch_size")
                shape = (info["shape"][0], batch_size, info["shape"][2])
                states.append(sym.zeros(shape=shape, name=name))
            else:
                states.append(func(name=name, **kwargs))
        return states

    def _blob_layout(self, total_size):
        """(input_size, dirs) recovered from the packed blob length
        (ref: rnn_cell.py FusedRNNCell infers I the same way)."""
        ng = len(self._gate_names)
        dirs = len(self._directions)
        H, L = self._num_hidden, self._num_layers
        rest = (L - 1) * dirs * ng * H * (H * dirs + H + 2)
        input_size = (total_size - rest) // (dirs * ng * H) - H - 2
        return int(input_size), dirs

    def unpack_weights(self, args):
        """Split the packed '%sparameters' blob into the per-gate unfused
        names unfuse()'s stack binds (ref: FusedRNNCell.unpack_weights;
        layout rnn-inl.h GetParamSize — see ops/rnn_ops._unpack_params)."""
        import numpy as np
        from ..ndarray import array as nd_array
        from ..ops.rnn_ops import _unpack_params

        args = dict(args)
        blob = args.pop(self._prefix + "parameters")
        arr = blob.asnumpy() if hasattr(blob, "asnumpy") else \
            np.asarray(blob)
        input_size, dirs = self._blob_layout(arr.size)
        ws = _unpack_params(arr, self._mode, self._num_layers, input_size,
                            self._num_hidden, dirs == 2)
        for layer in range(self._num_layers):
            for d, dname in enumerate(self._directions):
                w_ih, w_hh, b_ih, b_hh = ws[layer * dirs + d]
                p = "%s%s%d_" % (self._prefix, dname, layer)
                args[p + "i2h_weight"] = nd_array(np.asarray(w_ih))
                args[p + "h2h_weight"] = nd_array(np.asarray(w_hh))
                args[p + "i2h_bias"] = nd_array(np.asarray(b_ih))
                args[p + "h2h_bias"] = nd_array(np.asarray(b_hh))
        return args

    def pack_weights(self, args):
        """Inverse of unpack_weights: gather unfused names back into the
        packed blob (weights layer/direction-major, then all biases)."""
        import numpy as np
        from ..ndarray import array as nd_array

        args = dict(args)
        parts_w, parts_b = [], []
        for layer in range(self._num_layers):
            for dname in self._directions:
                p = "%s%s%d_" % (self._prefix, dname, layer)
                for suffix, dest in (("i2h_weight", parts_w),
                                     ("h2h_weight", parts_w)):
                    a = args.pop(p + suffix)
                    dest.append(np.asarray(
                        a.asnumpy() if hasattr(a, "asnumpy") else a).ravel())
        for layer in range(self._num_layers):
            for dname in self._directions:
                p = "%s%s%d_" % (self._prefix, dname, layer)
                for suffix in ("i2h_bias", "h2h_bias"):
                    a = args.pop(p + suffix)
                    parts_b.append(np.asarray(
                        a.asnumpy() if hasattr(a, "asnumpy") else a).ravel())
        args[self._prefix + "parameters"] = nd_array(
            np.concatenate(parts_w + parts_b))
        return args

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        inputs, _ = _normalize_sequence(length, inputs, "TNC", merge=True,
                                        in_layout=layout)
        if begin_state is None:
            raise MXNetError("unroll needs begin_state "
                             "(cell.begin_state(batch_size=N))")
        states = begin_state
        kw = {"state_size": self._num_hidden,
              "num_layers": self._num_layers, "mode": self._mode,
              "bidirectional": self._bidirectional, "p": self._dropout,
              "state_outputs": self._get_next_state}
        if self._mode == "lstm":
            rnn = sym.RNN(inputs, self._parameter, states[0], states[1],
                          name="%srnn" % self._prefix, **kw)
        else:
            rnn = sym.RNN(inputs, self._parameter, states[0],
                          name="%srnn" % self._prefix, **kw)
        if self._get_next_state:
            n_states = 2 if self._mode == "lstm" else 1
            outputs = rnn[0]
            final = [rnn[1 + i] for i in range(n_states)]
        else:
            outputs = rnn
            final = []
        if layout == "NTC":
            outputs = sym.swapaxes(outputs, dim1=0, dim2=1)
        if merge_outputs is False:
            outputs, _ = _normalize_sequence(length, outputs, layout, False,
                                             in_layout=layout)
        return outputs, final

    def unfuse(self):
        """Equivalent SequentialRNNCell of unfused cells (ref:
        rnn_cell.py:FusedRNNCell.unfuse)."""
        stack = SequentialRNNCell()
        make = {
            "rnn_relu": lambda p: RNNCell(self._num_hidden,
                                          activation="relu", prefix=p),
            "rnn_tanh": lambda p: RNNCell(self._num_hidden,
                                          activation="tanh", prefix=p),
            # forget_bias 0: the fused blob's biases already carry any
            # initial forget bias (LSTMCell applies forget_bias at
            # runtime — the TPU-native stand-in for the reference's
            # LSTMBias INITIALIZER — so a non-zero value here would
            # double-bias weights unpacked from a trained blob)
            "lstm": lambda p: LSTMCell(self._num_hidden, prefix=p,
                                       forget_bias=0.0),
            "gru": lambda p: GRUCell(self._num_hidden, prefix=p),
        }[self._mode]
        for i in range(self._num_layers):
            if self._bidirectional:
                stack.add(BidirectionalCell(
                    make("%sl%d_" % (self._prefix, i)),
                    make("%sr%d_" % (self._prefix, i)),
                    output_prefix="%sbi_l%d_" % (self._prefix, i)))
            else:
                stack.add(make("%sl%d_" % (self._prefix, i)))
            if self._dropout > 0 and i != self._num_layers - 1:
                stack.add(DropoutCell(self._dropout,
                                      prefix="%s_dropout%d_"
                                      % (self._prefix, i)))
        return stack


class SequentialRNNCell(BaseRNNCell):
    """Stack of cells applied in order per step (ref: SequentialRNNCell)."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._override_cell_params = params is not None
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)
        if self._override_cell_params:
            # share the container both ways (ref: SequentialRNNCell.add)
            assert cell._own_params, \
                "Either specify params for SequentialRNNCell or child " \
                "cells, not both."
            cell.params._params.update(self.params._params)
        self.params._params.update(cell.params._params)

    @property
    def state_info(self):
        return sum([c.state_info for c in self._cells], [])

    def begin_state(self, **kwargs):
        assert not self._modified
        return sum([c.begin_state(**kwargs) for c in self._cells], [])

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._cells:
            n = len(cell.state_info)
            cell_states = states[p:p + n]
            p += n
            inputs, cell_states = cell(inputs, cell_states)
            next_states.extend(cell_states)
        return inputs, next_states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        if begin_state is None:
            raise MXNetError("unroll needs begin_state")
        num_cells = len(self._cells)
        p = 0
        next_states = []
        for i, cell in enumerate(self._cells):
            n = len(cell.state_info)
            states = begin_state[p:p + n]
            p += n
            inputs, states = cell.unroll(
                length, inputs=inputs, begin_state=states, layout=layout,
                merge_outputs=None if i < num_cells - 1 else merge_outputs)
            next_states.extend(states)
        return inputs, next_states


class DropoutCell(BaseRNNCell):
    """Dropout on the outputs (ref: DropoutCell); stateless."""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix=prefix, params=params)
        self.dropout = dropout

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        if self.dropout > 0:
            inputs = sym.Dropout(inputs, p=self.dropout)
        return inputs, states


class ModifierCell(BaseRNNCell):
    """Base for cells that wrap another cell (ref: ModifierCell)."""

    def __init__(self, base_cell):
        super().__init__()
        base_cell._modified = True
        self.base_cell = base_cell

    @property
    def params(self):
        self._own_params = False
        return self.base_cell.params

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return begin

    def __call__(self, inputs, states):
        raise NotImplementedError()


class ZoneoutCell(ModifierCell):
    """Zoneout regularization (ref: ZoneoutCell; Krueger et al. 2016):
    each state element keeps its previous value with probability p."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        assert not isinstance(base_cell, FusedRNNCell), \
            "FusedRNNCell does not support zoneout; unfuse() first"
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self.prev_output = None

    def reset(self):
        super().reset()
        self.prev_output = None

    def __call__(self, inputs, states):
        cell = self.base_cell
        next_output, next_states = cell(inputs, states)

        def mask(p, like):
            return sym.Dropout(sym.ones_like(like), p=p)

        prev_output = self.prev_output if self.prev_output is not None \
            else sym.zeros_like(next_output)
        if self.zoneout_outputs > 0:
            m = mask(self.zoneout_outputs, next_output)
            next_output = sym.where(m, next_output, prev_output)
        if self.zoneout_states > 0:
            next_states = [sym.where(mask(self.zoneout_states, ns), ns, s)
                           for ns, s in zip(next_states, states)]
        self.prev_output = next_output
        return next_output, next_states


class ResidualCell(ModifierCell):
    """Adds the input to the output (ref: ResidualCell; He 2015)."""

    def __call__(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        return output + inputs, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        self.base_cell._modified = False
        outputs, states = self.base_cell.unroll(
            length, inputs=inputs, begin_state=begin_state, layout=layout,
            merge_outputs=False)
        self.base_cell._modified = True
        ins, _ = _normalize_sequence(length, inputs, layout, False)
        outputs = [o + i for o, i in zip(outputs, ins)]
        outputs, _ = _normalize_sequence(length, outputs, layout,
                                         merge_outputs)
        return outputs, states


class BidirectionalCell(BaseRNNCell):
    """Forward + backward cells over the sequence (ref: BidirectionalCell);
    only unrollable — a single step has no backward context."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__("", params=params)
        self._output_prefix = output_prefix
        self._cells = [l_cell, r_cell]

    def __call__(self, inputs, states):
        raise MXNetError("Bidirectional cannot be stepped. Please use unroll")

    @property
    def state_info(self):
        return sum([c.state_info for c in self._cells], [])

    def begin_state(self, **kwargs):
        assert not self._modified
        return sum([c.begin_state(**kwargs) for c in self._cells], [])

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        inputs, _ = _normalize_sequence(length, inputs, layout, False)
        if begin_state is None:
            raise MXNetError("unroll needs begin_state")
        states = begin_state
        l_cell, r_cell = self._cells
        n_l = len(l_cell.state_info)
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs, begin_state=states[:n_l], layout=layout,
            merge_outputs=False)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=list(reversed(inputs)),
            begin_state=states[n_l:], layout=layout, merge_outputs=False)
        outputs = [sym.Concat(l_o, r_o, dim=1,
                              name="%st%d" % (self._output_prefix, i))
                   for i, (l_o, r_o) in enumerate(
                       zip(l_outputs, reversed(r_outputs)))]
        outputs, _ = _normalize_sequence(length, outputs, layout,
                                         merge_outputs)
        return outputs, l_states + r_states
