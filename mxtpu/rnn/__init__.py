"""mx.rnn: legacy RNN namespace (ref: python/mxnet/rnn/).

Round 4 restored the TRUE legacy semantics: the cells here COMPOSE Symbol
graphs (``rnn_cell.py`` — RNNCell/LSTMCell/GRUCell/FusedRNNCell and the
wrapper cells, with ``unroll`` building the time-unrolled graph for the
GraphExecutor/BucketingModule path), exactly as in the reference. The
modern NDArray/hybrid cells live in ``mxtpu.gluon.rnn``. The data-side
utilities (BucketSentenceIter, encode_sentences) are full ports.
"""
from .io import BucketSentenceIter, encode_sentences
from .rnn_cell import (BaseRNNCell, BidirectionalCell, DropoutCell,
                       FusedRNNCell, GRUCell, LSTMCell, ModifierCell,
                       ResidualCell, RNNCell, RNNParams, SequentialRNNCell,
                       ZoneoutCell)

from .rnn import (do_rnn_checkpoint, load_rnn_checkpoint, rnn_unroll,
                  save_rnn_checkpoint)

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "BidirectionalCell",
           "DropoutCell", "ZoneoutCell", "ResidualCell", "ModifierCell",
           "BucketSentenceIter", "encode_sentences", "rnn_unroll",
           "save_rnn_checkpoint", "load_rnn_checkpoint",
           "do_rnn_checkpoint"]
