"""mx.rnn: legacy RNN utilities (ref: python/mxnet/rnn/).

The legacy symbol-composing cells are superseded by gluon.rnn cells (which
trace to compiled graphs via hybridize — the TPU-native path); they are
re-exported here under the legacy names for API familiarity and operate on
NDArrays/hybrid blocks, NOT on Symbols (cell.unroll needs static input
shapes). Symbolic RNN graphs — e.g. BucketingModule sym_gen — use the
fused ``mx.sym.RNN`` op instead, whose packed-parameter/state shapes are
backward-filled by shape inference (tests/test_module.py
test_bucketing_module_trains_over_bucket_sentence_iter shows the
pattern). The data-side utilities (BucketSentenceIter, encode_sentences)
are full ports.
"""
from ..gluon.rnn.rnn_cell import (BidirectionalCell, DropoutCell, GRUCell,
                                  LSTMCell, ModifierCell, RNNCell,
                                  RecurrentCell, ResidualCell,
                                  SequentialRNNCell, ZoneoutCell)
from .io import BucketSentenceIter, encode_sentences

BaseRNNCell = RecurrentCell  # the legacy base covers all cell variants

__all__ = ["RNNCell", "LSTMCell", "GRUCell", "SequentialRNNCell",
           "BidirectionalCell", "DropoutCell", "ZoneoutCell", "ResidualCell",
           "ModifierCell", "BaseRNNCell", "BucketSentenceIter",
           "encode_sentences"]
