"""RNN checkpoint helpers (ref: python/mxnet/rnn/rnn.py:26-130):
save/load with FusedRNNCell weight pack/unpack so fused-blob checkpoints
round-trip through the reference's prefix-epoch file format.
"""
from __future__ import annotations

import warnings

from ..model import load_checkpoint, save_checkpoint
from .rnn_cell import BaseRNNCell

__all__ = ["rnn_unroll", "save_rnn_checkpoint", "load_rnn_checkpoint",
           "do_rnn_checkpoint"]


def rnn_unroll(cell, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC"):
    """Deprecated alias of cell.unroll (ref: rnn.py:26)."""
    warnings.warn("rnn_unroll is deprecated. Please call cell.unroll "
                  "directly.")
    if input_prefix:
        # the reference forwards this to name auto-created inputs; this
        # unroll names inputs explicitly — refuse rather than silently
        # produce differently-named variables
        raise ValueError("input_prefix is not supported: pass inputs= "
                         "explicitly (cell.unroll names them)")
    return cell.unroll(length=length, inputs=inputs, begin_state=begin_state,
                       layout=layout)


def save_rnn_checkpoint(cells, prefix, epoch, symbol, arg_params, aux_params):
    """Save with fused weights UNPACKED (ref: rnn.py:32) — the on-disk
    format holds per-gate arrays; the fused blob is a runtime layout."""
    if isinstance(cells, BaseRNNCell):
        cells = [cells]
    for cell in cells:
        arg_params = cell.unpack_weights(arg_params)
    save_checkpoint(prefix, epoch, symbol, arg_params, aux_params)


def load_rnn_checkpoint(cells, prefix, epoch):
    """Load and re-PACK weights for the given cells (ref: rnn.py:62)."""
    sym, arg, aux = load_checkpoint(prefix, epoch)
    if isinstance(cells, BaseRNNCell):
        cells = [cells]
    for cell in cells:
        arg = cell.pack_weights(arg)
    return sym, arg, aux


def do_rnn_checkpoint(cells, prefix, period=1):
    """Epoch-end callback checkpointing with unpacked weights
    (ref: rnn.py:97; the RNN twin of mx.callback.do_checkpoint)."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            save_rnn_checkpoint(cells, prefix, iter_no + 1, sym, arg, aux)

    return _callback
