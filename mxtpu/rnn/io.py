"""Bucketed sequence iterators (ref: python/mxnet/rnn/io.py)."""
from __future__ import annotations

import random as _pyrandom
from collections import defaultdict

import numpy as np

from ..base import MXNetError
from ..io import DataBatch, DataDesc, DataIter
from ..ndarray import array

__all__ = ["BucketSentenceIter", "encode_sentences"]


def encode_sentences(sentences, vocab=None, invalid_label=-1, invalid_key="\n",
                     start_label=0, unknown_token=None):
    """Encode sentences to integer ids, building the vocab on the fly
    (ref: rnn/io.py:encode_sentences)."""
    idx = start_label
    if vocab is None:
        vocab = {invalid_key: invalid_label}
        new_vocab = True
    else:
        new_vocab = False
    res = []
    for sent in sentences:
        coded = []
        for word in sent:
            if word not in vocab:
                if not new_vocab:
                    if unknown_token:
                        word = unknown_token
                    else:
                        raise MXNetError("Unknown token %s" % word)
                else:
                    if idx == invalid_label:
                        idx += 1
                    vocab[word] = idx
                    idx += 1
            coded.append(vocab[word])
        res.append(coded)
    return res, vocab


class BucketSentenceIter(DataIter):
    """Bucketed iterator for variable-length sequences feeding
    BucketingModule (ref: rnn/io.py:BucketSentenceIter)."""

    def __init__(self, sentences, batch_size, buckets=None, invalid_label=-1,
                 data_name="data", label_name="softmax_label", dtype="float32",
                 layout="NT"):
        super().__init__(batch_size)
        if not buckets:
            counts = defaultdict(int)
            for s in sentences:
                counts[len(s)] += 1
            buckets = [i for i, n in sorted(counts.items()) if n >= batch_size]
            if not buckets:
                buckets = [max(len(s) for s in sentences)]
        buckets.sort()
        self.buckets = buckets
        self.data = [[] for _ in buckets]
        self.invalid_label = invalid_label
        for sent in sentences:
            if len(sent) == 0:
                continue
            buck = next((i for i, b in enumerate(buckets)
                         if b >= len(sent)), None)
            if buck is None:
                continue
            buff = np.full((buckets[buck],), invalid_label, dtype=dtype)
            buff[:len(sent)] = sent
            self.data[buck].append(buff)
        self.data = [np.asarray(x, dtype=dtype) for x in self.data]
        self.batch_size = batch_size
        self.data_name = data_name
        self.label_name = label_name
        self.dtype = dtype
        self.layout = layout
        self.major_axis = layout.find("N")
        self.default_bucket_key = max(buckets)
        self.reset()

    @property
    def provide_data(self):
        shape = (self.batch_size, self.default_bucket_key) \
            if self.major_axis == 0 else \
            (self.default_bucket_key, self.batch_size)
        return [DataDesc(self.data_name, shape, self.dtype,
                         layout=self.layout)]

    @property
    def provide_label(self):
        shape = (self.batch_size, self.default_bucket_key) \
            if self.major_axis == 0 else \
            (self.default_bucket_key, self.batch_size)
        return [DataDesc(self.label_name, shape, self.dtype,
                         layout=self.layout)]

    def reset(self):
        self.curr_idx = 0
        self.idx = []
        for i, buck in enumerate(self.data):
            if len(buck):
                np.random.shuffle(buck)  # in place: reshuffle batch membership
            for j in range(0, len(buck) - self.batch_size + 1,
                           self.batch_size):
                self.idx.append((i, j))
        _pyrandom.shuffle(self.idx)

    def next(self):
        if self.curr_idx == len(self.idx):
            raise StopIteration
        i, j = self.idx[self.curr_idx]
        self.curr_idx += 1
        data = self.data[i][j:j + self.batch_size]
        # label = data shifted left by one (next-token prediction)
        label = np.full_like(data, self.invalid_label)
        label[:, :-1] = data[:, 1:]
        if self.major_axis == 1:
            data = data.T
            label = label.T
        bucket_key = self.buckets[i]
        shape = data.shape
        return DataBatch(
            data=[array(data)], label=[array(label)],
            bucket_key=bucket_key,
            provide_data=[DataDesc(self.data_name, shape, self.dtype,
                                   layout=self.layout)],
            provide_label=[DataDesc(self.label_name, shape, self.dtype,
                                    layout=self.layout)])
