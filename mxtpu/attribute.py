"""Attribute scoping for symbol composition (ref: python/mxnet/attribute.py).

``AttrScope`` attaches attributes to every symbol created inside the scope —
the reference's mechanism for ``__ctx_group__`` model-parallel placement,
``__lr_mult__`` etc.:

    with mx.AttrScope(ctx_group="dev1"):
        net = mx.sym.FullyConnected(net, num_hidden=128)

Scopes nest; inner values win. Consulted by mx.sym op calls
(mxtpu/symbol/__init__.py). Keys are stored with the reference's
``__key__`` dunder convention so symbol JSON round-trips match.
"""
from __future__ import annotations

import threading

__all__ = ["AttrScope", "current_attrs"]


class AttrScope:
    _state = threading.local()

    def __init__(self, **attrs):
        # own attrs only — merging happens at lookup (current_attrs walks
        # the stack), so a scope object can be reused without leaking the
        # first enclosing scope's attrs into later uses
        self._attrs = {"__%s__" % k if not k.startswith("__") else k: str(v)
                       for k, v in attrs.items()}

    def __enter__(self):
        _stack().append(self)
        return self

    def __exit__(self, *exc):
        _stack().pop()
        return False


def _stack():
    st = getattr(AttrScope._state, "stack", None)
    if st is None:
        st = AttrScope._state.stack = []
    return st


def current_attrs():
    """Merged attributes of the active scopes, innermost winning, or {}."""
    merged = {}
    for scope in _stack():
        merged.update(scope._attrs)
    return merged
