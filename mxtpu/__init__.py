"""mxtpu: a TPU-native deep-learning framework with MXNet's capabilities.

A ground-up re-design of the reference (Apache MXNet ~1.3, /root/reference) for
TPU/XLA: the dependency-scheduling engine becomes PJRT async dispatch, the NNVM
graph executor becomes a jit-compile cache, the CUDA/cuDNN operator library becomes
XLA lowerings + Pallas kernels, and the NCCL/parameter-server KVStore becomes XLA
collectives over the device mesh. See SURVEY.md at the repo root for the layer map.

Use ``import mxtpu as mx`` — the namespace mirrors ``import mxnet as mx``.
"""

import os as _os

import jax as _jax

# float32 contractions stay honest f32 (without this, JAX's default silently
# downcasts f32 matmuls to one-pass bf16, breaking reference-parity numerics —
# MXNet computes f32 in f32). bfloat16 contractions do NOT inherit this
# global: every op passes an explicit per-operand override
# (mxtpu/ops/precision_util.py) choosing DEFAULT precision plus an f32
# accumulator output — the measured-fastest MXU schedule (PERF.md; the
# earlier claim that HIGHEST-on-bf16 cost 3-6x was retracted there).
_jax.config.update("jax_default_matmul_precision", "float32")

# persistent compilation cache (MXTPU_COMPILE_CACHE=<dir>): first compiles
# through the TPU tunnel take minutes; caching across processes makes
# repeated bench/tool runs start warm. Opt-in — the default jax in-process
# cache already covers single-process reuse.
_cache_dir = _os.environ.get("MXTPU_COMPILE_CACHE")
if _cache_dir:
    _jax.config.update("jax_compilation_cache_dir", _cache_dir)
    _jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from . import base
from . import context  # module alias (ref: mxnet/context.py)
from .base import Context, MXNetError, cpu, current_context, gpu, num_gpus, tpu
# stdlib-only, imported FIRST among the framework modules: every later
# module (ndarray's d2h counter, the trainer's step phases) may hook it
from . import telemetry
from . import perf_model
from . import xprof
from . import autograd
from .layout import layout
from . import random
from . import ndarray
from . import ndarray as nd  # mx.nd alias
from .ndarray import NDArray
from . import ops
from . import initializer
from . import initializer as init  # mx.init alias
from . import lr_scheduler
from . import optimizer
from . import metric
from . import kvstore
from . import kvstore as kv  # mx.kv alias
from . import symbol
from . import symbol as sym  # mx.sym alias
from . import io
from . import recordio
from . import image
from . import profiler

# MXNET_PROFILER_AUTOSTART parity (ref docs/faq/env_var.md:152): profile
# the whole program without code changes; dump lands in profile.json at
# exit. Both the native and the reference env names are honored.
if _os.environ.get("MXTPU_PROFILER_AUTOSTART",
                   _os.environ.get("MXNET_PROFILER_AUTOSTART", "0")) == "1":
    import atexit as _atexit

    profiler.set_config(filename="profile.json")
    profiler.start()
    _atexit.register(lambda: (profiler.stop(), profiler.dump()))
from . import model
from . import callback
from . import monitor
from .monitor import Monitor
from . import module
from . import module as mod  # mx.mod alias
from . import executor  # mx.executor.Executor spelling (ref: executor.py)
from .module import Module
from . import gluon
from . import operator
from . import contrib
from . import rnn
from . import parallel
from . import fleet
from . import serving
from . import rtc
from . import libinfo
from .libinfo import __version__, feature_list
from . import test_utils
from . import name
from . import attribute
from .attribute import AttrScope
from . import registry
from . import engine
from . import util
from . import visualization
from . import visualization as viz  # mx.viz alias
from . import kvstore_server
from . import executor_manager
from . import log
from . import torch_interop
# reference import hook (kvstore_server.py:75): a DMLC_ROLE=server process
# must fail fast with the migration note, not silently join as a worker
kvstore_server._init_kvstore_server_module()
