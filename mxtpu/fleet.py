"""Elastic multi-host fleet training: bring-up, failure detection, rejoin.

The reference's production layer is the dist kvstore over ps-lite
(src/kvstore/kvstore_dist.h): a scheduler process rendezvouses workers
and servers, and a lost worker simply hangs the Van's TCP connections
until an operator notices. This module is the TPU-native rebuild of that
layer (ROADMAP item 3), made *elastic* — runs survive hardware churn:

* **Coordinated bring-up** — :func:`init` wraps
  ``mxtpu.distributed.init`` (→ ``jax.distributed.initialize``) with
  bounded-retry/backoff connection handling and a DEADLINE on the whole
  join (connect + barrier): a host that never shows up fails the
  bring-up LOUD with per-host status read off the fleet's filesystem
  status board, instead of every healthy host hanging forever inside a
  collective. Per-host data sharding rides the PR 9
  ``shard_keys``/``ShardedRecordReader`` determinism
  (:meth:`Fleet.data_shard`), and :meth:`Fleet.mesh` spans the global
  device set for ``gluon.Trainer(mesh=)``.
* **Failure detection** — :class:`FleetMembership` keeps a per-host
  heartbeat board on the shared fleet directory (the same shared-disk
  assumption checkpoints already make). A host whose heartbeat goes
  stale is diagnosed dead; a dead COORDINATOR (host 0 — the
  jax.distributed rendezvous service lives in that process) raises
  :class:`FleetWedgeError` with the membership view instead of an
  infinite collective hang. :class:`FleetCollectiveWatchdog` generalizes
  the PR 14 step-wedge watchdog to fleet collectives: a step blocked in
  a dead collective trips off-thread, dumps
  ``flight_record("fleet_collective_wedge")`` with the membership
  diagnosis, and (``exit_on_trip``) exits the process loud — the monitor
  cannot raise into a thread wedged inside a device call, so the
  artifact + exit code IS the loud failure.
* **Tiered restore + warm rejoin** — :class:`FleetSupervisor` is
  ``TrainSupervisor``'s fleet mode: per-host child processes with HARD
  timeouts and exit-code surfacing, membership-change events in
  ``history``, and the same poison-crash refusal discipline fleet-wide
  (refusals dump ``flight_record("supervisor_refusal")``). On a lost
  host the next generation launches on the surviving N−1 hosts; the
  child's ``ResilientLoop.resume`` restores the last intact checkpoint
  onto the RESHAPED mesh (orbax re-reads with live shardings and the
  ``MeshPlan`` re-places ZeRO-1 optimizer state), with the divergence
  sentinel as the cross-host consistency gate after restore. Once a
  reshaped generation shows checkpoint progress, the supervisor grows
  the fleet back to full size — the replacement host's rejoin is a
  zero-compile event via the compile-service disk cache
  (``MXTPU_COMPILE_CACHE_DIR``; gated in ``bench.py fleet_resume``).

Fault kinds ``host_loss@step`` (sudden host death — ``os._exit`` before
the step's collective), ``coordinator_loss`` (the membership probe sees
host 0 stale) and ``rejoin_stall`` (a joining host stalls inside
bring-up so its peers' deadline trips) ride ``resilience.inject``, so
the whole matrix runs deterministically in tier-1 via 2-process
fixtures and fake clocks. See docs/resilience.md (degradation matrix)
and docs/parallelism.md (multi-host section).
"""
from __future__ import annotations

import json
import os
import threading
import time

from .base import MXNetError

__all__ = ["Fleet", "FleetBringupError", "FleetWedgeError",
           "FleetMembership", "FleetCollectiveWatchdog", "FleetSupervisor",
           "init", "maybe_host_loss", "EXIT_HOST_LOSS", "EXIT_FLEET_WEDGE",
           "EXIT_REJOIN_STALL"]

import logging

_log = logging.getLogger("mxtpu.fleet")

# Exit codes the supervisor tier pattern-matches on: a sudden host death
# (injected or real SIGKILL-analog), a collective-wedge loud exit, and a
# bring-up stall — all distinct from ordinary crashes so membership events
# in FleetSupervisor.history carry the right diagnosis.
EXIT_HOST_LOSS = 41
EXIT_FLEET_WEDGE = 42
EXIT_REJOIN_STALL = 43


# ------------------------------------------------------------------ policies
def connect_retries():
    """Bring-up connection retry budget (MXTPU_FLEET_CONNECT_RETRIES,
    default 4): how many times :func:`init` re-attempts the
    jax.distributed join before the bring-up fails. Host-side control
    flow — nothing traced."""
    return int(os.environ.get("MXTPU_FLEET_CONNECT_RETRIES", "4"))  # graftlint: disable=policy-key-coverage


def connect_backoff_s():
    """Initial connect-retry backoff (MXTPU_FLEET_CONNECT_BACKOFF_S,
    default 1.0); later waits use decorrelated jitter
    (``resilience._next_backoff``) so a fleet re-joining a restarted
    coordinator cannot stampede it. Host-side — nothing traced."""
    return float(os.environ.get("MXTPU_FLEET_CONNECT_BACKOFF_S", "1.0"))  # graftlint: disable=policy-key-coverage


def bringup_timeout_s():
    """Deadline on the WHOLE bring-up — connect retries plus the
    rendezvous barrier (MXTPU_FLEET_BRINGUP_TIMEOUT_S, default 300 s).
    Past it :func:`init` raises :class:`FleetBringupError` carrying the
    per-host status board instead of hanging in the collective forever.
    Host-side deadline policy — nothing traced."""
    return float(os.environ.get("MXTPU_FLEET_BRINGUP_TIMEOUT_S", "300"))  # graftlint: disable=policy-key-coverage


def heartbeat_s():
    """Heartbeat write cadence on the fleet status board
    (MXTPU_FLEET_HEARTBEAT_S, default 2.0 s). Host-side — nothing
    traced."""
    return float(os.environ.get("MXTPU_FLEET_HEARTBEAT_S", "2.0"))  # graftlint: disable=policy-key-coverage


def heartbeat_miss():
    """Missed-heartbeat threshold (MXTPU_FLEET_HEARTBEAT_MISS, default
    3): a host whose newest heartbeat is older than ``miss × cadence``
    is diagnosed dead by :meth:`FleetMembership.dead_hosts`. Host-side —
    nothing traced."""
    return int(os.environ.get("MXTPU_FLEET_HEARTBEAT_MISS", "3"))  # graftlint: disable=policy-key-coverage


def collective_timeout_s():
    """Fleet collective-wedge bound (MXTPU_FLEET_COLLECTIVE_TIMEOUT_S,
    default 0 = off): a fleet step still armed past this many seconds
    trips :class:`FleetCollectiveWatchdog` — flight artifact with the
    membership diagnosis, then a loud failure. A FIXED bound (not the
    step watchdog's rolling baseline): a dead peer wedges the FIRST
    post-loss collective, long before any baseline exists on the new
    membership. Host-side deadline policy — nothing traced."""
    return float(os.environ.get("MXTPU_FLEET_COLLECTIVE_TIMEOUT_S", "0") or "0")  # graftlint: disable=policy-key-coverage


def child_timeout_s():
    """Per-child hard timeout in :meth:`FleetSupervisor.launch_round`
    (MXTPU_FLEET_CHILD_TIMEOUT_S, default 600 s): a hung child (dead
    collective, stalled rejoin) is killed and surfaced as ``"timeout"``
    instead of wedging the supervisor — and, in tier-1, the test suite.
    Host-side — nothing traced."""
    return float(os.environ.get("MXTPU_FLEET_CHILD_TIMEOUT_S", "600"))  # graftlint: disable=policy-key-coverage


class FleetBringupError(MXNetError):
    """The coordinated bring-up missed its deadline (or spent its connect
    retries): at least one host never joined. The message carries the
    per-host status board — who checked in, who is still connecting, who
    was never heard from — so the operator fixes the right host instead
    of staring at a hung collective."""


class FleetWedgeError(MXNetError):
    """A fleet collective wedged (a step blocked past the fleet bound) or
    the coordinator stopped heartbeating. By the time this raises, the
    flight artifact (``fleet_collective_wedge`` / ``coordinator_loss``)
    with the membership diagnosis is already on disk."""


# ------------------------------------------------------------ status board
def _atomic_write(path, payload):
    tmp = "%s.%d.tmp" % (path, os.getpid())
    with open(tmp, "w") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class FleetMembership:
    """Per-host heartbeat/status board on the shared fleet directory.

    Each host owns ONE file (``host_<rank>.json``) it rewrites
    atomically: status (``connecting`` → ``up`` → ``left``), a heartbeat
    timestamp, pid and the newest training step. Readers never block on
    a peer — liveness is file age, the same shared-disk trust model the
    checkpoint directory already relies on. ``clock`` is injectable so
    the whole staleness matrix tests sleep-free; the heartbeat timestamp
    uses the SAME clock, so fake-clock tests control both sides."""

    def __init__(self, fleet_dir, rank, num_hosts, clock=None):
        self.fleet_dir = str(fleet_dir)
        self.rank = int(rank)
        self.num_hosts = int(num_hosts)
        self._clock = time.time if clock is None else clock
        self._hb_thread = None
        self._hb_stop = None
        self.step = None
        os.makedirs(self.fleet_dir, exist_ok=True)

    def _path(self, rank):
        return os.path.join(self.fleet_dir, "host_%d.json" % rank)

    def write(self, status, step=None):
        """Publish this host's status (atomic rewrite of its board file)."""
        if step is not None:
            self.step = int(step)
        _atomic_write(self._path(self.rank), json.dumps(
            {"rank": self.rank, "status": status, "t": self._clock(),
             "pid": os.getpid(), "step": self.step}))

    def view(self):
        """{rank: record} for every host file present (a host never heard
        from simply has no entry — :meth:`dead_hosts` reports those too)."""
        out = {}
        for r in range(self.num_hosts):
            try:
                with open(self._path(r)) as f:
                    out[r] = json.load(f)
            except Exception:  # noqa: BLE001 — absent/torn file: not seen
                continue
        return out

    def describe(self, view=None):
        """One status line per host — the diagnosis text bring-up and
        wedge errors carry."""
        view = self.view() if view is None else view
        now = self._clock()
        lines = []
        for r in range(self.num_hosts):
            rec = view.get(r)
            if rec is None:
                lines.append("host %d: NEVER SEEN (no status file)" % r)
            else:
                lines.append(
                    "host %d: %s, heartbeat %.1fs ago (pid %s, step %s)"
                    % (r, rec.get("status"), now - rec.get("t", 0.0),
                       rec.get("pid"), rec.get("step")))
        return "; ".join(lines)

    def dead_hosts(self):
        """Ranks diagnosed dead: never seen, or heartbeat older than
        ``heartbeat_s() * heartbeat_miss()`` without a clean ``left``."""
        bound = heartbeat_s() * heartbeat_miss()
        now = self._clock()
        view = self.view()
        dead = []
        for r in range(self.num_hosts):
            rec = view.get(r)
            if rec is None:
                dead.append(r)
            elif rec.get("status") != "left" and \
                    now - rec.get("t", 0.0) > bound:
                dead.append(r)
        return dead

    def coordinator_alive(self):
        return 0 not in self.dead_hosts()

    def check(self, step=None):
        """Membership probe for the training loop / watchdog tier: writes
        this host's heartbeat, returns the dead-host list. A dead
        COORDINATOR is special-cased into a loud
        :class:`FleetWedgeError` — jax.distributed's rendezvous service
        lives in host 0, so once it is gone every later barrier or
        compile-cache coordination would hang, not error. Fault kind
        ``coordinator_loss`` forces that diagnosis deterministically."""
        from . import resilience, telemetry
        self.write("up", step=step)
        dead = self.dead_hosts()
        if resilience.inject("coordinator_loss") and 0 not in dead:
            dead.insert(0, 0)
        if 0 in dead and self.rank != 0:
            view = self.view()
            telemetry.flight_record(
                "coordinator_loss",
                extra={"rank": self.rank, "step": step, "dead": dead,
                       "view": view})
            raise FleetWedgeError(
                "fleet coordinator (host 0) stopped heartbeating — the "
                "jax.distributed rendezvous lives in that process, so "
                "collectives would hang forever, not error. Board: %s. "
                "Flight artifact dumped (reason=coordinator_loss); the "
                "supervisor tier restores onto a re-coordinated fleet."
                % self.describe(view))
        return dead

    # -------------------------------------------------------------- barrier
    def barrier(self, name, timeout_s, payload=None, clock=None,
                sleeper=None, poll_s=0.05, fail_on_dead=True):
        """Filesystem rendezvous on the status board: every host drops
        ``barrier_<name>/host_<rank>`` and polls for the full set under a
        deadline. This is the fleet's control-plane barrier — it works on
        every backend (XLA:CPU cannot run cross-process collectives at
        all, so a device-collective barrier is not portable) and it fails
        DIAGNOSABLY: a peer whose heartbeat went stale mid-wait fails the
        barrier as soon as it is diagnosed dead (``fail_on_dead``) rather
        than at the full deadline, and the raised
        :class:`FleetWedgeError` carries the board. A host that was never
        seen only fails at the deadline — during bring-up "not arrived
        yet" is not "dead". Returns ``{rank: payload}`` of every host's
        barrier payload (the cross-host divergence gate compares
        fingerprints through exactly this)."""
        clock = self._clock if clock is None else clock
        bdir = os.path.join(self.fleet_dir, "barrier_%s" % name)
        os.makedirs(bdir, exist_ok=True)
        mine = os.path.join(bdir, "host_%d" % self.rank)
        _atomic_write(mine, json.dumps({"rank": self.rank,
                                        "payload": payload}))
        deadline = clock() + float(timeout_s)
        while True:
            seen = {}
            for r in range(self.num_hosts):
                try:
                    with open(os.path.join(bdir, "host_%d" % r)) as f:
                        seen[r] = json.load(f).get("payload")
                except Exception:  # noqa: BLE001 — absent/torn: not there
                    continue
            if len(seen) == self.num_hosts:
                return seen
            if fail_on_dead:
                # only STALE hosts (file present, heartbeat old) fail the
                # wait early — dead_hosts() also lists never-seen ranks,
                # which here just have not arrived yet
                view = self.view()
                stale = [r for r in self.dead_hosts()
                         if r in view and r not in seen]
                if stale:
                    raise FleetWedgeError(
                        "fleet barrier %r: host(s) %s died while the "
                        "fleet waited (%d/%d arrived). Board: %s"
                        % (name, stale, len(seen), self.num_hosts,
                           self.describe(view)))
            if clock() > deadline:
                raise FleetWedgeError(
                    "fleet barrier %r missed its %.0fs deadline: %d/%d "
                    "hosts arrived (missing %s). Board: %s"
                    % (name, float(timeout_s), len(seen), self.num_hosts,
                       sorted(set(range(self.num_hosts)) - set(seen)),
                       self.describe()))
            if sleeper is None:
                time.sleep(poll_s)
            else:
                sleeper(poll_s)

    # ------------------------------------------------------------ heartbeat
    def start_heartbeat(self, interval_s=None):
        """Off-thread heartbeat writer (idempotent); fake-clock tests call
        :meth:`write` directly instead."""
        if self._hb_thread is not None and self._hb_thread.is_alive():
            return self
        interval_s = heartbeat_s() if interval_s is None else interval_s
        stop = threading.Event()

        def loop():
            while not stop.wait(interval_s):
                try:
                    self.write("up")
                except Exception:  # noqa: BLE001 — a flaky disk must not
                    pass           # kill the heartbeat thread
        t = threading.Thread(target=loop, daemon=True,
                             name="mxtpu-fleet-heartbeat")
        self._hb_thread, self._hb_stop = t, stop
        t.start()
        return self

    def stop_heartbeat(self):
        if self._hb_stop is not None:
            self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2.0)
        self._hb_thread = self._hb_stop = None


# ------------------------------------------------------- deadline bring-up
def _run_with_deadline(fn, timeout_s, on_timeout, clock=None, sleeper=None,
                       poll_s=0.05, thread_name="mxtpu-fleet-bringup"):
    """Run a possibly-hanging join step on a daemon thread under a
    deadline. On the deadline, ``on_timeout()`` builds the loud error —
    the stuck thread is abandoned (it is blocked inside a native
    rendezvous call nothing can interrupt; bring-up failure is fatal to
    the process anyway). ``clock``/``sleeper`` injectable → sleep-free
    tier-1."""
    clock = time.monotonic if clock is None else clock
    done = threading.Event()
    box = {}

    def run():
        try:
            box["out"] = fn()
        except BaseException as e:  # noqa: BLE001 — surfaced to the caller
            box["err"] = e
        finally:
            done.set()

    t = threading.Thread(target=run, daemon=True, name=thread_name)
    t.start()
    deadline = clock() + timeout_s
    while not done.is_set():
        if clock() > deadline:
            raise on_timeout()
        if sleeper is None:
            done.wait(poll_s)
        else:
            sleeper(poll_s)
    if "err" in box:
        raise box["err"]
    return box.get("out")


class Fleet:
    """Handle returned by :func:`init`: identity, membership, data
    sharding and the global mesh for one joined host."""

    def __init__(self, rank, num_hosts, membership=None, fleet_dir=None):
        self.rank = int(rank)
        self.num_hosts = int(num_hosts)
        self.membership = membership
        self.fleet_dir = fleet_dir

    def mesh(self, axes=None, devices=None):
        """The device mesh for ``gluon.Trainer(mesh=...)`` /
        ``ShardedTrainStep``. Default is pure data-parallel over all
        global devices — except where the backend cannot run
        process-spanning computations at all
        (``distributed.global_compute_supported()`` is False: XLA:CPU,
        the forced-CPU test tier), where each host gets a mesh over its
        OWN devices and cross-host coupling rides the fleet board
        (:meth:`step_barrier`) instead of device collectives."""
        import jax

        from . import distributed
        from .parallel import make_mesh
        if devices is None:
            if distributed.global_compute_supported():
                devices = jax.devices()
            else:
                devices = jax.local_devices()
                _log.info(
                    "fleet mesh: backend %r cannot span processes — "
                    "per-host local mesh over %d device(s), board-"
                    "coupled", jax.default_backend(), len(devices))
        return make_mesh({"data": -1} if axes is None else axes, devices)

    def data_shard(self, keys, epoch=0, seed=0, shuffle=True):
        """This host's deterministic slice of ``keys`` — PR 9
        ``shard_keys``: disjoint per-host shards whose union is exactly
        ``keys``, a pure function of ``(seed, epoch, rank, world)``, so
        a reshaped fleet re-derives balanced shards with no exchange."""
        from .io.stream import shard_keys
        return shard_keys(keys, num_shards=self.num_hosts,
                          shard_index=self.rank, epoch=epoch, seed=seed,
                          shuffle=shuffle)

    def reader(self, rec_path, **kwargs):
        """A ``ShardedRecordReader`` over this host's shard (the PR 9
        deterministic per-replica stream, fleet-wired)."""
        from .io.stream import ShardedRecordReader
        return ShardedRecordReader(rec_path, num_shards=self.num_hosts,
                                   shard_index=self.rank, **kwargs)

    def watchdog(self, timeout_s=None, clock=None, exit_on_trip=False,
                 exit_fn=None):
        """A :class:`FleetCollectiveWatchdog` wired to this fleet's
        membership view."""
        return FleetCollectiveWatchdog(
            membership=self.membership, timeout_s=timeout_s, clock=clock,
            exit_on_trip=exit_on_trip, exit_fn=exit_fn)

    def check(self, step=None):
        """Heartbeat + membership probe (see
        :meth:`FleetMembership.check`); no-op without a fleet dir."""
        if self.membership is None:
            return []
        return self.membership.check(step=step)

    def barrier(self, name="mxtpu_fleet", timeout_s=None, payload=None):
        """Fleet-wide rendezvous. With a membership board this is the
        filesystem barrier (portable, deadline-bounded, diagnosable —
        see :meth:`FleetMembership.barrier`); without one it degrades to
        the device-collective ``distributed.barrier`` (unbounded, but
        the only rendezvous there is). Returns ``{rank: payload}`` on
        the board path, None otherwise."""
        if self.membership is not None:
            if timeout_s is None:
                timeout_s = collective_timeout_s() or bringup_timeout_s()
            return self.membership.barrier(name, timeout_s,
                                           payload=payload)
        from . import distributed
        distributed.barrier(name)
        return None

    def step_barrier(self, step, fingerprint=None, obs=None):
        """Per-step cross-host coupling on the board: every host must
        finish step ``step`` within the fleet collective bound or the
        survivors fail LOUD (a dead peer is diagnosed off its stale
        heartbeat — the portable spelling of "the collective wedged").
        ``fingerprint`` (the divergence sentinel's update fingerprint)
        rides the barrier payload, and a cross-host mismatch — replicas
        whose states silently diverged — trips the same wedge path: the
        flight artifact carries every host's fingerprint. ``obs`` (a
        dict, e.g. ``{"trace": trace_id, "stages": {...}}``) upgrades
        the payload to the ISSUE-19 stitched form — fingerprint under
        ``"fp"``, plus this host's step trace id, stage breakdown, and
        barrier-arrival timestamp ``"t"`` — which fleet_obs' straggler
        sentinel and ``telemetry_report --fleet`` consume. Without
        ``obs`` the payload stays the bare fingerprint list (board
        compatibility with ISSUE-18 peers). No-op without a membership
        board."""
        if self.membership is None:
            return None
        from . import telemetry
        bound = collective_timeout_s() or bringup_timeout_s()
        payload = None if fingerprint is None else list(fingerprint)
        if obs is not None:
            payload = dict(obs)
            payload["fp"] = None if fingerprint is None else list(fingerprint)
            payload.setdefault("t", self.membership._clock())
        try:
            fps = self.membership.barrier(
                "step_%d" % int(step), bound, payload=payload)
        except FleetWedgeError:
            telemetry.inc("fleet.wedges")
            telemetry.flight_record(
                "fleet_collective_wedge",
                extra={"step": int(step), "what": "step barrier",
                       "diagnosis": {
                           "dead": self.membership.dead_hosts(),
                           "board": self.membership.describe()}})
            raise
        got = {}
        for r, p in fps.items():
            fp = p.get("fp") if isinstance(p, dict) else p
            if fp is not None:
                got[r] = fp
        if got:
            telemetry.inc("resilience.divergence_checks")
        if len(set(map(tuple, got.values()))) > 1:
            telemetry.flight_record(
                "fleet_divergence",
                extra={"step": int(step), "fingerprints": {
                    str(r): p for r, p in got.items()}})
            from .resilience import DivergenceError
            raise DivergenceError(
                "cross-host divergence at step %d: update fingerprints "
                "disagree across hosts (%s) — replicated state is no "
                "longer replicated. Flight artifact dumped "
                "(reason=fleet_divergence)." % (int(step), got))
        return fps

    def leave(self):
        """Clean departure: publish ``left`` (so peers diagnose a planned
        exit, not a death), stop the heartbeat, leave the runtime."""
        from . import distributed
        if self.membership is not None:
            self.membership.stop_heartbeat()
            try:
                self.membership.write("left")
            except Exception:  # noqa: BLE001 — board on a dying disk
                pass
        distributed.shutdown()


def _rendezvous_required():
    """Whether bring-up must join the global jax.distributed runtime.
    TPU/GPU fleets: yes — the rendezvous is what fuses every host's
    devices into one mesh. The forced-CPU tier: no — see the board-only
    branch in :func:`init`. Tests monkeypatch this to drive the
    rendezvous deadline/retry machinery on CPU."""
    import jax
    return jax.default_backend() != "cpu"


def init(fleet_dir=None, coordinator_address=None, num_processes=None,
         process_id=None, local_device_ids=None, timeout_s=None,
         clock=None, sleeper=None, rng=None, heartbeat=True, _stall=None):
    """Coordinated multi-host bring-up; returns a :class:`Fleet`.

    The join (``mxtpu.distributed.init`` under bounded
    retry-with-backoff — ``MXTPU_FLEET_CONNECT_RETRIES`` /
    ``MXTPU_FLEET_CONNECT_BACKOFF_S``, decorrelated jitter) plus the
    rendezvous barrier run under ONE deadline
    (``MXTPU_FLEET_BRINGUP_TIMEOUT_S``): a missing host fails the
    bring-up with :class:`FleetBringupError` carrying per-host status
    from the fleet directory's board, instead of hanging every healthy
    host inside the collective. With ``fleet_dir`` (or
    ``MXTPU_FLEET_DIR``) each host publishes ``connecting`` before the
    blocking join and ``up`` after it, then starts the off-thread
    heartbeat — the board is what bring-up timeouts and the supervisor
    tier diagnose from. ``clock``/``sleeper``/``rng`` are injectable for
    sleep-free tests.

    Fault kind ``rejoin_stall@rank`` makes THIS host stall inside
    bring-up (status ``stalled``, never reaches the barrier): its peers'
    deadline trips with the stalled host named, and the process exits
    ``EXIT_REJOIN_STALL`` once the hold expires — the deterministic
    tier-1 spelling of a replacement host that hangs while rejoining."""
    from . import distributed, resilience, telemetry
    fleet_dir = fleet_dir or os.environ.get("MXTPU_FLEET_DIR")  # graftlint: disable=policy-key-coverage
    timeout_s = bringup_timeout_s() if timeout_s is None else float(timeout_s)
    env_coord, env_n, env_id = distributed._env_config()
    world = num_processes if num_processes is not None else env_n
    rank_hint = process_id if process_id is not None else env_id

    mem = None
    if fleet_dir is not None and world is not None and rank_hint is not None:
        mem = FleetMembership(fleet_dir, rank_hint, world, clock=clock)
        mem.write("connecting")

    if resilience.inject("rejoin_stall", rank_hint):
        # the stalled-rejoin simulation: publish the diagnosis, hold past
        # every peer's deadline, then die with the dedicated exit code
        # (the supervisor's child hard-timeout is the outer backstop)
        if mem is not None:
            mem.write("stalled")
        hold = _stall if _stall is not None else (
            lambda: time.sleep(2.0 * timeout_s))
        hold()
        os._exit(EXIT_REJOIN_STALL)

    def on_timeout():
        board = mem.describe() if mem is not None else \
            "no fleet_dir: per-host status unavailable (pass fleet_dir= " \
            "or set MXTPU_FLEET_DIR for a shared status board)"
        telemetry.flight_record(
            "fleet_bringup_timeout",
            extra={"rank": rank_hint, "world": world,
                   "timeout_s": timeout_s,
                   "view": mem.view() if mem is not None else None})
        return FleetBringupError(
            "fleet bring-up missed its %.0fs deadline "
            "(MXTPU_FLEET_BRINGUP_TIMEOUT_S): at least one host never "
            "joined the rendezvous. Board: %s. Flight artifact dumped "
            "(reason=fleet_bringup_timeout)." % (timeout_s, board))

    def join():
        return resilience.with_retries(
            lambda: distributed.init(
                coordinator_address=coordinator_address,
                num_processes=num_processes, process_id=process_id,
                local_device_ids=local_device_ids),
            "fleet join (rank %s)" % rank_hint,
            retries=connect_retries(), backoff=connect_backoff_s(),
            metric="retry.fleet_connect", sleeper=sleeper, rng=rng,
            logger=_log)

    if mem is not None and not _rendezvous_required():
        # board-only bring-up (forced-CPU tier): joining the global jax
        # runtime there buys nothing (XLA:CPU cannot run a
        # process-spanning computation) and actively poisons the
        # compile cache — global device ids bake the host rank into
        # every serialized executable (a blob host 0 spilled names
        # device 0, which host 1 cannot even address), killing the
        # warm-rejoin zero-compile path. Each host stays its own
        # single-process jax world; membership, barriers, and the
        # divergence gate all ride the board.
        rank, world = int(rank_hint), int(world)
    else:
        rank, world = _run_with_deadline(join, timeout_s, on_timeout,
                                         clock=clock, sleeper=sleeper)
    if mem is None and fleet_dir is not None:
        mem = FleetMembership(fleet_dir, rank, world, clock=clock)
    if mem is not None:
        mem.rank, mem.num_hosts = rank, world  # autodetected identity wins
        mem.write("up")
    if mem is not None:
        # board barrier: portable (XLA:CPU cannot run the psum-rendezvous
        # across processes at all), deadline-bounded, and the timeout
        # diagnosis IS the board. fail_on_dead off — during bring-up a
        # host not yet arrived must get the full deadline, not a snap
        # "dead" diagnosis off its missing heartbeat
        try:
            mem.barrier("bringup", timeout_s, clock=clock, sleeper=sleeper,
                        fail_on_dead=False)
        except FleetWedgeError:
            raise on_timeout() from None
    else:
        _run_with_deadline(
            lambda: distributed.barrier("mxtpu_fleet_bringup"),
            timeout_s, on_timeout, clock=clock, sleeper=sleeper,
            thread_name="mxtpu-fleet-barrier")
    if mem is not None and heartbeat:
        mem.start_heartbeat()
    _log.info("fleet up: rank %d of %d hosts", rank, world)
    return Fleet(rank, world, membership=mem, fleet_dir=fleet_dir)


def maybe_host_loss(step):
    """Fault-injection point for sudden host death (kind
    ``host_loss@step``): the process exits ``EXIT_HOST_LOSS`` via
    ``os._exit`` — no cleanup, no ``left`` status, exactly the shape of
    a preempted/zapped host. Call at the top of the training step so the
    survivors wedge in THAT step's collective (the detection path under
    test). ``inject`` has already flight-recorded the fault when this
    fires."""
    from . import resilience
    if resilience.inject("host_loss", step):
        _log.error("injected host_loss at step %d: exiting %d",
                   step, EXIT_HOST_LOSS)
        os._exit(EXIT_HOST_LOSS)


# ------------------------------------------------- fleet collective watchdog
class FleetCollectiveWatchdog:
    """The PR 14 step-wedge watchdog generalized to fleet collectives.

    Same bracket discipline as ``resilience.TrainStepWatchdog`` — arm
    before the step's dispatch, disarm in its finally — but with a FIXED
    deadline (``MXTPU_FLEET_COLLECTIVE_TIMEOUT_S``): after a host loss
    the very FIRST collective wedges, before any rolling baseline could
    exist for the new membership. A trip consults the membership board
    for the diagnosis (which hosts are dead, is the coordinator among
    them), dumps ``flight_record("fleet_collective_wedge")``, bumps
    ``fleet.wedges`` — and then, because the training thread is blocked
    inside a dead collective no exception can reach, ``exit_on_trip``
    exits the process with ``EXIT_FLEET_WEDGE``: the artifact + exit
    code is the loud failure, and the supervisor tier reads the code as
    a host-level event. Fake-clock ``poll()`` drives the whole matrix
    sleep-free in tier-1."""

    def __init__(self, membership=None, timeout_s=None, clock=None,
                 exit_on_trip=False, exit_fn=None):
        self.membership = membership
        self.timeout_s = collective_timeout_s() if timeout_s is None \
            else float(timeout_s)
        self._clock = time.monotonic if clock is None else clock
        self._exit_on_trip = bool(exit_on_trip)
        self._exit_fn = exit_fn if exit_fn is not None else os._exit
        self._lock = threading.Lock()
        self._entries = []
        self._tripped = None
        self._monitor = None
        self._monitor_stop = None

    def arm(self, step, what="collective"):
        self._check_poisoned()
        if self.timeout_s <= 0:
            return None
        now = self._clock()
        entry = {"step": int(step), "what": what, "t0": now,
                 "deadline": now + self.timeout_s}
        with self._lock:
            self._entries.append(entry)
        return entry

    def disarm(self, entry):
        if entry is None:
            return
        with self._lock:
            if entry in self._entries:
                self._entries.remove(entry)
        self._check_poisoned()

    def poll(self):
        """Synchronous wedge scan (fake-clock test drive): raises
        :class:`FleetWedgeError` on a trip, artifact already written."""
        tripped = self._scan()
        if tripped:
            raise FleetWedgeError(self._describe(tripped[0]))

    def _check_poisoned(self):
        if self._tripped is not None:
            raise FleetWedgeError(self._describe(self._tripped))

    def _diagnosis(self):
        if self.membership is None:
            return {"dead": None, "board": "no membership view attached"}
        try:
            dead = self.membership.dead_hosts()
            return {"dead": dead, "coordinator_dead": 0 in dead,
                    "board": self.membership.describe()}
        except Exception as e:  # noqa: BLE001 — a dead disk still trips
            return {"dead": None, "board": "membership read failed: %s" % e}

    def _describe(self, e):
        diag = self._diagnosis()
        return ("fleet %s at step %d wedged: no completion within %.1fs "
                "(MXTPU_FLEET_COLLECTIVE_TIMEOUT_S); dead hosts: %s — %s. "
                "Flight artifact dumped (reason=fleet_collective_wedge)."
                % (e["what"], e["step"], self.timeout_s, diag.get("dead"),
                   diag.get("board")))

    def _scan(self):
        now = self._clock()
        with self._lock:
            tripped = [e for e in self._entries if now > e["deadline"]]
            for e in tripped:
                self._entries.remove(e)
        for e in tripped:
            self._trip(e, now)
        return tripped

    def _trip(self, e, now):
        from . import telemetry
        self._tripped = e
        telemetry.inc("fleet.wedges")
        diag = self._diagnosis()
        telemetry.flight_record(
            "fleet_collective_wedge",
            extra={"step": e["step"], "what": e["what"],
                   "elapsed_s": now - e["t0"], "bound_s": self.timeout_s,
                   "diagnosis": diag})
        _log.error("%s", self._describe(e))
        if self._exit_on_trip:
            self._exit_fn(EXIT_FLEET_WEDGE)

    def start_monitor(self, interval_s=0.25):
        """Off-thread scan (idempotent) — the production drive. The
        monitor holds the watchdog strongly only via the thread target;
        with ``exit_on_trip`` a trip exits the process from HERE, since
        the training thread is unreachable inside the dead collective."""
        if self.timeout_s <= 0:
            return self
        if self._monitor is not None and self._monitor.is_alive():
            return self
        stop = threading.Event()

        def loop():
            while not stop.wait(interval_s):
                try:
                    self._scan()
                except Exception:  # noqa: BLE001 — scan must never die
                    _log.exception("fleet wedge monitor scan failed")
        t = threading.Thread(target=loop, daemon=True,
                             name="mxtpu-fleet-wedge-monitor")
        self._monitor, self._monitor_stop = t, stop
        t.start()
        return self

    def stop_monitor(self):
        if self._monitor_stop is not None:
            self._monitor_stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=2.0)
        self._monitor = self._monitor_stop = None


# ------------------------------------------------------- fleet supervisor
class FleetSupervisor:
    """``TrainSupervisor``'s fleet mode: one supervisor, N per-host
    children per generation.

    ``command_for(rank, world, generation)`` builds each child's argv;
    :meth:`launch_round` gives every child the standard env bootstrap
    (``MXTPU_PROCESS_ID``/``MXTPU_NUM_PROCESSES``/``MXTPU_COORDINATOR``
    on a fresh port per generation, plus the fleet/checkpoint dirs), a
    HARD per-child timeout (``MXTPU_FLEET_CHILD_TIMEOUT_S`` — a hung
    collective is killed and surfaced as ``"timeout"``, it can never
    wedge the caller), and exit-code surfacing into ``history``.

    :meth:`run` is the elastic respawn loop with TrainSupervisor's
    refusal discipline fleet-wide:

    * a generation where some child died with a HOST-LEVEL signature
      (``EXIT_HOST_LOSS``, ``EXIT_FLEET_WEDGE``, a kill, or a timeout)
      relaunches on the surviving world size — membership event
      ``host_loss`` — and the children's tiered resume restores the last
      intact checkpoint onto the reshaped mesh;
    * a reshaped generation that crashes WITH checkpoint progress grows
      back to full size next launch — membership event
      ``rejoin_attempt`` (the replacement host starts warm off the
      compile-service disk cache);
    * two consecutive failed generations at the SAME checkpoint step are
      a poison-crash, and a spent ``MXTPU_SUPERVISOR_RESTARTS`` budget a
      crash-loop — both refuse via :class:`SupervisorRefusal` AFTER
      dumping ``flight_record("supervisor_refusal")`` with ``history``
      and the diagnosis.

    ``launch``/``clock``/``sleeper``/``rng``/``latest_fn`` are injectable
    so the loop tests sleep-free and subprocess-free in tier-1."""

    # codes meaning THIS child's host is gone (shrink the next world by
    # these) vs. codes meaning this child was a healthy VICTIM of someone
    # else's death (its collective wedged / it timed out blocked) — the
    # victims relaunch, so they must not count toward the shrink
    LOST_CODES = (EXIT_HOST_LOSS, EXIT_REJOIN_STALL, -9, -15)
    VICTIM_CODES = (EXIT_FLEET_WEDGE, "timeout")

    def __init__(self, command_for, num_hosts, ckpt_dir=None, fleet_dir=None,
                 max_restarts=None, backoff_s=None, max_backoff_s=60.0,
                 timeout_s=None, min_hosts=1, rejoin=True, env_for=None,
                 launch=None, clock=None, sleeper=None, rng=None,
                 latest_fn=None, logger=None):
        from .resilience import TrainSupervisor  # env defaults shared
        if num_hosts < 1:
            raise MXNetError("FleetSupervisor needs num_hosts >= 1")
        self.command_for = command_for
        self.num_hosts = int(num_hosts)
        self.min_hosts = int(min_hosts)
        self.rejoin = bool(rejoin)
        self.ckpt_dir = ckpt_dir
        self.fleet_dir = fleet_dir
        if max_restarts is None:
            max_restarts = os.environ.get("MXTPU_SUPERVISOR_RESTARTS", "8")  # graftlint: disable=policy-key-coverage
        if backoff_s is None:
            backoff_s = os.environ.get("MXTPU_SUPERVISOR_BACKOFF_S", "2.0")  # graftlint: disable=policy-key-coverage
        self.max_restarts = int(max_restarts)
        self.backoff_s = float(backoff_s)
        self.max_backoff_s = float(max_backoff_s)
        self.timeout_s = child_timeout_s() if timeout_s is None \
            else float(timeout_s)
        self.env_for = env_for
        self._launch = self.launch_round if launch is None else launch
        self._clock = time.monotonic if clock is None else clock
        self._sleeper = time.sleep if sleeper is None else sleeper
        self._rng = rng
        self._latest_fn = latest_fn
        self._log = logger or _log
        self.restarts = 0
        self.history = []  # [{"event": ..., ...}] membership-change log

    def _event(self, event, **detail):
        rec = {"event": event, **detail}
        self.history.append(rec)
        self._log.info("fleet supervisor: %s %s", event, detail)
        return rec

    def _latest(self):
        if self._latest_fn is not None:
            return self._latest_fn()
        if self.ckpt_dir is None:
            return None
        from .contrib import async_checkpoint as ackpt
        try:
            return ackpt.latest_step(self.ckpt_dir)
        except Exception:  # noqa: BLE001 — a broken dir reads as fresh
            return None

    # --------------------------------------------------------------- launch
    @staticmethod
    def _free_port():
        import socket
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def launch_round(self, world, generation, extra_env=None):
        """Launch one fleet generation and reap every child under a HARD
        deadline. Returns ``{rank: (rc, output_tail)}`` where ``rc`` is
        the exit code or the string ``"timeout"`` for a child that had
        to be killed — a hung collective is surfaced, never waited on
        unboundedly (the tier-1 1140 s budget depends on this)."""
        import subprocess
        port = self._free_port()
        procs = {}
        for rank in range(world):
            env = dict(os.environ)
            env.update({
                "MXTPU_COORDINATOR": "127.0.0.1:%d" % port,
                "MXTPU_NUM_PROCESSES": str(world),
                "MXTPU_PROCESS_ID": str(rank),
            })
            if self.fleet_dir is not None:
                # a FRESH board per generation: barrier dirs and host
                # status files from a dead generation must never satisfy
                # (or poison the divergence compare of) the next one
                env["MXTPU_FLEET_DIR"] = os.path.join(
                    str(self.fleet_dir), "gen_%d" % generation)
            if extra_env:
                env.update(extra_env)
            if self.env_for is not None:
                env.update(self.env_for(rank, world, generation) or {})
            procs[rank] = subprocess.Popen(
                self.command_for(rank, world, generation), env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        out = {}
        deadline = time.monotonic() + self.timeout_s
        for rank, p in procs.items():
            budget = max(0.1, deadline - time.monotonic())
            try:
                txt, _ = p.communicate(timeout=budget)
                out[rank] = (p.returncode, (txt or "")[-4000:])
            except subprocess.TimeoutExpired:
                p.kill()
                txt, _ = p.communicate()
                out[rank] = ("timeout", (txt or "")[-4000:])
                self._log.error(
                    "fleet child rank %d/%d (gen %d) hit the %.0fs hard "
                    "timeout and was killed", rank, world, generation,
                    self.timeout_s)
        return out

    # ------------------------------------------------------------------ run
    def run(self, extra_env=None):
        """Drive generations until one exits clean everywhere (returns
        the per-rank results of that generation) or a refusal raises."""
        from . import telemetry
        from .resilience import _next_backoff, _process_rng, _refuse
        delay = self.backoff_s
        prev_crash_step = ()  # sentinel: no failed generation yet
        generation = 0
        world = self.num_hosts
        while True:
            resume_step = self._latest()
            self._event("launch", generation=generation, world=world,
                        resume_step=resume_step)
            results = self._launch(world, generation, extra_env)
            rcs = {r: rc for r, (rc, _) in
                   ((r, v if isinstance(v, tuple) else (v, ""))
                    for r, v in results.items())}
            failed = {r: rc for r, rc in rcs.items() if rc != 0}
            if not failed:
                self._event("clean_exit", generation=generation, world=world)
                return results
            crash_step = self._latest()
            lost = sorted(r for r, rc in failed.items()
                          if rc in self.LOST_CODES)
            victims = sorted(r for r, rc in failed.items()
                             if rc in self.VICTIM_CODES)
            if not lost and victims:
                # every failure is a wedge/timeout with no identified
                # death: someone IS gone (a wedge means a peer stopped
                # answering) but no child owned up — treat the
                # highest-ranked victim as lost so the fleet still
                # shrinks instead of flapping at a size that cannot work
                lost = [victims[-1]]
                victims = victims[:-1]
            self._event("crash", generation=generation, world=world,
                        exit_codes={str(r): rc for r, rc in rcs.items()},
                        ckpt_step=crash_step, lost=lost, victims=victims)
            if crash_step is not None and crash_step == prev_crash_step:
                raise _refuse(
                    "the fleet crashed twice at checkpoint step %s with "
                    "ZERO progress in between (exit codes %s) — a "
                    "deterministic poison-crash; respawning replays it "
                    "forever. Inspect the flight artifacts before "
                    "restarting by hand." % (crash_step, failed),
                    self.history, self._log)
            if self.restarts >= self.max_restarts:
                raise _refuse(
                    "crash-loop budget spent: %d fleet restarts "
                    "(MXTPU_SUPERVISOR_RESTARTS) with children still dying "
                    "(last exit codes %s, last checkpoint step %s) — "
                    "refusing to flap further"
                    % (self.restarts, failed, crash_step),
                    self.history, self._log)
            progressed = crash_step is not None and (
                prev_crash_step == () or crash_step != prev_crash_step)
            prev_crash_step = crash_step
            self.restarts += 1
            generation += 1
            telemetry.inc("supervisor.restarts", tag="fleet")
            if lost and world - len(lost) >= self.min_hosts:
                world = world - len(lost)
                self._event("host_loss", ranks=lost, world=world,
                            ckpt_step=crash_step)
            elif self.rejoin and progressed and world < self.num_hosts:
                world = self.num_hosts
                self._event("rejoin_attempt", world=world,
                            ckpt_step=crash_step)
            self._sleeper(delay)
            delay = _next_backoff(self._rng or _process_rng(),
                                  self.backoff_s, delay, self.max_backoff_s)
