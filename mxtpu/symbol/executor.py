"""Executor: bind a Symbol to arrays and run compiled forward/backward.

Reference: ``include/mxnet/executor.h:56-152`` and GraphExecutor
(src/executor/graph_executor.cc — Init :690, InitDataEntryMemory :927,
RunOps :1318, SimpleBind :1626).

TPU-native re-design: *everything GraphExecutor hand-builds is the XLA
compiler's job*. Bind = allocate/adopt arg arrays; forward = one
``jax.jit``-compiled executable per (is_train, shape signature); backward =
the companion vjp executable (rematerialized, SURVEY §7 stage 3). Memory
planning, inplace detection, op fusion and segment bulking
(InitOpSegs/BulkTrainingOpSegs) have no analog here — XLA does them better.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import autograd
from ..base import MXNetError
from ..ndarray import NDArray, zeros
from .symbol import Symbol

__all__ = ["Executor"]


class Executor:
    def __init__(self, symbol, ctx=None, args=None, args_grad=None,
                 grad_req="write", aux_states=None):
        self._symbol = symbol
        self._ctx = ctx
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()

        if isinstance(args, dict):
            self.arg_dict = {n: args[n] for n in arg_names}
        else:
            if args is None or len(args) != len(arg_names):
                raise MXNetError("bind needs one array per argument %s"
                                 % arg_names)
            self.arg_dict = dict(zip(arg_names, args))
        self.arg_arrays = [self.arg_dict[n] for n in arg_names]

        if aux_states is None:
            aux_states = {}
        if isinstance(aux_states, (list, tuple)):
            aux_states = dict(zip(aux_names, aux_states))
        self.aux_dict = {n: aux_states.get(n) for n in aux_names}
        for n in aux_names:
            if self.aux_dict[n] is None:
                raise MXNetError("bind: missing aux state %s" % n)
        self.aux_arrays = [self.aux_dict[n] for n in aux_names]

        if isinstance(grad_req, str):
            grad_req = {n: grad_req for n in arg_names}
        elif isinstance(grad_req, (list, tuple)):
            grad_req = dict(zip(arg_names, grad_req))
        self._grad_req = grad_req
        if args_grad is None:
            args_grad = {n: zeros(self.arg_dict[n].shape,
                                  dtype=self.arg_dict[n].dtype)
                         for n in arg_names if grad_req.get(n, "null") != "null"}
        elif not isinstance(args_grad, dict):
            args_grad = dict(zip(arg_names, args_grad))
        self.grad_dict = args_grad
        self.grad_arrays = [self.grad_dict.get(n) for n in arg_names]

        self._arg_names = arg_names
        self._aux_names = aux_names
        self._diff_names = [n for n in arg_names
                            if grad_req.get(n, "null") != "null"]
        self.outputs = []
        self._monitor = None
        self._replicate_warned = set()
        self._last = None
        # names bound as feed inputs (data/label); set by simple_bind. When
        # ctx is a jax.sharding.Mesh these are batch-sharded over its 'data'
        # axis and everything else is replicated — the classic Module API's
        # answer to the reference's DataParallelExecutorGroup batch slicing
        # (python/mxnet/module/executor_group.py:281): GSPMD partitions the
        # one compiled program instead of running one executor per device.
        self._input_names = set()

    # ------------------------------------------------------------- factory
    @staticmethod
    def simple_bind(symbol, ctx=None, grad_req="write", type_dict=None,
                    **shapes):
        """Infer shapes from the given input shapes and allocate everything
        (ref: MXExecutorSimpleBind, src/c_api/c_api_executor.cc:224)."""
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        arg_shapes, _, aux_shapes = symbol.infer_shape(**shapes)
        if arg_shapes is None or any(s is None for s in arg_shapes):
            raise MXNetError("simple_bind: cannot infer all shapes from %s"
                             % shapes)
        type_dict = type_dict or {}
        args = {n: zeros(s, dtype=type_dict.get(n, "float32"))
                for n, s in zip(arg_names, arg_shapes)}
        # feed shapes in `shapes` refer to data inputs; honor their dtypes
        aux = {n: zeros(s, dtype=type_dict.get(n, "float32"))
               for n, s in zip(aux_names, aux_shapes)}
        exe = Executor(symbol, ctx=ctx, args=args, grad_req=grad_req,
                       aux_states=aux)
        exe._input_names = set(shapes)
        return exe

    # ------------------------------------------------------------- running
    def _feed(self):
        feed = dict(self.arg_dict)
        feed.update(self.aux_dict)
        return feed

    def forward(self, is_train=False, **kwargs):
        """Run forward; inputs may be updated via kwargs
        (ref: Executor::Forward, graph_executor.cc:64)."""
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError("unknown input %s" % k)
            self.arg_dict[k]._set_data(jnp.asarray(
                v._data if isinstance(v, NDArray) else v,
                dtype=self.arg_dict[k]._data.dtype))
        feed = self._feed()
        self._place_on_mesh(feed)
        prev = autograd.set_training(is_train)
        try:
            if self._monitor is not None:
                self.outputs = self._run_monitored(feed, is_train)
            else:
                self.outputs = self._run_jit(feed, is_train)
        finally:
            autograd.set_training(prev)
        self._last = (dict(feed), is_train)
        return self.outputs

    def _place_on_mesh(self, feed):
        """When bound to a Mesh ctx, commit feed inputs batch-sharded over
        the 'data' axis and parameters replicated; the jit then compiles one
        GSPMD program whose gradient all-reduce is implicit.

        A feed input whose batch dim does not divide the data axis CANNOT be
        sharded — it is replicated, i.e. data parallelism is silently lost
        for it. The reference asserts in this case (decide_slices,
        executor_group.py:281); we warn loudly once per (input, shape)
        instead of degrading in silence (VERDICT r2 weak #6)."""
        import logging
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        if not isinstance(self._ctx, Mesh):
            return
        mesh = self._ctx
        nd = mesh.shape.get("data", 0)
        for name, arr in feed.items():
            if nd and name in self._input_names and arr.shape:
                if arr.shape[0] % nd == 0:
                    spec = P("data")
                else:
                    spec = P()
                    key = (name, arr.shape)
                    if key not in self._replicate_warned:
                        self._replicate_warned.add(key)
                        logging.getLogger(__name__).warning(
                            "Executor on mesh: input %r batch dim %d does "
                            "not divide the 'data' axis (%d devices) — "
                            "replicating it, LOSING data parallelism for "
                            "this input. Pad the batch or resize the mesh.",
                            name, arr.shape[0], nd)
            else:
                spec = P()
            arr._set_data(jax.device_put(arr._data,
                                         NamedSharding(mesh, spec)))

    def _fn_token(self):
        """Stable function identity for the compile service: the symbol
        graph JSON digested once per executor (the graph IS the
        program — a code/topology edit across restarts must miss the
        disk cache)."""
        tok = getattr(self, "_fn_token_cache", None)
        if tok is None:
            import hashlib
            tok = hashlib.sha1(
                self._symbol.tojson().encode("utf-8")).hexdigest()[:16]
            self._fn_token_cache = tok
        return tok

    def _device_token(self):
        from jax.sharding import Mesh
        from .. import compile_service as csvc
        if isinstance(self._ctx, Mesh):
            return csvc.device_token(mesh=self._ctx)
        return csvc.device_token()

    def _run_jit(self, feed, is_train):
        from .. import compile_service as csvc
        from ..ops.registry import policy_key
        names = sorted(feed)
        datas = [feed[n]._data for n in names]
        # the compile service is the cache (LRU-bounded: this dict was
        # previously unbounded under shape churn) — one entry per
        # (symbol graph, train mode, feed signature, policy, device).
        # A run nested under an outer trace (tracer feed) keys its own
        # plain-jit variant: an AOT executable from an earlier eager run
        # of the same signature cannot be invoked with tracers
        example = csvc.concrete_args((datas,))
        key = csvc.canonical_key(
            site="executor", fn_id=self._fn_token(),
            signature=(is_train,) + tuple(
                (k, feed[k].shape, str(feed[k].dtype)) for k in names)
            + (("traced",) if example is None else ()),
            policy=policy_key(), device=self._device_token(),
            nonce=csvc.instance_nonce(self))
        sym = self._symbol
        # retrace watchdog: every executor cache miss is one compile.
        # Ragged final predict batches pad to the bound batch size
        # (BaseModule._pad_batch_to_bound) precisely so this site
        # stays flat through an epoch tail
        def prov():   # lazy: materialized only on a real cache miss
            return {"is_train": is_train,
                    "inputs": [(n, tuple(feed[n].shape)) for n in names
                               if n in getattr(self, "_input_names", ())],
                    "policy_key": list(key.policy)}

        def build():
            def pure(datas):
                fd = {n: NDArray(d) for n, d in zip(names, datas)}
                prev = autograd.set_training(is_train)
                prev_r = autograd.set_recording(False)
                try:
                    aux_updates = {}
                    outs = sym._execute(fd, is_train=is_train,
                                        collect_aux=aux_updates
                                        if is_train else None)
                finally:
                    autograd.set_recording(prev_r)
                    autograd.set_training(prev)
                return ([o._data for o in outs],
                        {k: v._data for k, v in aux_updates.items()})

            return jax.jit(pure)

        entry = csvc.get_or_build(key, build, provenance=prov,
                                  example_args=example)
        out_datas, aux_updates = entry.fn(datas)
        for k, v in aux_updates.items():
            self.aux_dict[k]._set_data(v)
        return [NDArray(d) for d in out_datas]

    def _run_monitored(self, feed, is_train):
        """Uncompiled per-op run so the monitor callback sees every node
        output (ref: MXExecutorSetMonitorCallback / GraphExecutor monitor,
        src/executor/graph_executor.cc:104)."""
        outs = self._symbol._execute(feed, is_train=is_train,
                                     node_hook=self._monitor)
        return outs

    def backward(self, out_grads=None):
        """Gradients into grad_dict honoring grad_req write/add
        (ref: Executor::Backward, graph_executor.cc:77)."""
        if self._last is None:
            raise MXNetError("call forward before backward")
        feed, is_train = self._last
        diff = self._diff_names
        if not diff:
            return
        sym = self._symbol
        names = sorted(feed)
        from .. import compile_service as csvc
        from ..ops.registry import policy_key
        if out_grads is None:
            cots = [jnp.ones_like(o._data) for o in self.outputs]
        else:
            if not isinstance(out_grads, (list, tuple)):
                out_grads = [out_grads]
            cots = [g._data if isinstance(g, NDArray) else jnp.asarray(g)
                    for g in out_grads]
        datas = [feed[n]._data for n in names]
        example = csvc.concrete_args((datas, cots))
        key = csvc.canonical_key(
            site="executor.backward", fn_id=self._fn_token(),
            signature=("bwd", is_train, tuple(diff)) + tuple(
                (k, feed[k].shape, str(feed[k].dtype)) for k in names)
            + (("traced",) if example is None else ()),
            policy=policy_key(), device=self._device_token(),
            nonce=csvc.instance_nonce(self))

        def build():
            def bwd(datas, cots):
                def f(diff_datas):
                    full = dict(zip(names, datas))
                    full.update(dict(zip(diff, diff_datas)))
                    fd = {n: NDArray(d) for n, d in full.items()}
                    prev = autograd.set_training(is_train)
                    prev_r = autograd.set_recording(False)
                    try:
                        outs = sym._execute(fd, is_train=is_train)
                    finally:
                        autograd.set_recording(prev_r)
                        autograd.set_training(prev)
                    return [o._data for o in outs]

                _, vjp_fn = jax.vjp(f, [dict(zip(names, datas))[n]
                                        for n in diff])
                return vjp_fn(cots)[0]

            return jax.jit(bwd)

        entry = csvc.get_or_build(
            key, build,
            provenance=lambda: {"is_train": is_train,
                                "policy_key": list(key.policy)},
            example_args=example)
        grads = entry.fn(datas, cots)
        for n, g in zip(diff, grads):
            tgt = self.grad_dict.get(n)
            if tgt is None:
                continue
            if self._grad_req.get(n) == "add":
                tgt._set_data(tgt._data + g)
            else:
                tgt._set_data(g.astype(tgt._data.dtype))

    # --------------------------------------------------------------- misc
    def set_monitor_callback(self, callback, monitor_all=False):
        self._monitor = callback

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for k, v in arg_params.items():
            if k in self.arg_dict:
                self.arg_dict[k]._set_data(jnp.asarray(
                    v._data, dtype=self.arg_dict[k]._data.dtype))
            elif not allow_extra_params:
                raise MXNetError("Found name \"%s\" not in arguments" % k)
        if aux_params:
            for k, v in aux_params.items():
                if k in self.aux_dict:
                    self.aux_dict[k]._set_data(jnp.asarray(
                        v._data, dtype=self.aux_dict[k]._data.dtype))
                elif not allow_extra_params:
                    raise MXNetError("Found name \"%s\" not in aux" % k)

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Return a new executor for new input shapes (ref: Executor::Reshape)
        — with a jit cache this is just a rebind."""
        arg_names = self._symbol.list_arguments()
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**kwargs)
        args = {}
        for n, s in zip(arg_names, arg_shapes):
            cur = self.arg_dict[n]
            args[n] = cur if tuple(cur.shape) == tuple(s) else \
                zeros(s, dtype=cur.dtype)
        aux = {n: a for n, a in self.aux_dict.items()}
        exe = Executor(self._symbol, ctx=self._ctx, args=args,
                       grad_req=self._grad_req, aux_states=aux)
        exe._input_names = set(self._input_names)
        return exe

    @property
    def output_dict(self):
        return dict(zip(self._symbol.list_outputs(), self.outputs))
