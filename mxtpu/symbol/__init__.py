"""mx.sym namespace: symbolic op functions generated from the op registry.

Reference: ``python/mxnet/symbol/register.py`` codegen — every registered op
gets a symbol-level function that composes graph nodes instead of executing.
"""
from __future__ import annotations

from ..base import MXNetError
from ..ops import registry as _reg
from .symbol import (Group, Symbol, Variable, load, load_json, trace_block,
                     var, _Node, _Counter, _ARG)
from .subgraph import (SubgraphProperty, SubgraphSelector,  # noqa: F401
                       get_subgraph_property, partition,
                       register_subgraph_property)

__all__ = ["Symbol", "var", "Variable", "Group", "load", "load_json",
           "trace_block", "zeros", "ones", "partition",
           "SubgraphProperty", "SubgraphSelector",
           "register_subgraph_property", "get_subgraph_property"]


# Tensor-parameter inputs auto-created as Variables when not supplied —
# reference behavior (python/mxnet/symbol/register.py codegen +
# nnvm ListInputNames): ``sym.Convolution(data, num_filter=k)`` creates
# ``<name>_weight``/``<name>_bias``; output ops create ``<name>_label``
# (which is how the conventional ``softmax_label`` arises).
_AUTO_PARAMS = {
    "Convolution": ("weight", "bias"),
    "Deconvolution": ("weight", "bias"),
    "FullyConnected": ("weight", "bias"),
    "Embedding": ("weight",),
    "BatchNorm": ("gamma", "beta", "moving_mean", "moving_var"),
    "InstanceNorm": ("gamma", "beta"),
    "LayerNorm": ("gamma", "beta"),
    "SoftmaxOutput": ("label",),
    "LinearRegressionOutput": ("label",),
    "LogisticRegressionOutput": ("label",),
    "MAERegressionOutput": ("label",),
    "SVMOutput": ("label",),
}
_PARAM_ORDER_CACHE = {}  # op name -> positional parameter order of op.fn


def _symbolic_call(op_name, *args, name=None, **kwargs):
    """Build a graph node for a registered op (the symbolic twin of
    ndarray._apply)."""
    op = _reg.get_op(op_name)
    in_edges = []
    pos_template = []
    for a in args:
        if isinstance(a, Symbol):
            if len(a._heads) != 1:
                raise MXNetError(
                    "op %s cannot take a multi-output symbol; slice it first"
                    % op_name)
            node, idx = a._heads[0]
            in_edges.append((node, 0 if idx is None else idx))
            pos_template.append(_ARG)
        else:
            pos_template.append(a)
    kw_arrays = []
    attrs = {}
    for k, v in kwargs.items():
        if isinstance(v, Symbol):
            node, idx = v._heads[0]
            in_edges.append((node, 0 if idx is None else idx))
            kw_arrays.append(k)
        else:
            attrs[k] = v
    from ..attribute import current_attrs
    from ..name import current as _current_nm
    nm = _current_nm()
    hint = op.name.lower().lstrip("_")
    if nm is not None:
        name = nm.get(name, hint)
    elif name is None:
        name = "%s%d" % (hint, _Counter.next(op.name.lower()))
    scope_attrs = current_attrs()
    if scope_attrs:
        # scope attrs are defaults; explicit kwargs-derived attrs win
        merged = dict(scope_attrs)
        merged.update(attrs)
        attrs = merged
    auto = _AUTO_PARAMS.get(op.name)
    if auto:
        fn_params = _PARAM_ORDER_CACHE.get(op.name)
        if fn_params is None:
            import inspect as _inspect
            fn_params = list(_inspect.signature(op.fn).parameters)
            _PARAM_ORDER_CACHE[op.name] = fn_params
        supplied = set(fn_params[:len(args)]) | set(kwargs)
        for pname in auto:
            if pname in supplied:
                continue
            if pname == "bias" and attrs.get("no_bias"):
                continue
            vname = (name + "_label" if pname == "label"
                     else "%s_%s" % (name, pname))
            vnode, _ = var(vname)._heads[0]
            in_edges.append((vnode, 0))
            kw_arrays.append(pname)
    # static output count, so sym[i] works BEFORE execution (nnvm knows
    # this statically via FNumOutputs; here: the registry count, overridden
    # by a num_outputs attr for split-style ops)
    rule = _reg.NUM_OUTPUT_RULES.get(op.name)
    n_out = int(rule(attrs) if rule is not None
                else attrs.get("num_outputs", op.num_outputs))
    node = _Node(op.name, name, attrs, in_edges, pos_template, kw_arrays,
                 num_outputs=n_out)
    return Symbol([(node, None)])


def _make_sym_fn(op_name):
    def sym_fn(*args, **kwargs):
        return _symbolic_call(op_name, *args, **kwargs)
    sym_fn.__name__ = op_name
    sym_fn.__doc__ = "Symbolic %s (composes a graph node; see mx.nd.%s)" % (
        op_name, op_name)
    return sym_fn


# generate the namespace (ref: symbol/register.py:143 codegen at import)
for _name in _reg.list_ops():
    if _name not in globals():
        globals()[_name] = _make_sym_fn(_name)
del _name

def __getattr__(name):
    """Ops registered AFTER import (CustomOp, contrib.external_kernel)
    resolve lazily from the registry — the reference regenerates its
    namespace on registration callbacks; a module __getattr__ is the
    python-native equivalent."""
    if name in _reg.REGISTRY:
        fn = _make_sym_fn(name)
        globals()[name] = fn
        return fn
    raise AttributeError("module %r has no attribute %r"
                         % (__name__, name))


# mx.sym.contrib.* — symbolic twin of mx.nd.contrib (ref: symbol/contrib.py)
import sys as _sys  # noqa: E402
import types as _types  # noqa: E402

contrib = _types.ModuleType(__name__ + ".contrib")
for _name in _reg.list_ops():
    if _name.startswith("_contrib_"):
        setattr(contrib, _name[len("_contrib_"):], _make_sym_fn(_name))
_sys.modules[contrib.__name__] = contrib
del _name


def _contrib_getattr(name):
    # late-registered contrib ops (PEP 562 on the synthetic module)
    full = "_contrib_" + name
    if full in _reg.REGISTRY:
        fn = _make_sym_fn(full)
        setattr(contrib, name, fn)
        return fn
    raise AttributeError("module %r has no attribute %r"
                         % (contrib.__name__, name))


contrib.__getattr__ = _contrib_getattr


def _prefixed_sym_module(mod_name, prefix):
    """Synthetic mx.sym.<mod_name> exposing registry ops whose names start
    with ``prefix``, unprefixed — the reference's gen_linalg/gen_image
    codegen modules (python/mxnet/symbol/linalg.py etc.)."""
    m = _types.ModuleType(__name__ + "." + mod_name)
    for nm in _reg.list_ops():
        if nm.startswith(prefix):
            setattr(m, nm[len(prefix):], _make_sym_fn(nm))

    def _getattr(name, _p=prefix, _m=m):
        if _p + name in _reg.REGISTRY:
            fn = _make_sym_fn(_p + name)
            setattr(_m, name, fn)
            return fn
        raise AttributeError("module %r has no attribute %r"
                             % (_m.__name__, name))

    m.__getattr__ = _getattr
    _sys.modules[m.__name__] = m
    return m


linalg = _prefixed_sym_module("linalg", "linalg_")
image = _prefixed_sym_module("image", "_image_")

# mx.sym.random — symbolic sampling twins (ref: python/mxnet/symbol/
# random.py). Conventions mirror mx.nd.random: exponential takes
# mean=scale (the registry op is rate-parameterized).
random = _types.ModuleType(__name__ + ".random")
for _rn in ("uniform", "normal", "poisson", "negative_binomial",
            "generalized_negative_binomial", "multinomial", "randint",
            "shuffle"):
    setattr(random, _rn, _make_sym_fn(_rn))
random.gamma = _make_sym_fn("_random_gamma")


def _sym_random_exponential(scale=1.0, **kwargs):
    return _make_sym_fn("exponential")(lam=1.0 / scale, **kwargs)


def _sym_random_randn(*shape, **kwargs):
    # ref: symbol/random.py randn — normal with *shape positional dims
    return _make_sym_fn("normal")(shape=shape or None, **kwargs)


random.exponential = _sym_random_exponential
random.randn = _sym_random_randn
_sys.modules[random.__name__] = random
del _rn

# mx.sym.sparse — symbolic spellings of the sparse-aware op set (ref:
# python/mxnet/symbol/sparse.py re-exports the gen_sparse ops). The graph
# here executes with dense storage (sparse STORAGE lives on NDArray /
# kvstore row_sparse paths); these spellings keep reference code
# composing, with dense-lowered semantics.
sparse = _types.ModuleType(__name__ + ".sparse")
for _sn in ("dot", "add_n", "elemwise_add", "elemwise_sub", "elemwise_mul",
            "elemwise_div", "zeros_like", "ones_like", "where", "Embedding",
            "LinearRegressionOutput", "make_loss", "relu", "sigmoid",
            "square", "sqrt", "abs", "sum", "mean", "broadcast_add",
            "broadcast_sub", "broadcast_mul", "broadcast_div", "clip",
            "negative"):
    if _sn in _reg.REGISTRY:
        setattr(sparse, _sn, _make_sym_fn(_sn))
# sparse retain/cast_storage live on NDArray (RowSparseNDArray.retain,
# .tostype) — no graph-op twin exists, so mx.sym.sparse has no `retain`
_sys.modules[sparse.__name__] = sparse
del _sn
