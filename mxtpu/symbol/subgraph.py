"""Subgraph / backend-partition framework.

Reference: ``src/operator/subgraph/subgraph_property.h:54-155`` (the
``SubgraphSelector`` / ``SubgraphProperty`` pair + registry),
``partition_graph.cc`` (the partition pass), and
``default_subgraph_property.cc`` (matched region executes as a CachedOp).

TPU-native re-design: the partition pass rewrites the Symbol DAG
(mxtpu/symbol/symbol.py) — a matched region collapses into ONE
``_subgraph_exec`` node whose attr carries the sub-symbol JSON, and the op
executes it as its *own separately-jitted XLA executable* (the CachedOp
analog). Properties can instead emit any replacement node: the bundled
``FlashAttentionProperty`` pattern-matches the unfused
softmax(QK^T * scale)V chain and swaps in the Pallas flash-attention kernel
— the TPU equivalent of the reference's MKLDNN conv-fusion property
(src/operator/subgraph/mkldnn/).
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from .symbol import Symbol, _ARG, _Counter, _Node, _topo

__all__ = ["SubgraphSelector", "SubgraphProperty", "DefaultSubgraphProperty",
           "FlashAttentionProperty", "register_subgraph_property",
           "get_subgraph_property", "partition"]

log = logging.getLogger(__name__)


class SubgraphSelector:
    """Growth policy for one candidate region (ref: subgraph_property.h:54).

    ``select`` seeds a region at a node; ``select_input``/``select_output``
    decide whether to grow across an edge. Defaults grow nothing.
    """

    def select(self, node) -> bool:
        raise NotImplementedError

    def select_input(self, node, input_node) -> bool:
        return False

    def select_output(self, node, output_node) -> bool:
        return False


class SubgraphProperty:
    """A named partition rule (ref: subgraph_property.h:100-155)."""

    name = None

    def create_selector(self) -> SubgraphSelector:
        raise NotImplementedError

    def create_subgraph_node(self, subsym, input_names, external_inputs,
                             name):
        """Build the replacement node, or return None to leave the region
        untouched. Default: a ``_subgraph_exec`` op that runs the
        sub-symbol as its own jit executable (the reference's default
        property runs it as a CachedOp)."""
        node = _Node("_subgraph_exec", name,
                     attrs={"subgraph_json": subsym.tojson(),
                            "input_names": tuple(input_names),
                            "n_outputs": len(subsym._heads)},
                     inputs=list(external_inputs),
                     pos_template=[_ARG] * len(external_inputs),
                     num_outputs=len(subsym._heads))
        return node


_PROPERTIES = {}


def register_subgraph_property(prop: SubgraphProperty):
    """Register a property under ``prop.name``
    (ref: MXNET_REGISTER_SUBGRAPH_PROPERTY)."""
    if not prop.name:
        raise MXNetError("subgraph property needs a name")
    _PROPERTIES[prop.name] = prop
    return prop


def get_subgraph_property(name):
    if name not in _PROPERTIES:
        raise MXNetError("unknown subgraph property %r (registered: %s)"
                         % (name, sorted(_PROPERTIES)))
    return _PROPERTIES[name]


def _consumers(nodes):
    out = {}
    for n in nodes:
        for inp, idx in n.inputs:
            out.setdefault(id(inp), []).append(n)
    return out


def _region_is_convex(region, consumers):
    """No path may leave the region and re-enter it (the reference's cycle
    check in partition_graph.cc) — otherwise the collapsed node would form
    a cycle with the outside graph."""
    region_ids = {id(n) for n in region}
    # nodes reachable strictly downstream of the region through >=1
    # outside node must not include region members
    outside_frontier = []
    for n in region:
        for c in consumers.get(id(n), []):
            if id(c) not in region_ids:
                outside_frontier.append(c)
    seen = set()
    stack = list(outside_frontier)
    while stack:
        n = stack.pop()
        if id(n) in seen:
            continue
        seen.add(id(n))
        if id(n) in region_ids:
            return False
        for c in consumers.get(id(n), []):
            stack.append(c)
    return True


def partition(symbol: Symbol, prop_or_name) -> Symbol:
    """Partition pass (ref: partition_graph.cc BuildSubgraph): grow regions
    per the property's selector, collapse each into a replacement node,
    return a new Symbol. The input symbol is not modified."""
    prop = (get_subgraph_property(prop_or_name)
            if isinstance(prop_or_name, str) else prop_or_name)

    # work on a cloned graph so the caller's symbol stays intact
    sym = _clone(symbol)
    nodes = _topo(sym._heads)
    consumers = _consumers(nodes)
    assigned = set()
    regions = []
    for seed in nodes:
        if seed.is_var() or id(seed) in assigned:
            continue
        sel = prop.create_selector()
        if not sel.select(seed):
            continue
        region = [seed]
        region_ids = {id(seed)}
        frontier = [seed]
        while frontier:
            n = frontier.pop()
            for inp, _idx in n.inputs:
                if inp.is_var() or id(inp) in region_ids \
                        or id(inp) in assigned:
                    continue
                if sel.select_input(n, inp):
                    region.append(inp)
                    region_ids.add(id(inp))
                    frontier.append(inp)
            for c in consumers.get(id(n), []):
                if id(c) in region_ids or id(c) in assigned:
                    continue
                if sel.select_output(n, c):
                    region.append(c)
                    region_ids.add(id(c))
                    frontier.append(c)
        if not _region_is_convex(region, consumers):
            log.warning("subgraph property %s: region at %s is not convex; "
                        "skipped", prop.name, seed.name)
            continue
        assigned |= region_ids
        regions.append(region)

    for region in regions:
        _collapse(sym, region, prop, consumers)
    return sym


def _clone(symbol):
    from .symbol import load_json
    return load_json(symbol.tojson())


def _collapse(sym, region, prop, _consumers_stale):
    """Replace `region` (a set of nodes of sym) with one property node."""
    region_ids = {id(n) for n in region}
    order = [n for n in _topo(sym._heads) if id(n) in region_ids]

    # external input edges, in first-use order (deduped per (node, idx))
    ext_edges = []
    edge_key = {}
    for n in order:
        for inp, idx in n.inputs:
            if id(inp) in region_ids:
                continue
            k = (id(inp), idx)
            if k not in edge_key:
                edge_key[k] = len(ext_edges)
                ext_edges.append((inp, idx))

    # region outputs: head edges or edges consumed outside the region
    out_edges = []
    out_key = {}
    all_nodes = _topo(sym._heads)
    for n in all_nodes:
        if id(n) in region_ids:
            continue
        for inp, idx in n.inputs:
            if id(inp) in region_ids and (id(inp), idx) not in out_key:
                out_key[(id(inp), idx)] = len(out_edges)
                out_edges.append((inp, idx))
    for h, idx in sym._heads:
        i = 0 if idx is None else idx
        if id(h) in region_ids and (id(h), i) not in out_key:
            out_key[(id(h), i)] = len(out_edges)
            out_edges.append((h, i))

    # build the sub-symbol: clone region nodes with external edges as vars
    input_names = []
    var_nodes = {}
    for j, (inp, idx) in enumerate(ext_edges):
        nm = inp.name if inp.is_var() and idx == 0 else "sg_in%d" % j
        input_names.append(nm)
        var_nodes[(id(inp), idx)] = _Node(None, nm, {})
    clones = {}
    for n in order:
        c = _Node(n.op, n.name, dict(n.attrs), [],
                  list(n.pos_template), list(n.kw_arrays),
                  num_outputs=n.num_outputs)
        for inp, idx in n.inputs:
            if id(inp) in region_ids:
                c.inputs.append((clones[id(inp)], idx))
            else:
                c.inputs.append((var_nodes[(id(inp), idx)], 0))
        clones[id(n)] = c
    subsym = Symbol([(clones[id(n)], i) for n, i in out_edges])

    name = "sg_%s%d" % (prop.name, _Counter.next("sg_" + prop.name))
    new_node = prop.create_subgraph_node(subsym, input_names, ext_edges,
                                         name)
    if new_node is None:  # property declined: leave the region as-is
        return

    # rewire consumers and heads to the replacement node's outputs
    for n in _topo(sym._heads):
        if id(n) in region_ids:
            continue
        n.inputs = [
            (new_node, out_key[(id(inp), idx)])
            if id(inp) in region_ids else (inp, idx)
            for inp, idx in n.inputs]
    sym._heads = [
        (new_node, out_key[(id(h), 0 if idx is None else idx)])
        if id(h) in region_ids else (h, idx)
        for h, idx in sym._heads]


# ------------------------------------------------------------ default prop
class _AllOpsSelector(SubgraphSelector):
    def select(self, node):
        return True

    def select_input(self, node, input_node):
        return True

    def select_output(self, node, output_node):
        return True


class DefaultSubgraphProperty(SubgraphProperty):
    """Collapse every connected op region into one separately-jitted
    executable (ref: default_subgraph_property.cc — subgraph as CachedOp)."""

    name = "default"

    def create_selector(self):
        return _AllOpsSelector()


# ----------------------------------------------------- flash-attention prop
def _is_scalar_scale(node):
    """A mul/div applying one python scalar (the scalar aliases resolve to
    the broadcast ops with the literal captured in pos_template). Division
    must have the ARRAY on the left — scalar/x is a reciprocal, not a
    scale."""
    if node is None or node.op not in ("broadcast_mul", "broadcast_div",
                                       "_mul_scalar", "_div_scalar"):
        return False
    if sum(1 for x in node.pos_template if x is _ARG) != 1:
        return False
    if "div" in node.op and (not node.pos_template
                             or node.pos_template[0] is not _ARG):
        return False
    return True


class _AttentionSelector(SubgraphSelector):
    """Matches softmax(batch_dot(q, k) [* scale]) @ v chains."""

    def select(self, node):
        # seed at the softmax over attention scores
        return node.op == "softmax"

    def select_input(self, node, input_node):
        # grow upstream: the scores batch_dot and an optional scalar scale
        if node.op == "softmax" or _is_scalar_scale(node):
            return input_node.op == "batch_dot" \
                or _is_scalar_scale(input_node)
        return False

    def select_output(self, node, output_node):
        # grow downstream from softmax into the probs @ v batch_dot
        return node.op == "softmax" and output_node.op == "batch_dot"


class FlashAttentionProperty(SubgraphProperty):
    """Swap matched attention patterns for the Pallas flash-attention kernel
    (mxtpu/ops/pallas/flash_attention.py) — the TPU analog of the
    reference's MKLDNN fusion properties."""

    name = "flash_attention"

    def create_selector(self):
        return _AttentionSelector()

    def create_subgraph_node(self, subsym, input_names, external_inputs,
                             name):
        info = _match_attention(subsym, input_names)
        if info is None:
            # pattern incomplete (e.g. a lone classifier softmax): leave
            # the region untouched — wrapping it in an opaque subgraph
            # would add a jit boundary for zero benefit
            return None
        q_i, k_i, v_i, scale, transpose_b = info
        node = _Node("_sg_flash_attention", name,
                     attrs={"scale": scale, "transpose_b": transpose_b},
                     inputs=[external_inputs[q_i], external_inputs[k_i],
                             external_inputs[v_i]],
                     pos_template=[_ARG, _ARG, _ARG],
                     num_outputs=1)
        return node


def _match_attention(subsym, input_names):
    """Validate the region is exactly softmax(bdot(q,k)*scale) @ v and
    return (q_idx, k_idx, v_idx, scale, transpose_b) into the region's
    external input list, else None."""
    nodes = _topo(subsym._heads)
    if len(subsym._heads) != 1:
        return None
    final, _ = subsym._heads[0]
    if final.op != "batch_dot":
        return None
    # the probs @ v contraction must be the plain orientation
    if final.attrs.get("transpose_a") or final.attrs.get("transpose_b"):
        return None
    for n in nodes:
        if n.is_var():
            continue
        if n.op not in ("batch_dot", "softmax") and not _is_scalar_scale(n):
            return None
    # walk: final(probs, v); probs = softmax(x); x = [scale ops](scores);
    # scores = batch_dot(q, k)
    (probs_n, _), (v_n, _) = final.inputs[0], final.inputs[1]
    if probs_n.op != "softmax" or not v_n.is_var():
        return None
    # the flash kernel softmaxes over the key axis (last): any explicit
    # non-default softmax axis disqualifies the match
    if probs_n.attrs.get("axis", -1) != -1:
        return None
    cur, _ = probs_n.inputs[0]
    scale = 1.0
    while _is_scalar_scale(cur):
        s = None
        for x in cur.pos_template:
            if x is not _ARG:
                s = float(x)
        if s is None:
            s = float(cur.attrs.get("b", 1.0))
        scale = scale * s if "mul" in cur.op else scale / s
        cur, _ = cur.inputs[0]
    if cur.op != "batch_dot":
        return None
    if cur.attrs.get("transpose_a"):  # q must be row-major queries
        return None
    (q_n, _), (k_n, _) = cur.inputs[0], cur.inputs[1]
    if not (q_n.is_var() and k_n.is_var()):
        return None
    transpose_b = bool(cur.attrs.get("transpose_b", False))
    idx = {nm: i for i, nm in enumerate(input_names)}
    return (idx[q_n.name], idx[k_n.name], idx[v_n.name], scale, transpose_b)


register_subgraph_property(DefaultSubgraphProperty())
register_subgraph_property(FlashAttentionProperty())
