"""Symbol: the deferred-composition graph layer.

Reference: ``python/mxnet/symbol/symbol.py`` (compose/infer_shape/save-load JSON,
simple_bind :1290) over the nnvm graph IR (SURVEY §2.1 "nnvm graph IR").

TPU-native re-design: a Symbol is a lightweight DAG over the op *registry*
(mxtpu/ops/registry.py) — each node stores the registered op name, static
attrs, and input edges. There are no separate shape/type inference passes:
``infer_shape``/``infer_type`` run jax abstract evaluation (``jax.eval_shape``)
over the graph, and the executor (mxtpu/symbol/executor.py) compiles the whole
graph with ``jax.jit`` — XLA performs the memory planning, operator fusion and
scheduling that GraphExecutor (src/executor/graph_executor.cc) hand-built.

Serialization keeps the reference's node-list JSON shape (nodes / arg_nodes /
heads) so graph checkpoints remain diffable and tooling-friendly.
"""
from __future__ import annotations

import ast
import json
import threading

import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError
from ..ndarray import NDArray
from ..ops import registry as _reg

__all__ = ["Symbol", "var", "Variable", "Group", "load", "load_json",
           "trace_block"]

# marker for "an array flows here" inside serialized positional templates
_ARG = "__arg__"


_AUX_SUFFIXES = ("running_mean", "running_var", "moving_mean", "moving_var")


class _Counter:
    _lock = threading.Lock()
    _counts = {}

    @classmethod
    def next(cls, hint):
        with cls._lock:
            c = cls._counts.get(hint, 0)
            cls._counts[hint] = c + 1
            return c


class _Node:
    """One graph node. op None => variable (a free input)."""

    __slots__ = ("op", "name", "attrs", "inputs", "pos_template",
                 "kw_arrays", "num_outputs")

    def __init__(self, op, name, attrs=None, inputs=(), pos_template=None,
                 kw_arrays=(), num_outputs=1):
        self.op = op
        self.name = name
        self.attrs = dict(attrs or {})
        self.inputs = list(inputs)          # [(node, out_index)]
        # how to rebuild the positional call: list of _ARG (array slot,
        # consumed from self.inputs in order) or a literal static value
        self.pos_template = (list(pos_template) if pos_template is not None
                             else [_ARG] * len(self.inputs))
        self.kw_arrays = list(kw_arrays)    # kwarg names that are array slots
        self.num_outputs = num_outputs

    def is_var(self):
        return self.op is None


def _topo(heads):
    """Post-order DFS over nodes reachable from heads (stable input order)."""
    seen = {}
    order = []

    def visit(node):
        if id(node) in seen:
            return
        seen[id(node)] = node
        for inp, _ in node.inputs:
            visit(inp)
        order.append(node)

    for node, _ in heads:
        visit(node)
    return order


class Symbol:
    """A (possibly multi-output) symbolic expression (ref: symbol.py:Symbol)."""

    def __init__(self, heads):
        self._heads = list(heads)  # [(node, out_index)]

    # ------------------------------------------------------------- structure
    @property
    def name(self):
        if len(self._heads) == 1:
            return self._heads[0][0].name
        return None

    def __repr__(self):
        names = ", ".join(n.name for n, _ in self._heads)
        return "<Symbol %s>" % names

    def __iter__(self):
        return (self[i] for i in range(len(self.list_outputs())))

    def __getitem__(self, index):
        outs = self._expand_heads()
        if isinstance(index, str):
            names = self.list_outputs()
            if index not in names:
                raise MXNetError("Cannot find output %s" % index)
            index = names.index(index)
        return Symbol([outs[index]])

    def _expand_heads(self):
        outs = []
        for node, idx in self._heads:
            if idx is None and node.num_outputs > 1:
                outs.extend((node, i) for i in range(node.num_outputs))
            else:
                outs.append((node, 0 if idx is None else idx))
        return outs

    def list_outputs(self):
        names = []
        for node, idx in self._expand_heads():
            if node.num_outputs > 1:
                names.append("%s_output%d" % (node.name, idx))
            else:
                names.append("%s_output" % node.name)
        return names

    def list_inputs(self):
        return [n.name for n in _topo(self._heads) if n.is_var()]

    def list_arguments(self):
        return [name for name in self.list_inputs()
                if not name.endswith(_AUX_SUFFIXES)]

    def list_auxiliary_states(self):
        return [name for name in self.list_inputs()
                if name.endswith(_AUX_SUFFIXES)]

    def get_internals(self):
        """All intermediate outputs as a grouped symbol (ref: get_internals)."""
        return Symbol([(n, 0) for n in _topo(self._heads)])

    def attr(self, key):
        if len(self._heads) == 1:
            v = self._heads[0][0].attrs.get(key)
            return None if v is None else str(v)
        return None

    def list_attr(self):
        if len(self._heads) == 1:
            return {k: str(v) for k, v in self._heads[0][0].attrs.items()}
        return {}

    # ------------------------------------------------------------- compose
    def __call__(self, *args, **kwargs):
        """Compose: replace free variables with other symbols
        (ref: symbol.py Symbol.__call__/_compose)."""
        self._compose(*args, **kwargs)
        return self

    def _compose(self, *args, **kwargs):
        if args:
            # positional: substitute variables in list_inputs order
            names = self.list_inputs()
            if len(args) > len(names):
                raise MXNetError("too many positional composition args")
            kwargs = dict(zip(names, args), **kwargs)
        mapping = {}
        for node in _topo(self._heads):
            if node.is_var() and node.name in kwargs:
                repl = kwargs[node.name]
                if not isinstance(repl, Symbol):
                    raise TypeError("compose expects Symbols")
                if len(repl._heads) != 1:
                    raise MXNetError("cannot compose with multi-output symbol")
                mapping[id(node)] = repl._heads[0]
        if not mapping:
            return
        for node in _topo(self._heads):
            node.inputs = [
                (mapping.get(id(inp), (inp, idx))[0],
                 mapping[id(inp)][1] if id(inp) in mapping else idx)
                for inp, idx in node.inputs]
        self._heads = [
            (mapping.get(id(n), (n, i))[0],
             mapping[id(n)][1] if id(n) in mapping else i)
            for n, i in self._heads]

    # ------------------------------------------------------------- execution
    def _execute(self, feed, is_train=False, collect_aux=None,
                 node_hook=None):
        """Run the graph on NDArrays. feed: name -> NDArray. Returns list of
        output NDArrays per head. When ``collect_aux`` is a dict, training-mode
        BatchNorm nodes deposit (new_running_mean, new_running_var) there —
        the in-kernel aux mutation of the reference (src/operator/nn/
        batch_norm.cc) done functionally. ``node_hook(name, ndarray)`` is
        invoked for every node output — the executor monitor-callback path
        (ref: MXExecutorSetMonitorCallback, graph_executor.cc:104)."""
        values = {}  # id(node) -> list of output NDArrays
        for node in _topo(self._heads):
            if node.is_var():
                if node.name not in feed:
                    raise MXNetError("variable %s is not bound" % node.name)
                values[id(node)] = [feed[node.name]]
                continue
            arrays = [values[id(inp)][idx] for inp, idx in node.inputs]
            it = iter(arrays)
            pos = [next(it) if a is _ARG else a for a in node.pos_template]
            # dunder attrs (__ctx_group__, __lr_mult__, ... from AttrScope)
            # are graph annotations, not op kwargs
            kwargs = {k: v for k, v in node.attrs.items()
                      if not (k.startswith("__") and k.endswith("__"))}
            for k in node.kw_arrays:
                kwargs[k] = next(it)
            op = _reg.get_op(node.op)
            if collect_aux is not None and node.op == "BatchNorm" \
                    and is_train and not kwargs.get("use_global_stats"):
                kwargs["output_mean_var"] = True
                out, mean, var = op.wrapper(*pos, **kwargs)
                momentum = float(kwargs.get("momentum", 0.9))
                # moving_mean/var arrive positionally (explicit 5-input
                # compose) or as kw_arrays (keyword compose, ANY order) —
                # value and destination NAME must come from the same slot,
                # or a reordered compose would write stats into gamma/beta
                npos = sum(1 for a in node.pos_template if a is _ARG)

                def _stat_slot(kw_name, pos_idx):
                    # each stat independently: positional (data, gamma,
                    # beta, moving_mean, moving_var) order, or kw_arrays
                    # at any position — mixed composes are legal
                    if kw_name in node.kw_arrays:
                        return (kwargs[kw_name],
                                npos + node.kw_arrays.index(kw_name))
                    return pos[pos_idx], pos_idx

                rm, mm_i = _stat_slot("moving_mean", 3)
                rv, mv_i = _stat_slot("moving_var", 4)
                collect_aux[node.inputs[mm_i][0].name] = \
                    rm * momentum + mean * (1 - momentum)
                collect_aux[node.inputs[mv_i][0].name] = \
                    rv * momentum + var * (1 - momentum)
                res = out
            else:
                res = op.wrapper(*pos, **kwargs)
            outs = list(res) if isinstance(res, (list, tuple)) else [res]
            node.num_outputs = len(outs)
            values[id(node)] = outs
            if node_hook is not None:
                for i, o in enumerate(outs):
                    nm = "%s_output" % node.name if len(outs) == 1 \
                        else "%s_output%d" % (node.name, i)
                    node_hook(nm, o)
        return [values[id(n)][i] for n, i in self._expand_heads()]

    def eval(self, ctx=None, **kwargs):
        """Evaluate with NDArray bindings (ref: symbol.py:eval). Returns a
        list of NDArrays."""
        return self._execute(kwargs)

    # ------------------------------------------------------------ inference
    def infer_shape(self, **kwargs):
        """(arg_shapes, out_shapes, aux_shapes) via jax abstract eval —
        the InferShape pass (src/executor/infer_graph_attr_pass.cc) for free."""
        args, outs, auxs = self._infer(kwargs, want="shape")
        return args, outs, auxs

    def infer_type(self, **kwargs):
        args, outs, auxs = self._infer(kwargs, want="dtype")
        return args, outs, auxs

    def _infer(self, hints, want="shape"):
        """Forward shape/type propagation with per-op parameter completion —
        the TPU-native InferShape pass. Known input specs flow through each
        node via per-node jax abstract eval; unknown *parameter* inputs
        (weights/bias/stats) are filled by the registry's per-op
        backward-fill rules (mxtpu/ops/registry.py PARAM_SHAPE_RULES), the
        analog of each reference op's FInferShape filling in unknowns
        (e.g. fully_connected.cc weight = (num_hidden, in_units))."""
        import jax

        nodes = _topo(self._heads)
        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()

        specs = {}  # var name -> ShapeDtypeStruct | None
        for n in nodes:
            if not n.is_var():
                continue
            if want == "dtype" and n.name in hints:
                shape = n.attrs.get("__shape__")
                dtype = hints[n.name]
            else:
                shape = hints.get(n.name, n.attrs.get("__shape__"))
                dtype = n.attrs.get("__dtype__", "float32")
            specs[n.name] = (jax.ShapeDtypeStruct(tuple(shape),
                                                  jnp.dtype(dtype))
                             if shape is not None else None)

        values = {}  # id(node) -> list[ShapeDtypeStruct] | None
        for node in nodes:
            if node.is_var():
                values[id(node)] = ([specs[node.name]]
                                    if specs[node.name] is not None else None)
                continue
            in_specs = [values[id(inp)][idx]
                        if values[id(inp)] is not None else None
                        for inp, idx in node.inputs]
            hook = _reg.get_param_shape_rule(node.op)
            if hook is not None and any(s is None for s in in_specs):
                filled = hook([None if s is None else tuple(s.shape)
                               for s in in_specs], node.attrs)
                for i, shape in (filled or {}).items():
                    inp, idx = node.inputs[i]
                    if inp.is_var() and specs.get(inp.name) is None \
                            and shape is not None:
                        dt = inp.attrs.get("__dtype__", "float32")
                        specs[inp.name] = jax.ShapeDtypeStruct(
                            tuple(shape), jnp.dtype(dt))
                        values[id(inp)] = [specs[inp.name]]
                        in_specs[i] = specs[inp.name]
            if any(s is None for s in in_specs):
                values[id(node)] = None
                continue
            values[id(node)] = self._abstract_node(node, in_specs)

        get = (lambda s: None if s is None else tuple(s.shape)) \
            if want == "shape" else (lambda s: None if s is None else s.dtype)
        outs = []
        for n, i in self._expand_heads():
            v = values[id(n)]
            outs.append(None if v is None else get(v[i]))
        return ([get(specs[n]) for n in arg_names],
                outs,
                [get(specs[n]) for n in aux_names])

    @staticmethod
    def _abstract_node(node, in_specs):
        """Abstract-eval one node (shapes/dtypes only, nothing computed)."""
        import jax

        op = _reg.get_op(node.op)

        def f(datas):
            arrays = [NDArray(d) for d in datas]
            it = iter(arrays)
            pos = [next(it) if a is _ARG else a for a in node.pos_template]
            # dunder attrs (__ctx_group__, __lr_mult__, ... from AttrScope)
            # are graph annotations, not op kwargs
            kwargs = {k: v for k, v in node.attrs.items()
                      if not (k.startswith("__") and k.endswith("__"))}
            for k in node.kw_arrays:
                kwargs[k] = next(it)
            res = op.wrapper(*pos, **kwargs)
            outs = list(res) if isinstance(res, (list, tuple)) else [res]
            return [o._data for o in outs]

        out = jax.eval_shape(f, list(in_specs))
        node.num_outputs = len(out)
        return list(out)

    # ---------------------------------------------------------------- bind
    def simple_bind(self, ctx=None, grad_req="write", **kwargs):
        from .executor import Executor
        return Executor.simple_bind(self, ctx=ctx, grad_req=grad_req, **kwargs)

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, **_ignored):
        from .executor import Executor
        return Executor(self, ctx=ctx, args=args, args_grad=args_grad,
                        grad_req=grad_req, aux_states=aux_states)

    # ------------------------------------------------------------ serialize
    def tojson(self):
        nodes = _topo(self._heads)
        nid = {id(n): i for i, n in enumerate(nodes)}
        jnodes = []
        for n in nodes:
            jnodes.append({
                "op": "null" if n.is_var() else n.op,
                "name": n.name,
                "attrs": {k: repr(v) for k, v in n.attrs.items()},
                "inputs": [[nid[id(inp)], idx, 0] for inp, idx in n.inputs],
                "pos_template": [x if x is _ARG else repr(x)
                                 for x in n.pos_template],
                "kw_arrays": list(n.kw_arrays),
                "num_outputs": n.num_outputs,
            })
        return json.dumps({
            "nodes": jnodes,
            "arg_nodes": [i for i, n in enumerate(nodes) if n.is_var()],
            "heads": [[nid[id(n)], 0 if i is None else i, 0]
                      for n, i in self._heads],
            "attrs": {"mxtpu_version": 1},
        }, indent=2)

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    # ----------------------------------------------------------- operators
    def __add__(self, other):
        return _binary("broadcast_add", "_plus_scalar", self, other)

    def __radd__(self, other):
        return self.__add__(other)

    def __sub__(self, other):
        return _binary("broadcast_sub", "_minus_scalar", self, other)

    def __rsub__(self, other):
        return _binary("broadcast_sub", "_rminus_scalar", self, other, rev=True)

    def __mul__(self, other):
        return _binary("broadcast_mul", "_mul_scalar", self, other)

    def __rmul__(self, other):
        return self.__mul__(other)

    def __truediv__(self, other):
        return _binary("broadcast_div", "_div_scalar", self, other)

    def __rtruediv__(self, other):
        return _binary("broadcast_div", "_rdiv_scalar", self, other, rev=True)

    def __pow__(self, other):
        return _binary("broadcast_power", "_power_scalar", self, other)

    def __neg__(self):
        return self.__mul__(-1.0)

    def __getattr__(self, name):
        # generated method surface: sym.reshape(...) -> symbolic op
        if name.startswith("_"):
            raise AttributeError(name)
        try:
            _reg.get_op(name)
        except KeyError:
            raise AttributeError(name)
        from . import _symbolic_call
        return lambda *a, **kw: _symbolic_call(name, self, *a, **kw)


def _binary(op_name, scalar_op, lhs, rhs, rev=False):
    # scalar variants are registered as (x, scalar) positional aliases of the
    # broadcast ops (mxtpu/ops/elemwise.py; _r* variants already reversed)
    from . import _symbolic_call
    if isinstance(rhs, Symbol):
        return _symbolic_call(op_name, lhs, rhs)
    try:
        _reg.get_op(scalar_op)
        return _symbolic_call(scalar_op, lhs, float(rhs))
    except KeyError:
        raise MXNetError("scalar op %s not registered" % scalar_op)


def var(name, attr=None, shape=None, dtype=None, init=None, **kwargs):
    """Create a variable symbol (ref: symbol.py:var). Active AttrScope
    attributes (mx.AttrScope) apply as defaults, like the reference."""
    from ..attribute import current_attrs
    attrs = current_attrs()
    attrs.update(attr or {})
    if shape is not None:
        attrs["__shape__"] = tuple(shape)
    if dtype is not None:
        attrs["__dtype__"] = str(_np.dtype(dtype)) if dtype is not None else None
    attrs.update(kwargs)
    return Symbol([(_Node(None, name, attrs), None)])


Variable = var


def Group(symbols):
    heads = []
    for s in symbols:
        heads.extend(s._expand_heads())
    return Symbol(heads)


def load_json(json_str):
    data = json.loads(json_str)
    nodes = []
    for jn in data["nodes"]:
        attrs = {k: _literal(v) for k, v in jn.get("attrs", {}).items()}
        op = None if jn["op"] == "null" else jn["op"]
        node = _Node(op, jn["name"], attrs,
                     num_outputs=jn.get("num_outputs", 1))
        node.pos_template = [_ARG if x == _ARG else _literal(x)
                             for x in jn.get("pos_template", [])]
        node.kw_arrays = list(jn.get("kw_arrays", []))
        nodes.append(node)
    for node, jn in zip(nodes, data["nodes"]):
        node.inputs = [(nodes[i], idx) for i, idx, _ in jn.get("inputs", [])]
        if not jn.get("pos_template"):
            node.pos_template = [_ARG] * len(node.inputs)
    heads = [(nodes[i], idx) for i, idx, _ in data["heads"]]
    return Symbol(heads)


def _literal(s):
    try:
        return ast.literal_eval(s)
    except (ValueError, SyntaxError):
        return s


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


# --------------------------------------------------------------- block trace
class _SymTape(threading.local):
    def __init__(self):
        self.active = None   # dict: id(NDArray) -> (node, out_idx)
        self.names = None


_SYM_TAPE = _SymTape()


def record_apply(op_name, args, kwargs, inputs, outputs):
    """Hook called by ndarray._apply when symbol tracing is active: appends the
    op call to the graph under construction (the analog of autograd's RecordOp
    for graph export)."""
    tape = _SYM_TAPE.active
    if tape is None:
        return
    in_edges = []
    for x in inputs:
        if id(x) not in tape:
            # unseen array entering the graph: promote to a variable
            name = "extra%d" % _Counter.next("extra")
            tape[id(x)] = (_Node(None, name, {}), 0)
        in_edges.append(tape[id(x)])
    pos_template = [_ARG if isinstance(a, NDArray) else a for a in args]
    kw_arrays = [k for k, v in kwargs.items() if isinstance(v, NDArray)]
    attrs = {k: v for k, v in kwargs.items() if not isinstance(v, NDArray)}
    name = "%s%d" % (op_name.lower(), _Counter.next(op_name.lower()))
    node = _Node(op_name, name, attrs, in_edges, pos_template, kw_arrays,
                 num_outputs=len(outputs))
    for i, o in enumerate(outputs):
        tape[id(o)] = (node, i)


def trace_block(block, *example_inputs):
    """Trace a HybridBlock's forward into a Symbol (used by Block.export and
    SymbolBlock; ref: gluon exports hybridized CachedOp graphs,
    python/mxnet/gluon/block.py:870).

    Traces in inference mode — BatchNorm uses global stats and Dropout is
    identity, matching the reference's deploy export. Returns
    ``(symbol, arg_names)``.
    """
    from .. import autograd
    from ..ndarray import zeros

    if not example_inputs:
        specs = getattr(block, "_in_specs", None)
        if not specs:
            raise MXNetError(
                "export/trace requires the block to have run at least once "
                "(or pass example inputs)")
        example_inputs = [zeros(s, dtype=d) for s, d in specs]

    tape = {}
    data_names = []
    for i, x in enumerate(example_inputs):
        name = "data" if i == 0 else "data%d" % i
        tape[id(x)] = (_Node(None, name, {"__shape__": tuple(x.shape),
                                          "__dtype__": str(x.dtype)}), 0)
        data_names.append(name)
    # parameters become named variables
    for pname, p in block.collect_params().items():
        if p._data is not None:
            tape[id(p.data())] = (_Node(None, pname, {}), 0)

    from ..gluon.block import _IN_TRACE

    prev = autograd.set_training(False)
    _SYM_TAPE.active = tape
    _IN_TRACE.active += 1  # force eager forward (bypass CachedOp jit)
    try:
        out = block(*example_inputs)
    finally:
        _IN_TRACE.active -= 1
        _SYM_TAPE.active = None
        autograd.set_training(prev)

    outs = out if isinstance(out, (list, tuple)) else [out]
    heads = []
    for o in outs:
        if id(o) not in tape:
            raise MXNetError("block output was not produced by registered ops")
        heads.append(tape[id(o)])
    sym = Symbol(heads)
    return sym, sym.list_arguments()
