"""Misc utilities (ref: python/mxnet/util.py)."""
from __future__ import annotations

import functools
import os

__all__ = ["makedirs", "get_gpu_count", "get_gpu_memory", "use_np_shape",
           "is_np_shape"]


def makedirs(d):
    """Create directory recursively, tolerating existing dirs
    (ref: util.py:makedirs)."""
    os.makedirs(os.path.expanduser(d), exist_ok=True)


def get_gpu_count():
    """Accelerator count (the reference counts CUDA GPUs; here TPU chips)."""
    import jax
    try:
        return len([d for d in jax.devices() if d.platform != "cpu"])
    except Exception:  # noqa: BLE001 - backend not initialized
        return 0


def get_gpu_memory(dev_id=0):
    """(free, total) accelerator memory in bytes when the backend exposes
    it, else (0, 0). One source of truth: ``xprof.device_memory`` owns
    the stats-key fallbacks, so this, the C-ABI
    ``MXGetGPUMemoryInformation``, and the live ``memory.hbm_*`` gauges
    can never disagree."""
    from . import xprof
    m = xprof.device_memory(dev_id)
    if not m["bytes_limit"]:  # stats dict is backend-dependent; never
        return 0, 0           # report negative free on a missing limit
    return m["bytes_free"], m["bytes_limit"]


def is_np_shape():
    """NumPy-shape semantics flag — always True here: zero-size and scalar
    shapes are native to jax, so the legacy 0=unknown convention of the
    reference never applies (ref: util.py:is_np_shape)."""
    return True


def use_np_shape(func):
    """Decorator kept for API compatibility (np-shape is always on)."""
    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        return func(*args, **kwargs)
    return wrapper
