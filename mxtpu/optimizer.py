"""Optimizer registry + the full update-rule zoo.

Reference: python/mxnet/optimizer/optimizer.py:41-1300 (registry, Updater,
multi-precision fp16 master weights :500, SGD/Signum/FTML/LBSGD/DCASGD/NAG/SGLD/
Adam/AdaGrad/RMSProp/AdaDelta/Ftrl/Adamax/Nadam) and the in-engine update kernels
src/operator/optimizer_op.cc.

TPU-native re-design: each optimizer's ``update`` applies a pure jnp update fn
(mxtpu/ops/optimizer_ops.py) to the NDArray payloads; when driven from the jitted
Trainer step the whole parameter update fuses into the step executable (the
reference's motivation for making updates *ops* — SURVEY §2.2 optimizer_op).
Multi-precision: bf16/fp16 weights keep an f32 master copy, like mp_sgd_update.
"""
from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as _np

from .base import MXNetError
from .ndarray import NDArray, array
from .ops import optimizer_ops as _uo

__all__ = ["Optimizer", "Updater", "create", "register", "get_updater",
           "SGD", "Signum", "FTML", "DCASGD", "NAG", "SGLD", "Adam", "AdaGrad",
           "RMSProp", "AdaDelta", "Ftrl", "Adamax", "Nadam", "LBSGD", "Test"]


class Optimizer:
    """Base optimizer (ref: optimizer.py:Optimizer). Holds lr/wd schedules,
    per-param lr_mult/wd_mult, update counts for bias correction."""

    opt_registry = {}

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.multi_precision = multi_precision
        self.param_idx2name = param_idx2name or {}
        self.param_dict = param_dict or {}
        self.idx2name = dict(self.param_idx2name)
        self.lr_mult = {}
        self.wd_mult = {}

    # -- registry ---------------------------------------------------------
    @staticmethod
    def register(klass):
        name = klass.__name__.lower()
        Optimizer.opt_registry[name] = klass
        return klass

    @staticmethod
    def create_optimizer(name, **kwargs):
        if name.lower() not in Optimizer.opt_registry:
            raise MXNetError("Cannot find optimizer %s" % name)
        return Optimizer.opt_registry[name.lower()](**kwargs)

    # -- lr/wd ------------------------------------------------------------
    def set_learning_rate(self, lr):
        self.lr = lr
        if self.lr_scheduler is not None:
            self.lr_scheduler.base_lr = lr

    @property
    def learning_rate(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    @learning_rate.setter
    def learning_rate(self, lr):
        self.set_learning_rate(lr)

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = dict(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler else self.lr
        name = self.idx2name.get(index, index if isinstance(index, str) else None)
        if name in self.param_dict:
            lr *= self.param_dict[name].lr_mult
        elif name in self.lr_mult:
            lr *= self.lr_mult[name]
        return lr

    def _get_wd(self, index):
        wd = self.wd
        name = self.idx2name.get(index, index if isinstance(index, str) else None)
        if name in self.param_dict:
            wd *= self.param_dict[name].wd_mult
        elif name in self.wd_mult:
            wd *= self.wd_mult[name]
        return wd

    # -- state ------------------------------------------------------------
    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and weight.dtype in (jnp.float16, jnp.bfloat16):
            master = weight.astype("float32")
            return (master, self.create_state(index, master))
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):  # pragma: no cover - abstract
        raise NotImplementedError

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and weight.dtype in (jnp.float16, jnp.bfloat16):
            master, base_state = state
            g32 = grad.astype("float32")
            self.update(index, master, g32, base_state)
            weight._set_data(master._data.astype(weight._data.dtype))
        else:
            self.update(index, weight, grad, state)

    def _common_kwargs(self, index):
        return dict(rescale_grad=self.rescale_grad,
                    clip_gradient=self.clip_gradient if self.clip_gradient else -1.0)


register = Optimizer.register
create = Optimizer.create_optimizer


def _zeros_like_state(weight):
    """Factory for optimizer state slots: each call allocates a DISTINCT
    zeros buffer. The fused update path (optimizer_fused.py) donates every
    state leaf to XLA; slots sharing one array would donate the same buffer
    twice and kick the whole step back to the eager loop."""
    shape, dtype = weight.shape, weight._data.dtype

    def make():
        return NDArray(jnp.zeros(shape, dtype))
    return make


@register
class SGD(Optimizer):
    """SGD ± momentum, multi-precision, lazy sparse update
    (ref: optimizer.py:SGD; kernels src/operator/optimizer_op.cc sgd_update)."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return NDArray(jnp.zeros(weight.shape, weight._data.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        from .ndarray.sparse import RowSparseNDArray
        if isinstance(grad, RowSparseNDArray):
            self._sparse_update(weight, grad, state, lr, wd)
            return
        if state is None:
            _uo.sgd_update(weight, grad, lr, wd=wd, **self._common_kwargs(index))
        else:
            _uo.sgd_mom_update(weight, grad, state, lr, momentum=self.momentum, wd=wd,
                               **self._common_kwargs(index))

    def _sparse_update(self, weight, grad, state, lr, wd):
        """Lazy update: only rows present in the gradient move (ref: sgd-inl lazy)."""
        rows = grad._aux["indices"]
        g = grad._data * self.rescale_grad
        if self.clip_gradient:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        w = weight._data
        wr = w[rows]
        g = g + wd * wr
        if state is None:
            weight._set_data(w.at[rows].add(-lr * g))
        else:
            m = state._data
            m_rows = self.momentum * m[rows] - lr * g
            state._set_data(m.at[rows].set(m_rows))
            weight._set_data(w.at[rows].add(m_rows))


@register
class NAG(Optimizer):
    """Nesterov accelerated SGD (ref: optimizer.py:NAG)."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return NDArray(jnp.zeros(weight.shape, weight._data.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        if state is None:
            _uo.sgd_update(weight, grad, lr, wd=wd, **self._common_kwargs(index))
        else:
            _uo.nag_mom_update(weight, grad, state, lr, momentum=self.momentum, wd=wd,
                               **self._common_kwargs(index))


@register
class Signum(Optimizer):
    """SignSGD + momentum (ref: optimizer.py:Signum)."""

    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return NDArray(jnp.zeros(weight.shape, weight._data.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        if state is None:
            _uo.signsgd_update(weight, grad, lr, wd=wd, **self._common_kwargs(index))
        else:
            _uo.signum_update(weight, grad, state, lr, momentum=self.momentum, wd=wd,
                              wd_lh=self.wd_lh, **self._common_kwargs(index))


@register
class FTML(Optimizer):
    """Follow the Moving Leader (ref: optimizer.py:FTML)."""

    def __init__(self, beta1=0.6, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        z = _zeros_like_state(weight)  # distinct buffers: the fused step
        return (z(), z(), z())           # DONATES each leaf (d, v, z)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        d, v, z = state
        new_w, new_d, new_v, new_z = _uo.ftml_update_fn(
            weight._data, grad._data, d._data, v._data, z._data, lr, t,
            beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon, wd=wd,
            rescale_grad=self.rescale_grad,
            clip_grad=self.clip_gradient if self.clip_gradient else -1.0)
        weight._set_data(new_w)
        d._set_data(new_d); v._set_data(new_v); z._set_data(new_z)


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (ref: optimizer.py:DCASGD)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lamda = lamda

    def create_state(self, index, weight):
        mom = NDArray(jnp.zeros(weight.shape, weight._data.dtype)) if self.momentum else None
        prev = NDArray(weight._data)
        return (mom, prev)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        mom, prev = state
        g = grad._data * self.rescale_grad
        if self.clip_gradient:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        g = g + wd * weight._data
        comp = g + self.lamda * g * g * (weight._data - prev._data)
        if mom is None:
            step = -lr * comp
        else:
            m = self.momentum * mom._data - lr * comp
            mom._set_data(m)
            step = m
        prev._set_data(weight._data)
        weight._set_data(weight._data + step)


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (ref: optimizer.py:SGLD)."""

    def update(self, index, weight, grad, state):
        from .random import next_key
        import jax
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = grad._data * self.rescale_grad
        if self.clip_gradient:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        g = g + wd * weight._data
        noise = jax.random.normal(next_key(), weight.shape) * math.sqrt(lr)
        weight._set_data(weight._data - lr / 2 * g + noise.astype(weight._data.dtype))


@register
class Adam(Optimizer):
    """Ref: optimizer.py:Adam (+ sparse lazy update src/operator/optimizer_op.cc
    adam_update row_sparse path)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        z = _zeros_like_state(weight)
        return (z(), z())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        lr_t = lr * math.sqrt(1.0 - self.beta2 ** t) / (1.0 - self.beta1 ** t)
        mean, var = state
        from .ndarray.sparse import RowSparseNDArray
        if isinstance(grad, RowSparseNDArray):
            rows = grad._aux["indices"]
            g = grad._data * self.rescale_grad
            if self.clip_gradient:
                g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
            g = g + wd * weight._data[rows]
            m_new = self.beta1 * mean._data[rows] + (1 - self.beta1) * g
            v_new = self.beta2 * var._data[rows] + (1 - self.beta2) * jnp.square(g)
            mean._set_data(mean._data.at[rows].set(m_new))
            var._set_data(var._data.at[rows].set(v_new))
            weight._set_data(weight._data.at[rows].add(
                -lr_t * m_new / (jnp.sqrt(v_new) + self.epsilon)))
            return
        _uo.adam_update(weight, grad, mean, var, lr_t, beta1=self.beta1,
                        beta2=self.beta2, epsilon=self.epsilon, wd=wd,
                        **self._common_kwargs(index))


@register
class AdaGrad(Optimizer):
    """Ref: optimizer.py:AdaGrad; sparse variant optimizer_op.cc _sparse_adagrad_update."""

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return NDArray(jnp.zeros(weight.shape, weight._data.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        from .ndarray.sparse import RowSparseNDArray
        if isinstance(grad, RowSparseNDArray):
            rows = grad._aux["indices"]
            g = grad._data * self.rescale_grad
            h_new = state._data[rows] + jnp.square(g)
            state._set_data(state._data.at[rows].set(h_new))
            weight._set_data(weight._data.at[rows].add(
                -lr * g / jnp.sqrt(h_new + self.float_stable_eps)))
            return
        _uo.adagrad_update(weight, grad, state, lr, epsilon=self.float_stable_eps,
                           wd=wd, **self._common_kwargs(index))


@register
class RMSProp(Optimizer):
    """Ref: optimizer.py:RMSProp (centered=Alex variant w/ gamma2)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9, epsilon=1e-8,
                 centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1, self.gamma2 = gamma1, gamma2
        self.epsilon = epsilon
        self.centered = centered
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        z = _zeros_like_state(weight)
        if self.centered:
            return (z(), z(), z())  # n, g, delta
        return (z(),)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        kw = dict(gamma1=self.gamma1, epsilon=self.epsilon, wd=wd,
                  rescale_grad=self.rescale_grad,
                  clip_gradient=self.clip_gradient if self.clip_gradient else -1.0,
                  clip_weights=self.clip_weights if self.clip_weights else -1.0)
        if self.centered:
            n, g, delta = state
            _uo.rmspropalex_update(weight, grad, n, g, delta, lr, gamma2=self.gamma2, **kw)
        else:
            (n,) = state
            _uo.rmsprop_update(weight, grad, n, lr, **kw)


@register
class AdaDelta(Optimizer):
    """Ref: optimizer.py:AdaDelta."""

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho, self.epsilon = rho, epsilon

    def create_state(self, index, weight):
        z = _zeros_like_state(weight)
        return (z(), z())  # acc_g, acc_delta

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        acc_g, acc_delta = state
        g = grad._data * self.rescale_grad
        if self.clip_gradient:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        g = g + wd * weight._data
        ag = self.rho * acc_g._data + (1 - self.rho) * jnp.square(g)
        delta = jnp.sqrt(acc_delta._data + self.epsilon) / jnp.sqrt(ag + self.epsilon) * g
        ad = self.rho * acc_delta._data + (1 - self.rho) * jnp.square(delta)
        acc_g._set_data(ag)
        acc_delta._set_data(ad)
        weight._set_data(weight._data - delta)


@register
class Ftrl(Optimizer):
    """Ref: optimizer.py:Ftrl."""

    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1, self.beta = lamda1, beta

    def create_state(self, index, weight):
        z = _zeros_like_state(weight)
        return (z(), z())  # z, n

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        z, n = state
        _uo.ftrl_update(weight, grad, z, n, lr, lamda1=self.lamda1, beta=self.beta,
                        wd=wd, **self._common_kwargs(index))


@register
class Adamax(Optimizer):
    """Ref: optimizer.py:Adamax."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2

    def create_state(self, index, weight):
        z = _zeros_like_state(weight)
        return (z(), z())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        lr_t = lr / (1.0 - self.beta1 ** t)
        m, u = state
        g = grad._data * self.rescale_grad
        if self.clip_gradient:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        g = g + wd * weight._data
        m_new = self.beta1 * m._data + (1 - self.beta1) * g
        u_new = jnp.maximum(self.beta2 * u._data, jnp.abs(g))
        m._set_data(m_new)
        u._set_data(u_new)
        weight._set_data(weight._data - lr_t * m_new / (u_new + 1e-8))


@register
class Nadam(Optimizer):
    """Ref: optimizer.py:Nadam."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.0

    def create_state(self, index, weight):
        z = _zeros_like_state(weight)
        return (z(), z())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        g = grad._data * self.rescale_grad
        if self.clip_gradient:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        g = g + wd * weight._data
        momentum_t = self.beta1 * (1.0 - 0.5 * 0.96 ** (t * self.schedule_decay))
        momentum_t_1 = self.beta1 * (1.0 - 0.5 * 0.96 ** ((t + 1) * self.schedule_decay))
        self.m_schedule *= momentum_t
        m_schedule_next = self.m_schedule * momentum_t_1
        m, v = state
        m_new = self.beta1 * m._data + (1 - self.beta1) * g
        v_new = self.beta2 * v._data + (1 - self.beta2) * jnp.square(g)
        g_prime = g / (1 - self.m_schedule)
        m_prime = m_new / (1 - m_schedule_next)
        v_prime = v_new / (1 - self.beta2 ** t)
        m_bar = (1 - momentum_t) * g_prime + momentum_t_1 * m_prime
        m._set_data(m_new)
        v._set_data(v_new)
        weight._set_data(weight._data - lr * m_bar / (jnp.sqrt(v_prime) + self.epsilon))


@register
class LBSGD(SGD):
    """Large-batch SGD with LARS-style layer-wise adaptive rate
    (ref: optimizer.py:LBSGD, warmup + lars trust ratio)."""

    def __init__(self, momentum=0.0, warmup_strategy="linear", warmup_epochs=5,
                 batch_scale=1, updates_per_epoch=32, begin_epoch=0, num_epochs=60,
                 **kwargs):
        super().__init__(momentum=momentum, **kwargs)
        self.warmup_strategy = warmup_strategy
        self.warmup_epochs = warmup_epochs
        self.batch_scale = batch_scale
        self.updates_per_epoch = updates_per_epoch
        self.init_updates = begin_epoch * updates_per_epoch
        self.num_epochs = num_epochs
        self.adaptive = warmup_strategy == "lars"

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        # LARS trust ratio
        wn = float(jnp.linalg.norm(weight._data.reshape(-1)))
        gn = float(jnp.linalg.norm(grad._data.reshape(-1))) * self.rescale_grad
        if wn > 0 and gn > 0:
            lr = lr * min(wn / (gn + wd * wn + 1e-9), 1.0) if self.adaptive else lr
        if state is None:
            _uo.sgd_update(weight, grad, lr, wd=wd, **self._common_kwargs(index))
        else:
            _uo.sgd_mom_update(weight, grad, state, lr, momentum=self.momentum, wd=wd,
                               **self._common_kwargs(index))


@register
class Test(Optimizer):
    """Plumbing-test optimizer (ref: optimizer.py "Test")."""

    def create_state(self, index, weight):
        return NDArray(jnp.zeros(weight.shape, weight._data.dtype))

    def update(self, index, weight, grad, state):
        weight._set_data(weight._data + grad._data * self.rescale_grad)
        state._set_data(weight._data)


class Updater:
    """Per-index state store applying an optimizer (ref: optimizer.py:Updater;
    serialized as the kvstore's server-side updater)."""

    def __init__(self, optimizer: Optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state_multi_precision(index, weight)
        self.optimizer.update_multi_precision(index, weight, grad, self.states[index])

    def update_batch(self, indices, grads, weights):
        """Apply one step to many (index, grad, weight) triples. Here: the
        eager per-index loop; FusedUpdater (optimizer_fused.py) overrides
        this with ONE donated jit over the whole batch."""
        for i, g, w in zip(indices, grads, weights):
            self(i, g, w)

    def get_states(self, dump_optimizer=False):
        import pickle
        state = {}
        for k, v in self.states.items():
            state[k] = _state_to_numpy(v)
        if not dump_optimizer:
            return pickle.dumps(state)
        # the live param_dict holds Parameters wrapping device-placed
        # buffers (on a mesh: NamedSharding -> Mesh -> Device, which
        # pickle refuses); every load path rebinds it to the live params,
        # so serialize the optimizer without it
        pd, self.optimizer.param_dict = self.optimizer.param_dict, {}
        try:
            return pickle.dumps((state, self.optimizer))
        finally:
            self.optimizer.param_dict = pd

    def set_states(self, states):
        import pickle
        obj = pickle.loads(states)
        if isinstance(obj, tuple):
            obj, self.optimizer = obj
        self.states = {k: _state_from_numpy(v) for k, v in obj.items()}


def _state_to_numpy(v):
    if v is None:
        return None
    if isinstance(v, NDArray):
        # fleet meshes ZeRO-shard state across processes; asnumpy() on a
        # non-fully-addressable array raises, so fetch collectively
        from .parallel.mesh import host_value
        return host_value(v._data)
    if isinstance(v, (tuple, list)):
        return tuple(_state_to_numpy(x) for x in v)
    return v


def _state_from_numpy(v):
    if v is None:
        return None
    if isinstance(v, tuple):
        return tuple(_state_from_numpy(x) for x in v)
    if isinstance(v, _np.ndarray):
        return array(v)
    return v


def get_updater(optimizer: Optimizer) -> Updater:
    """An Updater whose batch path fuses the whole step into one donated jit
    (optimizer_fused.FusedUpdater; MXTPU_FUSED_OPTIMIZER=0 keeps its batch
    path on the eager loop). Per-index __call__ semantics are unchanged."""
    from .optimizer_fused import FusedUpdater
    return FusedUpdater(optimizer)


@register
class GroupAdaGrad(Optimizer):
    """Per-row (grouped) AdaGrad (ref: python/mxnet/optimizer/contrib.py
    GroupAdaGrad + src/operator/contrib/optimizer_op.cc
    _contrib_group_adagrad_update): history is the MEAN of squared
    gradients over each row (axis 1+), one adaptive rate per embedding row
    — the memory-light AdaGrad used for large embeddings."""

    def __init__(self, eps=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return NDArray(jnp.zeros((weight.shape[0],), weight._data.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        from .ndarray.sparse import RowSparseNDArray
        red = tuple(range(1, weight._data.ndim))
        if isinstance(grad, RowSparseNDArray):
            rows = grad._aux["indices"]
            g = grad._data * self.rescale_grad
            if self.clip_gradient is not None:
                g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
            h_new = state._data[rows] + jnp.mean(jnp.square(g), axis=red)
            state._set_data(state._data.at[rows].set(h_new))
            div = jnp.sqrt(h_new + self.float_stable_eps)
            weight._set_data(weight._data.at[rows].add(
                -lr * g / div.reshape((-1,) + (1,) * (g.ndim - 1))))
            return
        g = grad._data * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        h_new = state._data + jnp.mean(jnp.square(g), axis=red)
        state._set_data(h_new)
        div = jnp.sqrt(h_new + self.float_stable_eps)
        weight._set_data(weight._data
                         - lr * g / div.reshape((-1,) + (1,) * (g.ndim - 1)))


# mx.optimizer.contrib — the reference's contrib optimizer namespace
# (python/mxnet/optimizer/contrib.py: GroupAdaGrad lives there)
import sys as _sys
import types as _types

contrib = _types.ModuleType(__name__ + ".contrib")
contrib.GroupAdaGrad = GroupAdaGrad
contrib.__all__ = ["GroupAdaGrad"]
_sys.modules[contrib.__name__] = contrib
