"""Generic create/register machinery (ref: python/mxnet/registry.py).

The reference builds per-class registries (optimizers, metrics,
initializers, lr schedulers) from dmlc-style registry helpers; here those
registries already exist on their base classes — this module exposes the
same ``get_register_func``/``get_create_func``/``get_alias_func`` surface
so code written against mx.registry ports unchanged.
"""
from __future__ import annotations

from .base import MXNetError

__all__ = ["get_register_func", "get_alias_func", "get_create_func"]

_REGISTRIES = {}  # (base_class, nickname) -> {name: class}


def _registry_for(base_class, nickname):
    """The live class-family registry: the framework's own registries for
    Optimizer/EvalMetric/Initializer (so mx.registry sees every built-in,
    e.g. create('xavier') works), a fresh dict for user base classes."""
    from . import initializer as _init
    from . import metric as _metric
    from .optimizer import Optimizer as _Opt

    if issubclass(base_class, _Opt):
        return _Opt.opt_registry
    if issubclass(base_class, _metric.EvalMetric):
        return _metric._REGISTRY
    if issubclass(base_class, _init.Initializer):
        return _init._REGISTRY
    return _REGISTRIES.setdefault((base_class, nickname), {})


def get_register_func(base_class, nickname):
    """Returns register(klass, name=None) for the class family."""
    reg = _registry_for(base_class, nickname)

    def register(klass, name=None):
        if not issubclass(klass, base_class):
            raise MXNetError("%s is not a subclass of %s"
                             % (klass, base_class.__name__))
        reg[(name or klass.__name__).lower()] = klass
        return klass

    register.__name__ = "register_%s" % nickname
    return register


def get_alias_func(base_class, nickname):
    reg = _registry_for(base_class, nickname)

    def alias(name):
        def deco(klass):
            reg[name.lower()] = klass
            return klass
        return deco

    alias.__name__ = "alias_%s" % nickname
    return alias


def get_create_func(base_class, nickname):
    """Returns create(name_or_instance, **kwargs) for the class family."""
    reg = _registry_for(base_class, nickname)

    def create(obj, **kwargs):
        if isinstance(obj, base_class):
            return obj
        name = str(obj).lower()
        if name not in reg:
            raise MXNetError("%s %s not registered; have %s"
                             % (nickname, obj, sorted(reg)))
        return reg[name](**kwargs)

    create.__name__ = "create_%s" % nickname
    return create
