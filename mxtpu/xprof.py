"""Executable observatory: per-jit-site cost/memory ledger, live HBM
accounting, and runtime MFU attribution (``MXTPU_XPROF``, default on).

The telemetry layer (PRs 4/10) gave the runtime full *time* observability;
this module adds *compute and memory*. Every jit-cache owner already
reports compiles via :func:`mxtpu.telemetry.record_retrace` — that call
now takes the freshly-built executable (``compiled=``) and this module
keeps a bounded per-site **ledger** of what each executable costs:

* XLA cost-model FLOPs and bytes-accessed (``cost_analysis()``),
* HBM footprint — argument / output / temp / generated-code bytes and the
  donated-bytes savings (``memory_analysis()``),
* compile wall-time (the first dispatch, which is trace+compile),
* a live call count, so executed-FLOPs (and the Trainer's ``perf.mfu``
  gauge) come from bookkeeping the dispatch path already does.

Resolution discipline: analyses need an AOT ``Compiled`` handle, which
jax only hands out through ``lower().compile()`` — one extra *host-side*
lowering per executable (the repo-accepted cost of
``ShardedTrainStep.compiled_step_flops``). That work is LAZY and runs at
explicit query points only (:func:`ledger`, the warmup pre-flight, the
MFU meter's first tick) — never on a /metrics scrape, never inside a
flight dump (an OOM moment must not invoke the compiler), and never on
the steady-state step path. Everything here is host bookkeeping: zero
device work, zero syncs — the ``trainer.step.d2h == 0`` contract holds
with the observatory ON (transfer-guard test parametrized over
``MXTPU_XPROF``).

Live HBM accounting: :func:`poll_memory` reads ``device.memory_stats()``
into ``memory.hbm_{used,limit,headroom,peak}_bytes{device}`` gauges, an
off-thread monitor (``MXTPU_MEMWATCH_S`` seconds, 0 = off) keeps them
fresh, warmup runs a will-it-fit :func:`preflight` (Σ AOT bucket
footprints vs the device limit → ``memory.overcommit``), and a
``RESOURCE_EXHAUSTED`` anywhere on the dispatch paths triggers
:func:`oom_flight` — a flight-recorder artifact carrying the ledger,
per-device memory stats, and (in serving) the KVCacheAccountant view, so
an HBM OOM leaves a post-mortem instead of just a dead process.

Gating: ``MXTPU_XPROF=0`` skips the wrap at compile-record time (a
construction-time lever like ``MXTPU_SERVE_INT8`` — flipping it mid-run
affects new compiles, not executables already cached) and disables the
memwatch/preflight/MFU surfaces. Host-side only — NOT in ``policy_key``.
"""
from __future__ import annotations

import collections
import itertools
import logging
import numbers
import os
import threading
import time

from . import telemetry

__all__ = ["enabled", "memwatch_interval", "attach", "watch", "ledger",
           "ledger_snapshot", "resolve", "executed_flops", "summary",
           "device_memory", "poll_memory", "ensure_memwatch",
           "stop_memwatch", "preflight", "site_footprint", "is_oom",
           "oom_flight",
           "MFUMeter", "TRAIN_SITES", "reset"]

_log = logging.getLogger("mxtpu.xprof")

_LOCK = threading.Lock()
_SITES = {}                    # site -> deque of ledger entries
_SEQ = itertools.count(1)
_PER_SITE = 16                 # bounded: a retrace storm keeps the newest

# jit sites that execute on the training step path — the executed-FLOPs
# numerator of the Trainer's perf.mfu gauge
TRAIN_SITES = ("fused_optimizer", "cached_op", "executor",
               "executor.backward", "parallel.train_step", "subgraph_exec")

_MEMWATCH = {"thread": None, "stop": None, "lock": threading.Lock()}

# substrings that mark a device allocator failure across jaxlib spellings
# (XlaRuntimeError RESOURCE_EXHAUSTED, PJRT "Out of memory", and the
# injected fault kind 'oom' which mimics the first)
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory")


# ------------------------------------------------------------------ policies
def enabled():
    """Observatory lever: ``MXTPU_XPROF`` default ON (requires the
    telemetry registry, which bare counters keep available always)."""
    return os.environ.get("MXTPU_XPROF", "1") != "0"


def memwatch_interval():
    """Off-thread HBM poll period in seconds (``MXTPU_MEMWATCH_S``);
    0 (default) = no monitor thread."""
    try:
        return float(os.environ.get("MXTPU_MEMWATCH_S", "0"))
    except ValueError:
        return 0.0


def _jsonable(v):
    """Provenance/extra payloads must survive json.dump inside a flight
    artifact: tuples/sets become lists, numpy scalars coerce, everything
    else degrades to repr."""
    if v is None or isinstance(v, (bool, str)):
        return v
    if isinstance(v, numbers.Integral):
        return int(v)
    if isinstance(v, numbers.Real):
        return float(v)
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple, set, frozenset)):
        return [_jsonable(x) for x in v]
    return repr(v)


# ------------------------------------------------------------------- ledger
class _Spec:
    """Captured abstract value of one call argument: shape + dtype (+
    sharding when the leaf was a placed jax.Array — GSPMD analyses differ
    per layout). Holding the spec, never the buffer: capture must not pin
    donated HBM."""

    __slots__ = ("shape", "dtype", "sharding")

    def __init__(self, shape, dtype, sharding):
        self.shape = shape
        self.dtype = dtype
        self.sharding = sharding


def _capture(args, kwargs):
    import jax

    def leaf(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return _Spec(tuple(x.shape), x.dtype,
                         getattr(x, "sharding", None))
        return x  # python scalars keep their weak-typed signature

    return jax.tree_util.tree_map(leaf, (args, dict(kwargs)))


def _shapes_of(spec_tree, limit=16):
    """Public shape summary of a captured signature: "dtype[d1,d2,...]"
    per array leaf, bounded. This is what the ledger streams (the
    ``--tuning-queue`` emitter keys tuning candidates on it); the full
    ``_Spec`` tree stays private for AOT re-lowering."""
    import jax
    out = []
    for x in jax.tree_util.tree_leaves(spec_tree):
        if isinstance(x, _Spec):
            out.append("%s[%s]" % (jnp_name(x.dtype),
                                   ",".join(str(d) for d in x.shape)))
            if len(out) >= limit:
                break
    return out


def jnp_name(dtype):
    try:
        import numpy as np
        return np.dtype(dtype).name
    except Exception:  # noqa: BLE001 — exotic dtypes still summarize
        return str(dtype)


def _to_abstract(spec_tree, with_sharding):
    import jax

    def leaf(x):
        if isinstance(x, _Spec):
            if with_sharding and x.sharding is not None:
                try:
                    return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                                sharding=x.sharding)
                except (TypeError, ValueError):
                    pass
            return jax.ShapeDtypeStruct(x.shape, x.dtype)
        return x

    return jax.tree_util.tree_map(
        leaf, spec_tree, is_leaf=lambda x: isinstance(x, _Spec))


class _WatchedJit:
    """Thin wrapper around a jitted callable: the FIRST invocation is
    timed (trace+compile wall clock — the compile stall a served request
    or training step actually experienced) and its abstract signature
    captured for lazy analysis resolution; later invocations bump the
    ledger entry's call count behind a per-call lever check (one env
    read + one add), so flipping ``MXTPU_XPROF=0`` mid-run stops the
    accounting and ``bench.py telemetry_overhead``'s alternating
    ``xprof`` mode genuinely A/Bs the per-dispatch cost (the wrapper
    frame itself is construction-time and rides every mode). Attribute
    access forwards to the wrapped jit, so ``.lower()``-style AOT
    callers keep working."""

    __slots__ = ("_fn", "_entry", "_pending_first")

    def __init__(self, fn, entry, pending_first=True):
        self._fn = fn
        self._entry = entry
        # pending_first=False: an AOT executable from the compile
        # service (built explicitly or deserialized from disk) — the
        # first dispatch is pure replay, so only call counting remains
        self._pending_first = pending_first

    def __call__(self, *args, **kwargs):
        e = self._entry
        if self._pending_first:
            self._pending_first = False
            try:
                e["_abstract"] = _capture(args, kwargs)
                e["shapes"] = _shapes_of(e["_abstract"])
            except Exception:  # noqa: BLE001 — capture must never break
                pass           # the dispatch it observes
            t0 = time.perf_counter()
            out = self._fn(*args, **kwargs)
            dt = time.perf_counter() - t0
            e["compile_s"] = dt
            e["calls"] += 1
            telemetry.observe("compile.wall_s", dt)
            return out
        out = self._fn(*args, **kwargs)
        if os.environ.get("MXTPU_XPROF", "1") != "0":
            e["calls"] += 1
        return out

    def __getattr__(self, name):
        return getattr(self._fn, name)


def _new_entry(site, provenance):
    entry = {"site": site, "seq": next(_SEQ),
             "provenance": _jsonable(provenance),
             "calls": 0, "compile_s": None,
             "resolved": False, "error": None}
    with _LOCK:
        dq = _SITES.get(site)
        if dq is None:
            dq = _SITES[site] = collections.deque(maxlen=_PER_SITE)
        dq.append(entry)
    return entry


def attach(site, provenance=None, compiled=None, compile_s=None):
    """Register one executable-cache miss in the ledger and return the
    callable the site should cache. ``compiled`` is either the
    freshly-built jitted callable (wrapped for first-call timing +
    signature capture) or an already-AOT ``Compiled`` object from the
    compile service (analyses fill immediately; the wrapper keeps call
    counting with ``compile_s`` — the service-measured lower+compile
    wall time — recorded up front since the first dispatch is replay).
    Off (``MXTPU_XPROF=0``) this returns ``compiled`` unchanged — zero
    added dispatch layers."""
    if compiled is None:
        return None
    if not enabled():
        return compiled
    entry = _new_entry(site, provenance)
    if hasattr(compiled, "cost_analysis"):
        # an AOT executable from the compile service: analyses resolve
        # LAZILY from the handle we already hold (same discipline as the
        # lower-at-query path — warmup must not pay a cost_analysis per
        # bucket); the wrapper keeps call counting, and compile_s is the
        # service-measured lower+compile wall (first dispatch is replay)
        entry["_compiled"] = compiled
        if compile_s is not None:
            entry["compile_s"] = compile_s
            telemetry.observe("compile.wall_s", compile_s)
        return _WatchedJit(compiled, entry, pending_first=False)
    entry["_fn"] = compiled
    return _WatchedJit(compiled, entry)


def watch(site, compiled, provenance=None, compile_s=None):
    """Ledger-only registration for a companion executable that shares a
    site's retrace count (e.g. CachedOp's compiled backward, reported
    with the forward's single ``record_retrace``) or a disk-restored
    executable (a load is not a compile) — same wrap, no extra
    ``retrace.<site>`` bump."""
    return attach(site, provenance, compiled, compile_s=compile_s)


def _fill_from_compiled(entry, compiled):
    from . import perf_model
    fl = perf_model.flops_of(compiled)
    ba = perf_model.bytes_accessed_of(compiled)
    entry["flops"] = fl
    entry["bytes_accessed"] = ba
    entry.update(perf_model.memory_dict(compiled.memory_analysis()))
    ridge = perf_model.critical_intensity()
    entry["critical_intensity"] = ridge
    entry["intensity"] = (fl / ba) if fl and ba else None
    entry["verdict"] = perf_model.roofline_verdict(fl, ba, ridge)


# serializes analysis resolution: two concurrent resolvers (the MFU
# meter's tick on the training thread, a diagnostic ledger() elsewhere)
# must not race on an entry's one-shot handle pop — the loser would taint
# a successfully-resolved entry with a spurious "never invoked" error
_RESOLVE_LOCK = threading.Lock()


def _resolve_entry(entry):
    """Fill one entry's analyses: re-lower the wrapped jit at its
    captured abstract signature and compile (host work only; the
    executable cache the site already holds is untouched). One attempt —
    an analysis failure is recorded, never raised into the caller."""
    with _RESOLVE_LOCK:
        if entry["resolved"]:
            return
        _resolve_entry_locked(entry)


def _resolve_entry_locked(entry):
    pre = entry.pop("_compiled", None)
    if pre is not None:
        # the AOT handle was captured at attach time: no re-lowering
        try:
            _fill_from_compiled(entry, pre)
        except Exception as e:  # noqa: BLE001 — diagnostics degrade
            entry["error"] = "%s: %s" % (type(e).__name__, e)
        entry["resolved"] = True
        return
    fn = entry.pop("_fn", None)
    spec = entry.pop("_abstract", None)
    try:
        if fn is None or spec is None:
            raise RuntimeError("executable never invoked before resolve")
        args, kwargs = spec, {}
        try:
            a, kw = _to_abstract(args, True)
            compiled = fn.lower(*a, **kw).compile()
        except Exception:  # noqa: BLE001 — sharding-annotated lowering
            # can refuse on some backends; shapes alone still analyze
            a, kw = _to_abstract(args, False)
            compiled = fn.lower(*a, **kw).compile()
        _fill_from_compiled(entry, compiled)
    except Exception as e:  # noqa: BLE001 — diagnostics degrade, never kill
        entry["error"] = "%s: %s" % (type(e).__name__, e)
    entry["resolved"] = True


def _public(entry):
    return {k: v for k, v in entry.items() if not k.startswith("_")}


def ledger(site=None, resolve=True):
    """The per-site executable ledger as a list of dicts (sorted by
    compile order). ``resolve=True`` (the diagnostic default) fills any
    pending cost/memory analyses first — one host-side lowering per
    still-unresolved executable; pass ``resolve=False`` on scrape/dump
    paths that must never invoke the compiler."""
    with _LOCK:
        entries = [e for s, dq in sorted(_SITES.items())
                   if site is None or s == site for e in list(dq)]
    if resolve:
        for e in entries:
            if not e["resolved"]:
                _resolve_entry(e)
    return sorted((_public(e) for e in entries), key=lambda e: e["seq"])


def ledger_snapshot():
    """Resolve-free ledger view — what ``telemetry.snapshot()`` exports
    on ``/metrics`` and what flight artifacts embed (a scrape or an OOM
    dump must never stall on ``lower().compile()``)."""
    return ledger(resolve=False)


def resolve(site=None):
    """Force analysis resolution for ``site`` (or everything)."""
    return ledger(site, resolve=True)


def executed_flops(sites=None):
    """Σ cost-model FLOPs × call count over resolved ledger entries —
    the MFU numerator. ``sites`` filters by exact site name or
    dotted-prefix family (``serving.predict`` matches
    ``serving.predict.r0``)."""
    with _LOCK:
        entries = [e for dq in _SITES.values() for e in list(dq)]
    total = 0.0
    for e in entries:
        fl = e.get("flops")
        if not fl:
            continue
        s = e["site"]
        if sites is not None and not any(
                s == want or s.startswith(want + ".") for want in sites):
            continue
        total += fl * e["calls"]
    return total


def summary():
    """One-line ledger digest for bench JSON stamps: compile count,
    total compile seconds, and the process-peak HBM across devices."""
    with _LOCK:
        entries = [e for dq in _SITES.values() for e in list(dq)]
    comp = [e["compile_s"] for e in entries if e.get("compile_s")]
    out = {"compiles": len(entries),
           "compile_s_total": round(sum(comp), 3) if comp else 0.0}
    peak = 0
    try:
        import jax
        for d in jax.devices():
            peak = max(peak, device_memory(d).get("peak_bytes_in_use", 0))
    except Exception:  # noqa: BLE001 — a dead PJRT client still stamps
        pass
    out["peak_hbm_bytes"] = peak or None
    return out


# --------------------------------------------------------- HBM accounting
def device_memory(device=0):
    """Normalized device memory view — THE one helper every consumer
    (``util.get_gpu_memory``, the C-ABI ``MXGetGPUMemoryInformation``,
    the memwatch gauges) reads, so they can never disagree on key
    fallbacks. ``device`` is a jax Device or an index. Keys:
    ``bytes_in_use`` / ``bytes_limit`` / ``peak_bytes_in_use`` /
    ``bytes_free`` — all 0 when the backend exposes no stats (CPU)."""
    stats = {}
    try:
        if not hasattr(device, "memory_stats"):
            import jax
            device = jax.devices()[int(device)]
        stats = device.memory_stats() or {}
    except Exception:  # noqa: BLE001 — backend not initialized / no stats
        stats = {}
    limit = int(stats.get("bytes_limit")
                or stats.get("bytes_reservable_limit") or 0)
    used = int(stats.get("bytes_in_use") or 0)
    peak = int(stats.get("peak_bytes_in_use") or used)
    return {"bytes_in_use": used, "bytes_limit": limit,
            "peak_bytes_in_use": peak,
            "bytes_free": max(limit - used, 0) if limit else 0}


def poll_memory(stats=None):
    """One HBM sweep into the per-device gauges
    (``memory.hbm_{used,limit,headroom,peak}_bytes`` tagged ``d<i>``).
    ``stats`` (``{tag: device_memory-dict}``) is injectable so tests and
    stats-less backends can drive the gauge path. Devices with no
    exposed stats are skipped — on the CPU tier this is a no-op."""
    if not enabled():
        return {}
    if stats is None:
        try:
            import jax
            devs = jax.devices()
        except Exception:  # noqa: BLE001
            return {}
        stats = {}
        for i, d in enumerate(devs):
            m = device_memory(d)
            if m["bytes_limit"] or m["bytes_in_use"]:
                stats["d%d" % i] = m
    for tag, m in stats.items():
        used = int(m.get("bytes_in_use", 0))
        limit = int(m.get("bytes_limit", 0))
        telemetry.gauge("memory.hbm_used_bytes", used, tag=tag)
        telemetry.gauge("memory.hbm_limit_bytes", limit, tag=tag)
        telemetry.gauge("memory.hbm_headroom_bytes",
                        max(limit - used, 0), tag=tag)
        telemetry.gauge("memory.hbm_peak_bytes",
                        int(m.get("peak_bytes_in_use", used)), tag=tag)
    return stats


def ensure_memwatch():
    """Start the off-thread HBM monitor when ``MXTPU_MEMWATCH_S`` > 0
    (idempotent; called from Trainer init and serving warmup so the
    gauges are live wherever device memory is being committed)."""
    interval = memwatch_interval()
    if interval <= 0 or not enabled():
        return False
    with _MEMWATCH["lock"]:
        t = _MEMWATCH["thread"]
        if t is not None and t.is_alive():
            return True
        stop = threading.Event()
        t = threading.Thread(target=_memwatch_loop, args=(interval, stop),
                             daemon=True, name="mxtpu-memwatch")
        _MEMWATCH["thread"] = t
        _MEMWATCH["stop"] = stop
        t.start()
    return True


def stop_memwatch():
    with _MEMWATCH["lock"]:
        stop, t = _MEMWATCH["stop"], _MEMWATCH["thread"]
        _MEMWATCH["thread"] = None
        _MEMWATCH["stop"] = None
    if stop is not None:
        stop.set()
    if t is not None:
        t.join(timeout=1.0)


def _memwatch_loop(interval, stop):
    while not stop.wait(interval):
        try:
            poll_memory()
        except Exception:  # noqa: BLE001 — a poll error must never kill
            pass           # the monitor (next interval retries)


def site_footprint(site, resolve=True, family=False):
    """A site's steady-state resident-byte estimate from its executable
    ledger. Footprint model (shared with :func:`preflight`): arguments
    are shared across buckets (params + request buffers — counted once
    at the donated-savings-adjusted max), temps are per-dispatch scratch
    (max — buckets never run concurrently), outputs (KV carries, result
    buffers) may all stay live (Σ). ``family=True`` matches the dotted
    prefix too (``serving.predict.zoo.m`` covers its ``.canary``
    subsite) — what the model zoo records as a resident model's HBM
    cost and sums into co-residency preflights."""
    entries = ledger(None if family else site, resolve=resolve)
    args_max = temp_max = out_sum = 0
    for e in entries:
        s = e.get("site")
        if family and not (s == site or (s or "").startswith(site + ".")):
            continue
        if e.get("error"):
            continue
        args_max = max(args_max, (e.get("argument_bytes") or 0)
                       - (e.get("donated_bytes") or 0))
        temp_max = max(temp_max, e.get("temp_bytes") or 0)
        out_sum += e.get("output_bytes") or 0
    return args_max + temp_max + out_sum


def preflight(site, device=0, limit=None, extra_bytes=0):
    """Will-it-fit pre-flight after an AOT warmup: the site's executables'
    combined footprint (:func:`site_footprint`) plus ``extra_bytes``
    already committed by co-residents (the model zoo passes the summed
    ledger footprints of the other models on the device) vs the device
    HBM limit. Past the limit it warns and bumps
    ``memory.overcommit{site}`` — warmup SUCCEEDING does not mean steady
    state fits once every bucket's residents (and neighbours) coexist.

    Returns ``(need_bytes, limit_bytes)``; None when the limit is
    unknown and not supplied (CPU tier) — skipped WITHOUT resolving, so
    host-tier warmups pay zero extra lowering."""
    if not enabled():
        return None
    if limit is None:
        limit = device_memory(device)["bytes_limit"]
    if not limit:
        return None
    need = site_footprint(site, resolve=True) + int(extra_bytes or 0)
    telemetry.gauge("memory.preflight_bytes", need, tag=site)
    if need > limit:
        telemetry.inc("memory.overcommit", tag=site)
        _log.warning(
            "memory pre-flight: site %r AOT footprint ~%.0f MiB "
            "(co-resident %.0f MiB included) exceeds the %.0f MiB device "
            "limit — warmup succeeded but steady state may "
            "RESOURCE_EXHAUST; shrink buckets/capacity, evict a "
            "co-resident model, or enable int8 (docs/observability.md)",
            site, need / 2**20, (extra_bytes or 0) / 2**20, limit / 2**20)
    return need, limit


# ------------------------------------------------------------- OOM flight
def is_oom(exc):
    """True when ``exc`` is a device allocator failure — jaxlib's
    ``RESOURCE_EXHAUSTED``/"Out of memory" spellings and the injected
    ``resilience.ResourceExhausted`` (fault kind ``oom``) all match."""
    if exc is None:
        return False
    s = "%s: %s" % (type(exc).__name__, exc)
    return any(m in s for m in _OOM_MARKERS)


def oom_flight(where, exc, extra=None, trace_ids=()):
    """Flight-record an HBM OOM: the artifact carries the executable
    ledger (resolve-free — the compiler is not invoked at the death
    moment), per-device memory stats, and any caller view (the decode
    path passes its KVCacheAccountant snapshot). Callers re-raise after
    — the flight recorder documents the failure, it does not absorb it."""
    telemetry.inc("memory.oom", tag=where)
    mem = {}
    try:
        import jax
        for i, d in enumerate(jax.devices()):
            mem["d%d" % i] = device_memory(d)
    except Exception:  # noqa: BLE001 — a dying backend still dumps
        pass
    ex = {"where": where, "error": str(exc)[:4000],
          "ledger": ledger_snapshot(), "memory": mem}
    if extra:
        ex.update(_jsonable(extra))
    return telemetry.flight_record("oom", trace_ids=trace_ids, extra=ex)


# ---------------------------------------------------------------- MFU meter
class MFUMeter:
    """Runtime MFU from bookkeeping alone: every ``every`` steps, the
    delta of ledger executed-FLOPs over the wall-clock delta, divided by
    the datasheet peak (``perf_model.peak_flops`` × ``n_devices``), lands
    in the ``perf.mfu`` gauge — zero extra device work, the smoothing is
    the window itself. The first tick resolves the step path's pending
    ledger analyses (one-time host lowering, at warmup-adjacent time);
    later ticks only resolve executables compiled since. Off-TPU the
    gauge appears only under an ``MXTPU_PEAK_TFLOPS`` override."""

    def __init__(self, sites=TRAIN_SITES, every=32, n_devices=1,
                 device=None):
        self._sites = tuple(sites)
        self._every = max(int(every), 1)
        self._n_devices = max(int(n_devices), 1)
        self._device = device
        self._n = 0
        self._t0 = None
        self._fl0 = 0.0
        self.last = None

    def step(self):
        """Count one training step; on window boundaries update the
        gauge. Returns the latest MFU (None until known)."""
        if not enabled():
            return None
        self._n += 1
        if self._n % self._every:
            return self.last
        from . import perf_model
        resolve_sites = set(self._sites)
        for s in list(_SITES):
            if any(s == w or s.startswith(w + ".") for w in self._sites):
                resolve_sites.add(s)
        for s in resolve_sites:
            if s in _SITES:
                resolve(s)
        now = time.perf_counter()
        fl = executed_flops(self._sites)
        if self._t0 is not None:
            peak = perf_model.peak_flops(self._device)
            dt = now - self._t0
            dfl = fl - self._fl0
            if peak and dt > 0 and dfl > 0:
                self.last = dfl / dt / (peak * self._n_devices)
                telemetry.gauge("perf.mfu", self.last)
        self._t0, self._fl0 = now, fl
        return self.last


# -------------------------------------------------------------------- reset
def reset():
    """Test hook: clear the ledger and stop the memwatch thread (wrapped
    executables keep counting into their orphaned entries — they are
    simply no longer listed). ``telemetry.reset()`` calls this."""
    stop_memwatch()
    with _LOCK:
        _SITES.clear()
