"""Ring attention: sequence/context parallelism over the mesh.

No reference counterpart — the reference's long-sequence story is fused RNNs +
bucketing (SURVEY §5 "Long-context/sequence parallelism: Absent"). On TPU,
long-context attention shards the sequence axis across devices and rotates
key/value blocks around the ICI ring with ``ppermute`` while each device keeps
its query shard resident, accumulating the softmax *online* (flash-attention
style m/l running max/sum), so the full [T, T] score matrix never materializes
and per-device memory is O(T/n * T/n) per step.

Layout convention: ``[batch, heads, seq, head_dim]`` (the MXU-friendly layout:
the contraction q @ k^T is a [Tq, d] x [d, Tk] matmul per head).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ["ring_attention", "ring_flash_attention",
           "ring_self_attention"]

_NEG_INF = -1e30  # mask value; avoids -inf - -inf = nan in the online rescale


def ring_attention(q, k, v, axis_name, causal=False, scale=None):
    """Per-shard ring attention body — call INSIDE ``shard_map`` (or ``pmap``)
    with the sequence axis sharded over ``axis_name``.

    q, k, v: [B, H, T_local, D] local shards. Returns [B, H, T_local, D].
    """
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, h, t_local, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    q32 = q.astype(jnp.float32)
    q_pos = idx * t_local + jnp.arange(t_local)

    acc0 = jnp.zeros((b, h, t_local, d), jnp.float32)
    m0 = jnp.full((b, h, t_local), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, t_local), jnp.float32)
    perm = [(j, (j + 1) % n) for j in range(n)]

    def attend(k_c, v_c, acc, m, l, src):
        k_pos = src * t_local + jnp.arange(t_local)
        s = jnp.einsum("bhqd,bhkd->bhqk", q32, k_c.astype(jnp.float32),
                       preferred_element_type=jnp.float32) * scale
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l = l * alpha + p.sum(axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_c.astype(jnp.float32),
            preferred_element_type=jnp.float32)
        return acc, m_new, l

    def step(carry, i):
        k_c, v_c, acc, m, l = carry
        # after i rotations of send-to-next, we hold the block that started
        # on shard (idx - i) mod n
        src = (idx - i) % n
        if causal:
            # blocks strictly in the future (src > idx) are fully masked:
            # skip both einsums (saves ~half the attention FLOPs on average)
            acc, m, l = jax.lax.cond(
                src <= idx,
                lambda args: attend(*args, src),
                lambda args: (args[2], args[3], args[4]),
                (k_c, v_c, acc, m, l))
        else:
            acc, m, l = attend(k_c, v_c, acc, m, l, src)
        k_c = jax.lax.ppermute(k_c, axis_name, perm)
        v_c = jax.lax.ppermute(v_c, axis_name, perm)
        return (k_c, v_c, acc, m, l), None

    (_, _, acc, _, l), _ = jax.lax.scan(
        step, (k, v, acc0, m0, l0), jnp.arange(n))
    out = acc / jnp.maximum(l[..., None], 1e-30)
    return out.astype(q.dtype)


def ring_flash_attention(q, k, v, axis_name, causal=False, scale=None,
                         block_q=512, block_k=512):
    """Ring attention whose per-step block runs the FUSED flash kernel
    (Pallas on TPU; XLA fallback elsewhere) instead of materializing the
    [T_local, T_local] block scores. Per-step partial results merge
    exactly via their log-sum-exps:

        out = Σ_j exp(lse_j - lse_total) · out_j

    The rotation schedule makes causality STATIC per step: at step 0
    every device attends its OWN diagonal block (causal kernel); later
    steps see strictly-past blocks (merged via lse) or strictly-future
    blocks (fully masked — the kernel is SKIPPED via lax.cond, matching
    the dense body's ~half-FLOP causal saving). Staged
    behind MXTPU_RING_FLASH (see registry.policy_key) pending on-chip
    measurement; numerics are pinned against the dense path either way.
    """
    from ..ops.pallas.flash_attention import flash_attention_with_lse

    n = jax.lax.psum(1, axis_name)  # concrete inside shard_map
    idx = jax.lax.axis_index(axis_name)
    b, h, t_local, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    perm = [(j, (j + 1) % n) for j in range(n)]

    def merge(o_a, lse_a, o_b, lse_b):
        m = jnp.maximum(lse_a, lse_b)
        wa = jnp.exp(lse_a - m)
        wb = jnp.exp(lse_b - m)
        den = jnp.maximum(wa + wb, 1e-30)
        o = (o_a * wa[..., None] + o_b * wb[..., None]) / den[..., None]
        return o, m + jnp.log(den)

    o_run = jnp.zeros((b, h, t_local, d), jnp.float32)
    lse_run = jnp.full((b, h, t_local), _NEG_INF, jnp.float32)
    k_c, v_c = k, v
    for j in range(n):
        if causal and j > 0:
            # strictly-future blocks (src > idx) are fully masked: skip
            # the kernel entirely, as the dense ring body does
            src = (idx - j) % n

            def _attend(args):
                o_r, lse_r, k_b, v_b = args
                out_j, lse_j = flash_attention_with_lse(
                    q, k_b, v_b, causal=False, scale=scale,
                    block_q=block_q, block_k=block_k)
                return merge(o_r, lse_r, out_j.astype(jnp.float32), lse_j)

            o_run, lse_run = jax.lax.cond(
                src < idx, _attend, lambda args: (args[0], args[1]),
                (o_run, lse_run, k_c, v_c))
        else:
            out_j, lse_j = flash_attention_with_lse(
                q, k_c, v_c, causal=causal, scale=scale,
                block_q=block_q, block_k=block_k)
            o_run, lse_run = merge(o_run, lse_run,
                                   out_j.astype(jnp.float32), lse_j)
        if j < n - 1:
            k_c = jax.lax.ppermute(k_c, axis_name, perm)
            v_c = jax.lax.ppermute(v_c, axis_name, perm)
    return o_run.astype(q.dtype)


def _dense_attention(q, k, v, causal=False, scale=None):
    """Single-device reference path (the degenerate 1-shard ring) — one
    implementation shared with flash_attention's fallback."""
    from ..ops.pallas.flash_attention import _xla_attention
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    return _xla_attention(q, k, v, causal, scale)


def ring_self_attention(q, k, v, mesh=None, seq_axis="sp", batch_axis=None,
                        causal=False, scale=None):
    """Sequence-parallel attention over a mesh (dense fallback when mesh is
    None or lacks the sequence axis).

    q, k, v: [B, H, T, D] *global* arrays (or tracers inside a jitted sharded
    program). The sequence axis T is sharded over ``seq_axis``; the batch axis
    optionally over ``batch_axis``.
    """
    if mesh is None or seq_axis not in mesh.shape or mesh.shape[seq_axis] == 1:
        # single-shard path: fused flash kernel (Pallas on TPU, XLA fallback)
        from ..ops.pallas import flash_attention
        return flash_attention(q, k, v, causal=causal, scale=scale)
    spec = P(batch_axis, None, seq_axis, None)
    import os
    body = ring_flash_attention \
        if os.environ.get("MXTPU_RING_FLASH", "0") == "1" else ring_attention
    fn = functools.partial(body, axis_name=seq_axis, causal=causal,
                           scale=scale)
    from .shmap import shard_map
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)


# mx.nd-level op so eager autograd tapes through attention like any other op
# (the registry's _apply path, ref: Imperative::Invoke)
from ..ops.registry import register as _register  # noqa: E402

ring_attention_nd = _register("_contrib_ring_attention")(
    lambda q, k, v, mesh=None, seq_axis="sp", batch_axis=None, causal=False,
    scale=None: ring_self_attention(q, k, v, mesh=mesh, seq_axis=seq_axis,
                                    batch_axis=batch_axis, causal=causal,
                                    scale=scale))
