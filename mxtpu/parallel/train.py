"""ShardedTrainStep: the whole training step as ONE compiled sharded program.

This is the TPU-native fast path that replaces the reference's entire
per-batch machinery — DataParallelExecutorGroup batch slicing
(python/mxnet/module/executor_group.py:281), KVStore push/pull gradient
reduction (src/kvstore/kvstore_local.h:184), and in-engine optimizer kernels
(src/operator/optimizer_op.cc) — with a single ``jax.jit`` over a
`jax.sharding.Mesh`:

* forward + loss + backward + optimizer update trace into one XLA program,
* the batch is sharded on the ``data`` axis; the mean loss / summed gradients
  ARE the cross-device all-reduce (GSPMD inserts the collectives — the
  explicit push/pull of the reference becomes implicit dataflow),
* parameters may carry PartitionSpecs (tensor parallelism — absent from the
  reference, SURVEY §2.3) and are donated, so the update is in-place in HBM
  like the reference's in-engine mutate-in-place optimizer ops.

The block's imperative forward is traced through the same `_TraceFrame`
machinery as CachedOp (mxtpu/gluon/block.py), so BatchNorm moving-stat
updates and Dropout RNG stay functional under the trace.
"""
from __future__ import annotations

import re

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import autograd
from .. import random as _random
from ..base import MXNetError
from ..gluon.block import _flatten_nd, _regroup, _run_traced
from ..ndarray import NDArray
from ..ops import optimizer_ops as _uo

__all__ = ["ShardedTrainStep", "pure_forward"]




def pure_forward(block, train=False):
    """Extract the block's forward as a pure jittable function.

    Returns ``(fn, param_datas)`` where ``fn(param_datas, *input_arrays,
    rng=None)`` maps raw jax arrays to raw jax array(s). Pass a fresh ``rng``
    key per call for stochastic layers (Dropout) — with the default ``None``
    a fixed key is used, which is only correct for deterministic inference
    (every call would otherwise draw the SAME dropout mask). The block must
    be initialized with shapes settled (run one eager forward first for
    deferred init).
    """
    params = list(block.collect_params().values())
    if any(p._data is None for p in params):
        raise MXNetError(
            "pure_forward requires initialized parameters; call initialize() "
            "and run one forward pass to settle deferred shapes")
    param_datas = [p.data()._data for p in params]

    def fn(param_datas, *in_datas, rng=None):
        key = jax.random.PRNGKey(0) if rng is None else rng

        def body():
            return block(*[NDArray(d) for d in in_datas])
        out, _aux = _run_traced(params, param_datas, key, train, body)
        flat = _flatten_nd(out, [])
        datas = [o._data for o in flat]
        return datas[0] if len(datas) == 1 else tuple(datas)

    return fn, param_datas


# --------------------------------------------------------------- optimizers
# Functional (weight, grad, *states, **hyper) -> (weight, *states) adapters
# over the same pure update kernels the imperative Optimizer zoo uses
# (mxtpu/ops/optimizer_ops.py ~ src/operator/optimizer_op.cc).
def _sgd(w, g, states, lr, wd, mom, t, clip_gradient=-1.0):
    if mom == 0.0:
        return _uo.sgd_update_fn(w, g, lr, wd=wd,
                                 clip_gradient=clip_gradient), states
    new_w, new_m = _uo.sgd_mom_update_fn(w, g, states[0], lr, momentum=mom,
                                         wd=wd, clip_gradient=clip_gradient)
    return new_w, (new_m,)


def _adam(w, g, states, lr, wd, mom, t, beta1=0.9, beta2=0.999, epsilon=1e-8,
          clip_gradient=-1.0):
    # bias correction folded into lr, as the reference's Adam.update does
    # (python/mxnet/optimizer/optimizer.py Adam)
    coef1 = 1.0 - beta1 ** t
    coef2 = 1.0 - beta2 ** t
    lr_t = lr * jnp.sqrt(coef2) / coef1
    new_w, new_mean, new_var = _uo.adam_update_fn(
        w, g, states[0], states[1], lr_t, beta1=beta1, beta2=beta2,
        epsilon=epsilon, wd=wd, clip_gradient=clip_gradient)
    return new_w, (new_mean, new_var)


# name -> (update_fn, state_init, accepted extra hyperparameter keys)
_FUNCTIONAL_OPTS = {
    "sgd": (_sgd,
            lambda w, mom: () if mom == 0.0 else (jnp.zeros_like(w),),
            ("clip_gradient",)),
    "adam": (_adam,
             lambda w, mom: (jnp.zeros_like(w), jnp.zeros_like(w)),
             ("beta1", "beta2", "epsilon", "clip_gradient")),
}


class ShardedTrainStep:
    """One jitted, mesh-sharded training step for a gluon block.

    Parameters
    ----------
    block : HybridBlock — initialized, shapes settled.
    loss : callable ``loss(out, label) -> NDArray`` (e.g. a gluon Loss).
    mesh : jax.sharding.Mesh with a data axis (and optionally model/sp axes).
    optimizer : "sgd" | "adam".
    optimizer_params : dict — learning_rate, momentum, wd (python-side; a
        changed learning rate does NOT retrigger compilation: hyperparams are
        traced scalars).
    data_axis : mesh axis name the batch is sharded over.
    param_specs : list of ``(name_regex, PartitionSpec)`` — tensor-parallel
        placement rules; first match wins; default replicated. Shapes not
        divisible by the mesh axis fall back to replicated.
    batch_specs : optional list of PartitionSpecs, one per flattened batch
        input; default shards dim 0 over `data_axis`.
    forward : optional ``forward(block, *batch) -> loss NDArray`` overriding
        the default ``loss(block(data), label)`` convention.
    shard_weight_update : bool — ZeRO-1 cross-replica weight-update sharding
        (arXiv:2004.13336): optimizer state of REPLICATED trainable params
        whose dim 0 divides the data-axis size is sharded over that axis
        (reduce-scatter grad -> shard-local update -> all-gather weight,
        bit-identical loss, state memory / replica count). Params that are
        tensor-parallel or not divisible silently keep replicated state.
    """

    def __init__(self, block, loss, mesh, optimizer="sgd",
                 optimizer_params=None, data_axis="data", param_specs=(),
                 batch_specs=None, forward=None, donate=True,
                 shard_weight_update=False):
        self._block = block
        self._loss = loss
        self._mesh = mesh
        self._data_axis = data_axis
        self._forward = forward
        self._donate = donate
        self._batch_specs = batch_specs

        opt_params = dict(optimizer_params or {})
        self._lr = float(opt_params.pop("learning_rate", 0.01))
        self._mom = float(opt_params.pop("momentum", 0.0))
        self._wd = float(opt_params.pop("wd", 0.0))
        self._lr_scheduler = opt_params.pop("lr_scheduler", None)
        if optimizer not in _FUNCTIONAL_OPTS:
            raise MXNetError("ShardedTrainStep supports %s; got %r"
                             % (sorted(_FUNCTIONAL_OPTS), optimizer))
        update_fn, state_init, extra_keys = _FUNCTIONAL_OPTS[optimizer]
        extras = {k: opt_params.pop(k) for k in list(opt_params)
                  if k in extra_keys}
        if opt_params:
            raise MXNetError("unknown optimizer_params for %r: %s"
                             % (optimizer, sorted(opt_params)))
        self._update_fn = (lambda *a, _f=update_fn, _e=extras: _f(*a, **_e))
        self._num_update = 0

        params = list(block.collect_params().values())
        if any(p._data is None for p in params):
            raise MXNetError(
                "initialize() the block and run one forward pass before "
                "building a ShardedTrainStep")
        self._params = params
        self._trainable = [p.grad_req != "null" for p in params]

        # a mesh spanning several processes (multi-host DCN training) needs
        # global-array assembly instead of plain device_put — each process
        # contributes its addressable shards (the reference's ps-lite
        # worker/server split becomes this one symmetric path)
        self._multiprocess = len(
            {d.process_index for d in mesh.devices.flat}) > 1

        rules = [(re.compile(pat), spec) for pat, spec in param_specs]
        self._param_shardings = [
            NamedSharding(mesh, self._spec_for(p, rules)) for p in params]
        self._param_datas = [
            self._place(p.data()._data, s)
            for p, s in zip(params, self._param_shardings)]
        for p, d in zip(params, self._param_datas):
            p.data()._set_data(d)
        # ZeRO-1 / cross-replica weight-update sharding (Xu et al. 2020,
        # arXiv:2004.13336 — PAPERS.md): optimizer state of replicated
        # params is sharded over the data axis; GSPMD then lowers the
        # update to reduce-scatter(grad) -> shard-local update ->
        # all-gather(weight), cutting state memory and update FLOPs by the
        # replica count with bit-identical results (tests/test_parallel.py
        # asserts the loss trajectory matches the replicated run).
        def _state_sharding(p_sh, d, t):
            if not (shard_weight_update and t):
                return p_sh
            ax = mesh.shape.get(data_axis, 1)
            if (p_sh.is_fully_replicated and d.ndim >= 1 and d.shape
                    and d.shape[0] % ax == 0 and ax > 1):
                return NamedSharding(mesh, P(data_axis))
            return p_sh

        state_plans = [
            _state_sharding(sh, d, t)
            for d, t, sh in zip(self._param_datas, self._trainable,
                                self._param_shardings)]
        self._opt_states = [
            tuple(self._place(s0, plan) for s0 in state_init(
                jax.ShapeDtypeStruct(d.shape, d.dtype), self._mom))
            if t else ()
            for d, t, plan in zip(self._param_datas, self._trainable,
                                  state_plans)]
        self._state_shardings = [
            tuple(plan for _ in st)
            for st, plan in zip(self._opt_states, state_plans)]
        self._jit = None
        self._in_fmt = None
        self._policy = None
        self._last_abstract = None

    # ------------------------------------------------------------- placement
    def _place(self, data, sharding, local=False):
        """Put a host value onto the mesh. Single-process: device_put.

        Multi-process, ``local=False`` (parameters / optimizer state): every
        process holds the same FULL value and each contributes the shards it
        addresses — correct for replicated and tensor-parallel specs alike.
        ``local=True`` (batch inputs): the value is this process's local
        shard and the global batch is their concatenation (standard SPMD
        per-host data loading).
        """
        if not self._multiprocess:
            return jax.device_put(data, sharding)
        import numpy as np
        from jax.experimental import multihost_utils
        arr = np.asarray(data)
        if local:
            return multihost_utils.host_local_array_to_global_array(
                arr, self._mesh, sharding.spec)
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx])

    def _spec_for(self, param, rules):
        for pat, spec in rules:
            if pat.match(param.name):
                spec = spec if isinstance(spec, P) else P(*spec)
                # replicated fallback when the shape doesn't divide the mesh
                ok = True
                for dim, axis in zip(param.shape, tuple(spec)):
                    if axis is None:
                        continue
                    axes = axis if isinstance(axis, tuple) else (axis,)
                    size = 1
                    for a in axes:
                        if a not in self._mesh.shape:
                            raise MXNetError(
                                "param_specs rule %r -> %s names axis %r not "
                                "in mesh axes %s"
                                % (pat.pattern, spec, a,
                                   tuple(self._mesh.shape)))
                        size *= self._mesh.shape[a]
                    if dim % size:
                        ok = False
                if ok:
                    return spec
                return P()
        return P()

    # ------------------------------------------------------------------ step
    def _build(self, in_fmt, n_inputs):
        from .. import telemetry
        from ..ops.registry import policy_key
        # retrace watchdog: one compile per batch structure — after the
        # first step this site must stay flat (an in_fmt change means the
        # caller reshaped its batch pytree mid-run)
        telemetry.record_retrace(
            "parallel.train_step",
            {"block": type(self._block).__name__, "n_inputs": n_inputs,
             "donate": bool(self._donate),
             "policy_key": list(policy_key())})
        params, trainable = self._params, self._trainable
        block, loss_blk, forward = self._block, self._loss, self._forward
        update_fn = self._update_fn
        t_idx = [i for i, t in enumerate(trainable) if t]

        wd, mom = self._wd, self._mom  # static: `if wd:` in the kernels

        def step(param_datas, opt_states, hyper, rng, in_datas):
            lr, t = hyper  # traced scalars: lr schedule / step count don't recompile
            frozen = list(param_datas)

            def loss_of(train_datas):
                datas = list(frozen)
                for i, d in zip(t_idx, train_datas):
                    datas[i] = d

                def body():
                    args, _, _ = _regroup(
                        [NDArray(d) for d in in_datas], in_fmt)
                    if forward is not None:
                        return forward(block, *args)
                    if len(args) < 2:
                        raise MXNetError(
                            "default convention needs (data..., label); pass "
                            "forward= for custom batch structures")
                    out = block(*args[:-1])
                    return loss_blk(out, args[-1])

                out, aux = _run_traced(params, datas, rng, True, body)
                scalar = jnp.mean(out._data)
                return scalar, aux

            train_datas = [param_datas[i] for i in t_idx]
            (loss_val, aux), grads = jax.value_and_grad(
                loss_of, has_aux=True)(train_datas)

            new_datas = list(param_datas)
            new_states = [list(s) for s in opt_states]
            for j, i in enumerate(t_idx):
                w, st = update_fn(new_datas[i], grads[j], opt_states[i],
                                  lr, wd, mom, t)
                # the f32 lr/state promote the arithmetic to f32 (precision),
                # but storage keeps the parameter dtype (bf16 fast path) —
                # the reference's multi-precision update pattern
                # (optimizer.py:500 mp_sgd_update)
                new_datas[i] = w.astype(param_datas[i].dtype)
                new_states[i] = [s.astype(o.dtype)
                                 for s, o in zip(st, opt_states[i])]
            for i, a in enumerate(aux):
                if a is not None:  # BatchNorm moving stats etc.
                    new_datas[i] = a.astype(new_datas[i].dtype)
            return new_datas, new_states, loss_val

        mesh = self._mesh
        repl = NamedSharding(mesh, P())
        if self._batch_specs is not None:
            in_specs = [spec if isinstance(spec, P) else P(*spec)
                        for spec in self._batch_specs]
        else:
            in_specs = [P(self._data_axis)] * n_inputs
        self._in_shardings = [NamedSharding(mesh, s) for s in in_specs]
        donate = (0, 1) if self._donate else ()
        return jax.jit(
            step,
            in_shardings=(self._param_shardings,
                          [list(s) for s in self._state_shardings],
                          None, None, self._in_shardings),
            out_shardings=(self._param_shardings,
                           [list(s) for s in self._state_shardings],
                           repl),
            donate_argnums=donate)

    def __call__(self, *batch):
        """Run one step on a batch (``(data, label)`` by default). Returns the
        scalar loss as a lazy NDArray — no host sync (SURVEY §1: frontend
        never blocks; sync at asnumpy())."""
        in_fmt = []
        flat = _flatten_nd(batch, in_fmt)
        in_datas = [x._data if isinstance(x, NDArray) else jnp.asarray(x)
                    for x in flat]
        # rebuild on a policy flip too: the traced block consults the
        # registry.policy_key levers (BN one-pass, conv routing, ...) at
        # trace time — reusing the old executable would silently run the
        # stale policy (the aliasing hazard documented at registry.py:90)
        from ..ops.registry import policy_key
        policy = policy_key()
        if self._jit is None or self._in_fmt != in_fmt \
                or self._policy != policy:
            self._jit = self._build(in_fmt, len(in_datas))
            self._in_fmt = in_fmt
            self._policy = policy
            self._last_abstract = None
        in_datas = [self._place(d, s, local=True)
                    for d, s in zip(in_datas, self._in_shardings)]
        self._num_update += 1
        lr = (self._lr_scheduler(self._num_update)
              if self._lr_scheduler else self._lr)
        hyper = (jnp.float32(lr), jnp.float32(self._num_update))
        rng = _random.next_key()
        opt_states = [list(s) for s in self._opt_states]
        if self._last_abstract is None:
            # abstract shapes for compiled_step_flops; shapes are invariant
            # per (in_fmt, shapes) so capture once, off the per-step path
            self._last_abstract = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                (self._param_datas, opt_states, hyper, rng, in_datas))
        new_datas, new_states, loss = self._jit(
            self._param_datas, opt_states, hyper, rng, in_datas)
        self._param_datas = new_datas
        self._opt_states = [tuple(s) for s in new_states]
        for p, d in zip(self._params, new_datas):
            p.data()._set_data(d)
        return NDArray(loss)

    def compiled_step_flops(self):
        """FLOPs of one compiled step per XLA's own cost model.

        The analog of the reference's per-op FLOP counting in its benchmark
        scripts — but measured on the exact fused HLO that runs, not a
        hand-derived formula. Requires at least one __call__ (shapes must be
        known); pays one extra (cached-HLO) compile.
        """
        if self._jit is None or self._last_abstract is None:
            raise MXNetError("run at least one step before asking for FLOPs")
        compiled = self._jit.lower(*self._last_abstract).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):  # older jax returns [dict]
            cost = cost[0]
        return float(cost["flops"])

    @property
    def learning_rate(self):
        if self._lr_scheduler is not None:
            return self._lr_scheduler(max(self._num_update, 1))
        return self._lr

    def set_learning_rate(self, lr):
        if self._lr_scheduler is not None:
            # the reference Trainer raises here too (gluon/trainer.py)
            raise MXNetError(
                "cannot set learning_rate: an lr_scheduler is active")
        self._lr = float(lr)
