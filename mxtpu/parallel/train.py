"""ShardedTrainStep: the whole training step as ONE compiled sharded program.

This is the TPU-native fast path that replaces the reference's entire
per-batch machinery — DataParallelExecutorGroup batch slicing
(python/mxnet/module/executor_group.py:281), KVStore push/pull gradient
reduction (src/kvstore/kvstore_local.h:184), and in-engine optimizer kernels
(src/operator/optimizer_op.cc) — with a single ``jax.jit`` over a
`jax.sharding.Mesh`:

* forward + loss + backward + optimizer update trace into one XLA program,
* the batch is sharded on the ``data`` axis; the mean loss / summed gradients
  ARE the cross-device all-reduce (GSPMD inserts the collectives — the
  explicit push/pull of the reference becomes implicit dataflow),
* parameters may carry PartitionSpecs (tensor parallelism — absent from the
  reference, SURVEY §2.3) and are donated, so the update is in-place in HBM
  like the reference's in-engine mutate-in-place optimizer ops.

The block's imperative forward is traced through the same `_TraceFrame`
machinery as CachedOp (mxtpu/gluon/block.py), so BatchNorm moving-stat
updates and Dropout RNG stay functional under the trace.

Since ISSUE 7 this class is a thin wrapper over machinery shared with the
mesh-native ``gluon.Trainer``: the optimizer update rules come from the
``mxtpu.optimizer_fused`` registry (full zoo, traced-t hyper twins, one
multi-precision storage rule), and the ZeRO-1 state-sharding plan mirrors
``optimizer_fused.MeshPlan`` — the difference is only WHERE backward
lives (inside this one jit vs the eager autograd tape).
"""
from __future__ import annotations

import re

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import optimizer as opt_mod
from .. import optimizer_fused as _fused
from .. import random as _random
from ..base import MXNetError
from ..gluon.block import _flatten_nd, _regroup, _run_traced
from ..ndarray import NDArray
from ..optimizer_fused import _tree_data

__all__ = ["ShardedTrainStep", "pure_forward"]




def pure_forward(block, train=False):
    """Extract the block's forward as a pure jittable function.

    Returns ``(fn, param_datas)`` where ``fn(param_datas, *input_arrays,
    rng=None)`` maps raw jax arrays to raw jax array(s). For stochastic
    layers (Dropout) in ``train=True`` mode, each ``rng=None`` call draws a
    fresh key from ``mxtpu.random`` — two calls produce DIFFERENT dropout
    masks, matching eager semantics (a fixed default key would silently
    reuse one mask forever). Under an outer ``jax.jit`` the draw happens at
    trace time and is baked into the executable: pass ``rng=`` explicitly
    per call there. ``train=False`` keeps a fixed key — deterministic
    inference needs no entropy. The block must be initialized with shapes
    settled (run one eager forward first for deferred init).
    """
    params = list(block.collect_params().values())
    if any(p._data is None for p in params):
        raise MXNetError(
            "pure_forward requires initialized parameters; call initialize() "
            "and run one forward pass to settle deferred shapes")
    param_datas = [p.data()._data for p in params]

    def fn(param_datas, *in_datas, rng=None):
        if rng is None:
            key = _random.next_key() if train else jax.random.PRNGKey(0)
        else:
            key = rng

        def body():
            return block(*[NDArray(d) for d in in_datas])
        out, _aux = _run_traced(params, param_datas, key, train, body)
        flat = _flatten_nd(out, [])
        datas = [o._data for o in flat]
        return datas[0] if len(datas) == 1 else tuple(datas)

    return fn, param_datas


class ShardedTrainStep:
    """One jitted, mesh-sharded training step for a gluon block.

    Parameters
    ----------
    block : HybridBlock — initialized, shapes settled.
    loss : callable ``loss(out, label) -> NDArray`` (e.g. a gluon Loss).
    mesh : jax.sharding.Mesh with a data axis (and optionally model/sp axes).
    optimizer : registry name (or Optimizer instance) with a traced-t
        functional rule in the ``mxtpu.optimizer_fused`` registry — the
        whole zoo (sgd/adam/rmsprop/adagrad/adadelta/ftrl/adamax/nag/
        signum/ftml/dcasgd/groupadagrad, ``optimizer_fused.
        traced_rule_names()``), ONE registry shared with the fused Trainer
        step so the two jit surfaces cannot drift. Host-state optimizers
        (Nadam's m_schedule, SGLD's rng, LBSGD's norms) have no pure rule
        and raise — use the eager ``gluon.Trainer`` for those.
    optimizer_params : dict — learning_rate, momentum, wd, clip_gradient,
        betas... (python-side; a changed learning rate does NOT retrigger
        compilation: hyperparams are traced scalars).
    data_axis : mesh axis name the batch is sharded over.
    param_specs : list of ``(name_regex, PartitionSpec)`` — tensor-parallel
        placement rules; first match wins; default replicated. Shapes not
        divisible by the mesh axis fall back to replicated.
    batch_specs : optional list of PartitionSpecs, one per flattened batch
        input; default shards dim 0 over `data_axis`.
    forward : optional ``forward(block, *batch) -> loss NDArray`` overriding
        the default ``loss(block(data), label)`` convention.
    shard_weight_update : bool — ZeRO-1 cross-replica weight-update sharding
        (arXiv:2004.13336): optimizer state of REPLICATED trainable params
        whose dim 0 divides the data-axis size is sharded over that axis
        (reduce-scatter grad -> shard-local update -> all-gather weight,
        bit-identical loss, state memory / replica count). Params that are
        tensor-parallel or not divisible silently keep replicated state.
    """

    def __init__(self, block, loss, mesh, optimizer="sgd",
                 optimizer_params=None, data_axis="data", param_specs=(),
                 batch_specs=None, forward=None, donate=True,
                 shard_weight_update=False):
        self._block = block
        self._loss = loss
        self._mesh = mesh
        self._data_axis = data_axis
        self._forward = forward
        self._donate = donate
        self._batch_specs = batch_specs

        opt_params = dict(optimizer_params or {})
        self._lr_scheduler = opt_params.pop("lr_scheduler", None)
        if isinstance(optimizer, opt_mod.Optimizer):
            if opt_params:
                raise MXNetError("optimizer_params must be empty when "
                                 "optimizer is an Optimizer instance")
            opt = optimizer
        else:
            try:
                opt = opt_mod.create(optimizer, **opt_params)
            except TypeError as e:
                raise MXNetError("unknown optimizer_params for %r: %s"
                                 % (optimizer, e))
        # ONE functional-rule registry for both jit surfaces (ISSUE 7
        # satellite): the fused Trainer step and this sharded step draw the
        # same static/step/thyper triple, so the zoo and the multi-precision
        # storage rule cannot fork between them
        rule = _fused.functional_rule(opt)
        if rule is None or rule.thyper is None:
            raise MXNetError(
                "ShardedTrainStep needs a pure traced-t update rule from "
                "the mxtpu.optimizer_fused registry; %r has none "
                "(supported: %s). Host-state optimizers (Nadam/SGLD/LBSGD) "
                "keep their eager semantics on the gluon.Trainer path."
                % (optimizer, _fused.traced_rule_names()))
        if getattr(opt, "multi_precision", False):
            raise MXNetError(
                "ShardedTrainStep's in-jit update does not implement the "
                "multi-precision (f32-master) storage rule — its states "
                "would be (master, base) tuples the shared rule cannot "
                "consume. Use the mesh-native gluon.Trainer, whose "
                "FusedUpdater handles multi_precision sharded.")
        self._opt = opt
        self._rule = rule
        self._static = rule.static(opt)
        self._wd = float(opt.wd)
        self._num_update = 0

        params = list(block.collect_params().values())
        if any(p._data is None for p in params):
            raise MXNetError(
                "initialize() the block and run one forward pass before "
                "building a ShardedTrainStep")
        self._params = params
        self._trainable = [p.grad_req != "null" for p in params]

        # a mesh spanning several processes (multi-host DCN training) needs
        # global-array assembly instead of plain device_put — each process
        # contributes its addressable shards (the reference's ps-lite
        # worker/server split becomes this one symmetric path)
        self._multiprocess = len(
            {d.process_index for d in mesh.devices.flat}) > 1

        rules = [(re.compile(pat), spec) for pat, spec in param_specs]
        self._param_shardings = [
            NamedSharding(mesh, self._spec_for(p, rules)) for p in params]
        self._param_datas = [
            self._place(p.data()._data, s)
            for p, s in zip(params, self._param_shardings)]
        for p, d in zip(params, self._param_datas):
            p.data()._set_data(d)
        # optimizer state in the RULE's structure (None | array | tuple —
        # exactly what the optimizer's create_state builds and the shared
        # step fn consumes), materialized up front and placed on the mesh
        raw_states = [
            _tree_data(self._opt.create_state_multi_precision(
                i, NDArray(d))) if t else None
            for i, (d, t) in enumerate(zip(self._param_datas,
                                           self._trainable))]

        # ZeRO-1 / cross-replica weight-update sharding (Xu et al. 2020,
        # arXiv:2004.13336 — PAPERS.md): optimizer state of replicated
        # params is sharded over the data axis; GSPMD then lowers the
        # update to reduce-scatter(grad) -> shard-local update ->
        # all-gather(weight), cutting state memory and update FLOPs by the
        # replica count with bit-identical results (tests/test_parallel.py
        # asserts the loss trajectory matches the replicated run).
        def _state_sharding(p_sh, d, st):
            if not shard_weight_update:
                return p_sh
            ax = mesh.shape.get(data_axis, 1)
            leaves = jax.tree_util.tree_leaves(st)
            if (p_sh.is_fully_replicated and ax > 1 and d.ndim >= 1
                    and d.shape and d.shape[0] % ax == 0
                    and all(l.ndim >= 1 and l.shape
                            and l.shape[0] % ax == 0 for l in leaves)):
                return NamedSharding(mesh, P(data_axis))
            return p_sh

        state_plans = [
            _state_sharding(sh, d, st)
            for d, st, sh in zip(self._param_datas, raw_states,
                                 self._param_shardings)]
        self._opt_states = [
            jax.tree_util.tree_map(lambda s, _pl=plan: self._place(s, _pl),
                                   st)
            for st, plan in zip(raw_states, state_plans)]
        self._state_shardings = [
            jax.tree_util.tree_map(lambda _s, _pl=plan: _pl, st)
            for st, plan in zip(raw_states, state_plans)]
        self._jit = None
        self._in_fmt = None
        self._in_sig = None
        self._policy = None
        self._last_abstract = None

    # ------------------------------------------------------------- placement
    def _place(self, data, sharding, local=False):
        """Put a host value onto the mesh. Single-process: device_put.

        Multi-process, ``local=False`` (parameters / optimizer state): every
        process holds the same FULL value and each contributes the shards it
        addresses — correct for replicated and tensor-parallel specs alike.
        ``local=True`` (batch inputs): the value is this process's local
        shard and the global batch is their concatenation (standard SPMD
        per-host data loading).
        """
        if not self._multiprocess:
            return jax.device_put(data, sharding)
        import numpy as np
        from jax.experimental import multihost_utils
        arr = np.asarray(data)
        if local:
            return multihost_utils.host_local_array_to_global_array(
                arr, self._mesh, sharding.spec)
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx])

    def _spec_for(self, param, rules):
        for pat, spec in rules:
            if pat.match(param.name):
                spec = spec if isinstance(spec, P) else P(*spec)
                # replicated fallback when the shape doesn't divide the mesh
                ok = True
                for dim, axis in zip(param.shape, tuple(spec)):
                    if axis is None:
                        continue
                    axes = axis if isinstance(axis, tuple) else (axis,)
                    size = 1
                    for a in axes:
                        if a not in self._mesh.shape:
                            raise MXNetError(
                                "param_specs rule %r -> %s names axis %r not "
                                "in mesh axes %s"
                                % (pat.pattern, spec, a,
                                   tuple(self._mesh.shape)))
                        size *= self._mesh.shape[a]
                    if dim % size:
                        ok = False
                if ok:
                    return spec
                return P()
        return P()

    # ------------------------------------------------------------------ step
    def _resolve_in_shardings(self, n_inputs):
        """Batch input shardings (needed for placement BEFORE the build,
        so the compile service can AOT-lower against placed example
        args)."""
        mesh = self._mesh
        if self._batch_specs is not None:
            in_specs = [spec if isinstance(spec, P) else P(*spec)
                        for spec in self._batch_specs]
        else:
            in_specs = [P(self._data_axis)] * n_inputs
        self._in_shardings = [NamedSharding(mesh, s) for s in in_specs]

    def _build(self, in_fmt, n_inputs, example_args=None):
        from ..ops.registry import policy_key
        # retrace watchdog: one compile per batch structure — after the
        # first step this site must stay flat (an in_fmt change means the
        # caller reshaped its batch pytree mid-run); recorded at the
        # bottom of this builder where the finished jit can ride
        # compiled= into the xprof ledger
        retrace_prov = {
            "block": type(self._block).__name__, "n_inputs": n_inputs,
            "donate": bool(self._donate),
            "policy_key": list(policy_key())}
        params, trainable = self._params, self._trainable
        block, loss_blk, forward = self._block, self._loss, self._forward
        rule, static = self._rule, self._static
        thyper = rule.thyper
        t_idx = [i for i, t in enumerate(trainable) if t]

        wd = self._wd  # static: `if wd:` in the kernels

        def step(param_datas, opt_states, hyper, rng, in_datas):
            lr, t = hyper  # traced scalars: lr schedule / step count don't recompile
            frozen = list(param_datas)

            def loss_of(train_datas):
                datas = list(frozen)
                for i, d in zip(t_idx, train_datas):
                    datas[i] = d

                def body():
                    args, _, _ = _regroup(
                        [NDArray(d) for d in in_datas], in_fmt)
                    if forward is not None:
                        return forward(block, *args)
                    if len(args) < 2:
                        raise MXNetError(
                            "default convention needs (data..., label); pass "
                            "forward= for custom batch structures")
                    out = block(*args[:-1])
                    return loss_blk(out, args[-1])

                out, aux = _run_traced(params, datas, rng, True, body)
                scalar = jnp.mean(out._data)
                return scalar, aux

            train_datas = [param_datas[i] for i in t_idx]
            (loss_val, aux), grads = jax.value_and_grad(
                loss_of, has_aux=True)(train_datas)

            new_datas = list(param_datas)
            new_states = list(opt_states)
            # the shared registry's traced-t hyper twin (optimizer_fused
            # thyper): bias-correction terms are built IN-GRAPH from the
            # traced (lr, wd, t), so schedules and step count never
            # recompile — same tuples the fused Trainer step traces
            h = thyper(static, lr, wd, t)
            for j, i in enumerate(t_idx):
                w, st = rule.step(new_datas[i], grads[j], opt_states[i],
                                  h, 1.0, static)
                # the f32 lr/state promote the arithmetic to f32 (precision),
                # but storage keeps the parameter dtype (bf16 fast path) —
                # the reference's multi-precision update pattern
                # (optimizer.py:500 mp_sgd_update), shared with FusedUpdater
                new_datas[i] = w.astype(param_datas[i].dtype)
                new_states[i] = jax.tree_util.tree_map(
                    lambda n, o: n.astype(o.dtype), st, opt_states[i])
            for i, a in enumerate(aux):
                if a is not None:  # BatchNorm moving stats etc.
                    new_datas[i] = a.astype(new_datas[i].dtype)
            return new_datas, new_states, loss_val

        mesh = self._mesh
        repl = NamedSharding(mesh, P())
        self._resolve_in_shardings(n_inputs)
        donate = (0, 1) if self._donate else ()

        def build():
            return jax.jit(
                step,
                in_shardings=(self._param_shardings,
                              list(self._state_shardings),
                              None, None, self._in_shardings),
                out_shardings=(self._param_shardings,
                               list(self._state_shardings),
                               repl),
                donate_argnums=donate)

        from .. import compile_service as csvc
        in_shapes = None
        if example_args is not None:
            in_shapes = tuple((tuple(d.shape), str(d.dtype))
                              for d in example_args[4])
        key = csvc.canonical_key(
            site="parallel.train_step",
            fn_id="train_step:%s:%s:%s:%s" % (
                type(block).__name__, csvc.source_token(type(block)),
                csvc.source_token(loss_blk) if loss_blk is not None
                else "-",
                csvc.source_token(forward) if forward is not None
                else "-"),
            signature=(tuple(in_fmt), n_inputs, in_shapes, repr(static),
                       type(self._opt).__name__, tuple(trainable),
                       self._wd,
                       tuple((tuple(d.shape), str(d.dtype))
                             for d in self._param_datas)),
            policy=policy_key(),
            # per-buffer sharding tokens: a TP layout and a DP layout of
            # the same shapes are DIFFERENT executables (in/out
            # shardings are compiled in)
            sharding=(self._plan_fingerprint(),
                      tuple(str(s) for s in self._param_shardings),
                      tuple(repr(jax.tree_util.tree_map(str, s))
                            for s in self._state_shardings)),
            donation=donate, device=csvc.device_token(mesh=mesh),
            nonce=csvc.instance_nonce(self))
        entry = csvc.get_or_build(
            key, build, provenance=retrace_prov,
            example_args=csvc.concrete_args(example_args)
            if example_args is not None else None)
        return entry.fn

    def _plan_fingerprint(self):
        """Mesh layout token for the cache key: shape, axis names, and
        the batch specs that drive the input shardings."""
        return (tuple(self._mesh.shape.items()), self._data_axis,
                repr(self._batch_specs), bool(self._donate))

    def __call__(self, *batch):
        """Run one step on a batch (``(data, label)`` by default). Returns the
        scalar loss as a lazy NDArray — no host sync (SURVEY §1: frontend
        never blocks; sync at asnumpy())."""
        in_fmt = []
        flat = _flatten_nd(batch, in_fmt)
        in_datas = [x._data if isinstance(x, NDArray) else jnp.asarray(x)
                    for x in flat]
        # rebuild on a policy flip too: the traced block consults the
        # registry.policy_key levers (BN one-pass, conv routing, ...) at
        # trace time — reusing the old executable would silently run the
        # stale policy (the aliasing hazard documented at registry.py:90)
        from ..ops.registry import policy_key
        policy = policy_key()
        # input shapes join the rebuild condition: the compile service
        # may hand back a shape-pinned AOT executable (disk-warm start),
        # and a changed signature is a real compile either way — a
        # repeated signature is a service hit, not a retrace
        in_sig = tuple((tuple(d.shape), str(d.dtype)) for d in in_datas)
        rebuild = self._jit is None or self._in_fmt != in_fmt \
            or self._policy != policy or self._in_sig != in_sig
        prev_shardings = getattr(self, "_in_shardings", None)
        if rebuild:
            self._resolve_in_shardings(len(in_datas))
            self._last_abstract = None
        in_datas = [self._place(d, s, local=True)
                    for d, s in zip(in_datas, self._in_shardings)]
        self._num_update += 1
        lr = (self._lr_scheduler(self._num_update)
              if self._lr_scheduler else float(self._opt.learning_rate))
        hyper = (jnp.float32(lr), jnp.float32(self._num_update))
        rng = _random.next_key()
        if rebuild:
            # built AFTER placement so the service can AOT-lower (and
            # persist) against the real placed argument signature; the
            # rebuild-condition state (incl. the input shardings the
            # placement consumed) commits only on SUCCESS — a transient
            # build failure must not leave a stale-policy executable or
            # mismatched shardings looking current on the next step
            try:
                self._jit = self._build(in_fmt, len(in_datas),
                                        example_args=(self._param_datas,
                                                      self._opt_states,
                                                      hyper, rng,
                                                      in_datas))
            except BaseException:
                self._in_shardings = prev_shardings
                raise
            self._in_fmt = in_fmt
            self._policy = policy
            self._in_sig = in_sig
        if self._last_abstract is None:
            # abstract shapes for compiled_step_flops; shapes are invariant
            # per (in_fmt, shapes) so capture once, off the per-step path
            self._last_abstract = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                (self._param_datas, self._opt_states, hyper, rng, in_datas))
        new_datas, new_states, loss = self._jit(
            self._param_datas, self._opt_states, hyper, rng, in_datas)
        self._param_datas = new_datas
        self._opt_states = new_states
        for p, d in zip(self._params, new_datas):
            p.data()._set_data(d)
        return NDArray(loss)

    def compiled_step_flops(self):
        """FLOPs of one compiled step per XLA's own cost model.

        The analog of the reference's per-op FLOP counting in its benchmark
        scripts — but measured on the exact fused HLO that runs, not a
        hand-derived formula. Requires at least one __call__ (shapes must be
        known); pays one extra (cached-HLO) compile.
        """
        if self._jit is None or self._last_abstract is None:
            raise MXNetError("run at least one step before asking for FLOPs")
        if hasattr(self._jit, "cost_analysis"):
            # the compile service handed back an AOT executable (disk-warm
            # or spill path): its own analyses are the exact HLO that runs
            compiled = self._jit
        else:
            compiled = self._jit.lower(*self._last_abstract).compile()
        from .. import perf_model
        flops = perf_model.flops_of(compiled)  # list/dict/None-proof
        if flops is None:
            raise MXNetError(
                "XLA cost analysis exposes no flops for this "
                "executable on this backend/jax version")
        return flops

    @property
    def learning_rate(self):
        if self._lr_scheduler is not None:
            return self._lr_scheduler(max(self._num_update, 1))
        return float(self._opt.learning_rate)

    def set_learning_rate(self, lr):
        if self._lr_scheduler is not None:
            # the reference Trainer raises here too (gluon/trainer.py)
            raise MXNetError(
                "cannot set learning_rate: an lr_scheduler is active")
        self._opt.set_learning_rate(float(lr))
