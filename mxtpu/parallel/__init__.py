"""mxtpu.parallel: distributed training over the TPU device mesh.

This package is the TPU-native replacement for the reference's entire
multi-device/multi-node stack (SURVEY §2.3): the CUDA-P2P comm trees
(src/kvstore/comm.h, comm_tree.h, gpu_topology.h), NCCL store
(src/kvstore/kvstore_nccl.h) and the ps-lite parameter-server plane
(src/kvstore/kvstore_dist.h) all collapse into ONE mechanism — a
`jax.sharding.Mesh` with named axes, sharding annotations on a single jitted
training program, and XLA-inserted collectives riding ICI (DCN across slices).

What the reference could not express (SURVEY §2.3 "Parallelism NOT present" —
no tensor/sequence/context parallelism) is first-class here:

* ``data``  axis — batch sharding (the reference's data-parallel KVStore path),
* ``model`` axis — tensor parallelism via parameter PartitionSpecs,
* ``sp``    axis — sequence/context parallelism: ring attention
  (:mod:`mxtpu.parallel.ring_attention`) rotates K/V blocks around the ring
  with ``ppermute`` while accumulating flash-style online softmax.
"""
from .mesh import (make_mesh, data_parallel_mesh, is_multiprocess_mesh,
                   host_value, place_global)
from .train import ShardedTrainStep, pure_forward
from .ring_attention import ring_attention, ring_flash_attention, ring_self_attention
from .pipeline import pipeline_apply
from .moe import switch_ffn, shard_experts

__all__ = ["make_mesh", "data_parallel_mesh", "is_multiprocess_mesh",
           "host_value", "place_global", "ShardedTrainStep",
           "pipeline_apply", "switch_ffn", "shard_experts",
           "pure_forward", "ring_attention", "ring_flash_attention",
           "ring_self_attention"]
