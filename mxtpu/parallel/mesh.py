"""Device-mesh construction.

The reference discovers topology at runtime (CUDA P2P link matrix →
Kernighan-Lin tree partitioning, src/kvstore/gpu_topology.h); on TPU the ICI
torus topology is XLA's concern — the framework only names logical axes and
lets the compiler map collectives onto the interconnect.
"""
from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

__all__ = ["make_mesh", "data_parallel_mesh", "is_multiprocess_mesh",
           "host_value", "place_global"]


def make_mesh(axes, devices=None):
    """Build a named `jax.sharding.Mesh`.

    Parameters
    ----------
    axes : dict[str, int]
        Ordered mapping of axis name -> size. At most one size may be ``-1``,
        which absorbs all remaining devices.
    devices : list, optional
        Devices to lay out (default ``jax.devices()``).

    Examples
    --------
    >>> mesh = make_mesh({"data": -1})                    # pure DP
    >>> mesh = make_mesh({"data": 2, "sp": 2, "model": 2})  # DP x SP x TP
    """
    if devices is None:
        devices = jax.devices()
    names = list(axes)
    sizes = [axes[n] for n in names]
    n_dev = len(devices)
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        if n_dev % known:
            raise ValueError(
                "cannot infer -1 axis: %d devices not divisible by %d"
                % (n_dev, known))
        sizes[sizes.index(-1)] = n_dev // known
    total = int(np.prod(sizes))
    if total > n_dev:
        raise ValueError("mesh %s needs %d devices, only %d available"
                         % (axes, total, n_dev))
    if total < n_dev:
        import warnings
        warnings.warn("mesh %s uses %d of %d devices; the remaining %d are "
                      "idle (use -1 on one axis to absorb all devices)"
                      % (dict(zip(names, sizes)), total, n_dev,
                         n_dev - total), stacklevel=2)
    arr = np.asarray(devices[:total]).reshape(sizes)
    return Mesh(arr, tuple(names))


def data_parallel_mesh(devices=None, axis="data"):
    """All devices on one data axis — the KVStore `device`/`nccl` equivalent."""
    return make_mesh({axis: -1}, devices)


def is_multiprocess_mesh(mesh):
    """True when ``mesh`` spans devices owned by more than one process —
    the fleet case, where plain ``jax.device_put`` / ``np.asarray`` on a
    global array are illegal (a host can only touch its addressable
    shards) and every placement/fetch must go through the helpers below."""
    return len({d.process_index for d in mesh.devices.flat}) > 1


def place_global(data, sharding):
    """Place a host value onto ``sharding``, multi-process safe.

    Single-process shardings take the fast path (``jax.device_put``).
    Process-spanning shardings can't: device_put would need to write
    shards this host does not address. There every process holds the SAME
    host value (replicated placement and global-batch placement both
    satisfy this in our fleet wiring) and ``make_array_from_callback``
    builds the global array from per-shard slices of it — each host
    materializes only the shards it owns."""
    arr = np.asarray(data)
    devs = getattr(sharding, "device_set", None)
    multiproc = devs is not None and \
        len({d.process_index for d in devs}) > 1
    if not multiproc:
        return jax.device_put(arr, sharding)
    return jax.make_array_from_callback(arr.shape, sharding,
                                        lambda idx: arr[idx])


def host_value(arr):
    """Fetch a global array's full value onto this host as numpy.

    Fully-addressable arrays (single-process, or fully-replicated) are a
    plain ``device_get``. A process-spanning sharded array is not —
    ``np.asarray`` raises — so the fleet path rides
    ``multihost_utils.process_allgather(tiled=True)``, which is itself a
    collective: EVERY process must call it, which our callers
    (checkpoint checksums, optimizer state dumps) do by construction."""
    if not hasattr(arr, "sharding") or getattr(
            arr, "is_fully_addressable", True):
        return np.asarray(jax.device_get(arr))
    if getattr(arr, "is_fully_replicated", False):
        return np.asarray(jax.device_get(
            arr.addressable_shards[0].data))
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(arr, tiled=True))
