"""Device-mesh construction.

The reference discovers topology at runtime (CUDA P2P link matrix →
Kernighan-Lin tree partitioning, src/kvstore/gpu_topology.h); on TPU the ICI
torus topology is XLA's concern — the framework only names logical axes and
lets the compiler map collectives onto the interconnect.
"""
from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

__all__ = ["make_mesh", "data_parallel_mesh"]


def make_mesh(axes, devices=None):
    """Build a named `jax.sharding.Mesh`.

    Parameters
    ----------
    axes : dict[str, int]
        Ordered mapping of axis name -> size. At most one size may be ``-1``,
        which absorbs all remaining devices.
    devices : list, optional
        Devices to lay out (default ``jax.devices()``).

    Examples
    --------
    >>> mesh = make_mesh({"data": -1})                    # pure DP
    >>> mesh = make_mesh({"data": 2, "sp": 2, "model": 2})  # DP x SP x TP
    """
    if devices is None:
        devices = jax.devices()
    names = list(axes)
    sizes = [axes[n] for n in names]
    n_dev = len(devices)
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        if n_dev % known:
            raise ValueError(
                "cannot infer -1 axis: %d devices not divisible by %d"
                % (n_dev, known))
        sizes[sizes.index(-1)] = n_dev // known
    total = int(np.prod(sizes))
    if total > n_dev:
        raise ValueError("mesh %s needs %d devices, only %d available"
                         % (axes, total, n_dev))
    if total < n_dev:
        import warnings
        warnings.warn("mesh %s uses %d of %d devices; the remaining %d are "
                      "idle (use -1 on one axis to absorb all devices)"
                      % (dict(zip(names, sizes)), total, n_dev,
                         n_dev - total), stacklevel=2)
    arr = np.asarray(devices[:total]).reshape(sizes)
    return Mesh(arr, tuple(names))


def data_parallel_mesh(devices=None, axis="data"):
    """All devices on one data axis — the KVStore `device`/`nccl` equivalent."""
    return make_mesh({axis: -1}, devices)
