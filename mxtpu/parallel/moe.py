"""Mixture-of-experts with expert parallelism (the GSPMD MoE formulation).

The reference has no MoE / expert parallelism (SURVEY §2.3 "Parallelism
NOT present"). This is the TPU-native design: a switch (top-1) FFN layer
expressed as dense einsums over a dispatch tensor — the GSPMD/Switch
Transformer recipe — with the stacked expert weights sharded over an
``expert`` mesh axis. Under ``jit`` on such a mesh, XLA lowers the
dispatch/combine einsums to all-to-all collectives over ICI; on one device
the same program is just dense math, so numerics are identical at any
mesh size (tests prove parity against a per-token reference).

Routing: top-1 with capacity. Each expert processes at most
``C = ceil(T / E * capacity_factor)`` tokens; tokens over capacity are
DROPPED (output zero, the standard Switch behavior — the residual path of
the surrounding block carries them). The auxiliary load-balancing loss of
Switch Transformer (mean fraction * mean router prob, scaled by E) is
returned alongside the output (scaled by E, per the paper).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..base import MXNetError

__all__ = ["switch_ffn", "shard_experts"]


def switch_ffn(x, router_w, w1, b1, w2, b2, capacity_factor=1.25):
    """Top-1 switch FFN layer.

    Parameters
    ----------
    x : (T, D) tokens.
    router_w : (D, E) router projection.
    w1, b1 : (E, D, H), (E, H) — expert up-projections.
    w2, b2 : (E, H, D), (E, D) — expert down-projections.
    capacity_factor : per-expert capacity C = ceil(T/E * factor).

    Returns ``(out, aux_loss)``: (T, D) combined expert outputs (dropped
    tokens are zero) and the scalar load-balancing loss.
    """
    t, d = x.shape
    e = router_w.shape[1]
    cap = int(-(-t * capacity_factor // e))  # ceil

    logits = x @ router_w                      # (T, E)
    probs = jax.nn.softmax(logits, axis=-1)
    gate = jnp.max(probs, axis=-1)             # (T,)
    expert = jnp.argmax(probs, axis=-1)        # (T,)

    # capacity: position of each token within its expert's queue
    onehot = jax.nn.one_hot(expert, e, dtype=x.dtype)        # (T, E)
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0          # (T, E)
    keep = (pos >= 0) & (pos < cap)
    pos_cap = jnp.where(keep, pos, 0).astype(jnp.int32)
    slot = jax.nn.one_hot(jnp.sum(pos_cap, axis=-1), cap,
                          dtype=x.dtype)                     # (T, C)
    dispatch = (onehot * keep)[:, :, None] * slot[:, None, :]  # (T, E, C)

    # dispatch -> expert batches (E, C, D): the all-to-all under GSPMD
    xin = jnp.einsum("tec,td->ecd", dispatch, x)
    h = jax.nn.relu(jnp.einsum("ecd,edh->ech", xin, w1) + b1[:, None, :])
    xout = jnp.einsum("ech,ehd->ecd", h, w2) + b2[:, None, :]

    combine = dispatch * gate[:, None, None]                 # (T, E, C)
    out = jnp.einsum("tec,ecd->td", combine, xout)

    # Switch aux loss: E * sum_e( fraction_e * mean_prob_e )
    fraction = jnp.mean(onehot, axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(fraction * mean_prob)
    return out, aux


def shard_experts(params, mesh, num_experts, expert_axis="expert"):
    """Place expert-stacked weights on the expert axis; everything else
    (e.g. the router) replicated. A leaf is expert-stacked iff its leading
    dim EQUALS ``num_experts`` — an explicit count, not a divisibility
    heuristic, so a (D, E) router with D divisible by the axis can never
    be mis-sharded over its feature dim."""
    if expert_axis not in mesh.shape:
        raise MXNetError("mesh has no %r axis; axes: %s"
                         % (expert_axis, tuple(mesh.shape)))
    size = mesh.shape[expert_axis]
    if num_experts % size:
        raise MXNetError("num_experts (%d) must divide over the %r axis "
                         "(%d)" % (num_experts, expert_axis, size))

    def place(leaf):
        if leaf.ndim >= 2 and leaf.shape[0] == num_experts:
            return jax.device_put(leaf,
                                  NamedSharding(mesh, P(expert_axis)))
        return jax.device_put(leaf, NamedSharding(mesh, P()))

    return jax.tree_util.tree_map(place, params)
