"""``shard_map`` version compatibility.

``jax.shard_map`` is top-level API (with the ``check_vma`` kwarg) only on
newer jax; on the 0.4.x line it lives at
``jax.experimental.shard_map.shard_map`` with the same semantics under the
``check_rep`` kwarg. The parallel layer (ring attention, pipeline) calls
through this one resolver so the whole test tier runs on either.
"""
from __future__ import annotations

import jax

__all__ = ["shard_map"]


def shard_map(fn, mesh, in_specs, out_specs, check_vma=False):
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as esm
    return esm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)
