"""Pipeline parallelism: homogeneous layer stacks over a ``pipe`` mesh axis.

The reference has NO pipeline parallelism (SURVEY §2.3 "Parallelism NOT
present"); this is a TPU-native addition in the shape the hardware wants —
the scaling-book recipe: each pipeline stage owns an equal slice of a
stacked layer pytree (sharded on the leading axis over the ``pipe`` mesh
axis), microbatches stream through a ``lax.scan`` of compute+``ppermute``
ticks inside ``shard_map``, and jax autodiff differentiates straight
through the collective permutes, so one ``jax.grad`` gives the correct
pipelined backward (reverse permutes in reverse order).

Schedule: GPipe fill-drain. For S stages and M microbatches the loop runs
S-1+M ticks; bubble fraction (S-1)/(S-1+M) — choose M >= 4S for >80%
utilization. Activation memory per device is one microbatch (the scan
carries only the in-flight activation; jax rematerializes for backward).

Usage::

    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *per_layer_params)
    out = pipeline_apply(layer_fn, stacked, x, mesh, axis="pipe",
                         num_microbatches=8)

``layer_fn(params_i, x) -> y`` must be shape-preserving (x and y alike),
the natural shape for transformer blocks. ``stacked`` leaves carry the
layer axis first; its size must equal the ``pipe`` axis size times layers
per stage (layers within a stage run as an inner scan).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..base import MXNetError

__all__ = ["pipeline_apply"]


def pipeline_apply(layer_fn, stacked_params, x, mesh, axis="pipe",
                   num_microbatches=None, batch_axis=None):
    """Apply a stacked layer sequence, pipelined over ``axis``.

    Parameters
    ----------
    layer_fn : ``(params_i, x) -> y`` with ``y.shape == x.shape``.
    stacked_params : pytree whose leaves have a leading layer axis of size
        ``n_layers`` (a multiple of the pipe-axis size).
    x : the full batch; dim 0 is split into microbatches.
    mesh : jax.sharding.Mesh containing ``axis``.
    num_microbatches : how many microbatches to stream (default: pipe size).
    batch_axis : optional mesh axis name to ALSO shard each microbatch's
        dim 0 over (combine dp x pp).

    Returns the output of the full layer stack for the full batch, ordered
    like ``x``.
    """
    from .shmap import shard_map

    n_stages = mesh.shape[axis]
    n_layers = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if n_layers % n_stages:
        raise MXNetError("n_layers (%d) must divide over the %r axis (%d)"
                         % (n_layers, axis, n_stages))
    m = n_stages if num_microbatches is None else int(num_microbatches)
    if m < 1:
        raise MXNetError("num_microbatches must be >= 1, got %d" % m)
    if x.shape[0] % m:
        raise MXNetError("batch %d not divisible into %d microbatches"
                         % (x.shape[0], m))
    mb = x.shape[0] // m

    # leading layer axis sharded over pipe; microbatch stream replicated on
    # the pipe axis (each stage sees every tick), optionally dp-sharded
    param_spec = jax.tree_util.tree_map(
        lambda _: P(axis), stacked_params)
    xs_spec = P(None, batch_axis)  # (m, mb, ...)
    out_spec = P(None, batch_axis)

    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def stage_body(params_local, xs):
        # params_local: (layers_per_stage, ...) slice; xs: (m, mb, ...)
        idx = lax.axis_index(axis)

        def apply_stage(x_in):
            def one_layer(h, p_i):
                return layer_fn(p_i, h), None
            h, _ = lax.scan(one_layer, x_in, params_local)
            return h

        zero = jnp.zeros_like(xs[0])
        t_total = m + n_stages - 1

        def tick(carry, t):
            state = carry  # activation received from the left neighbor
            feed = xs[jnp.minimum(t, m - 1)]
            x_in = jnp.where(idx == 0,
                             jnp.where(t < m, feed, zero), state)
            y = apply_stage(x_in)
            state_next = lax.ppermute(y, axis, perm)
            # only the LAST stage's y is a finished microbatch; psum makes
            # it visible on every device so the gathered output is replicated
            # over the pipe axis (cheap at test scale; a production variant
            # would keep outputs stage-local)
            out = lax.psum(jnp.where(idx == n_stages - 1, y, zero), axis)
            return state_next, out

        _, outs = lax.scan(tick, zero, jnp.arange(t_total))
        # last stage finishes microbatch j at tick j + n_stages - 1
        return outs[n_stages - 1:]

    shmapped = shard_map(
        stage_body, mesh=mesh,
        in_specs=(param_spec, xs_spec),
        out_specs=out_spec,
        check_vma=False)

    xs = x.reshape((m, mb) + x.shape[1:])
    outs = shmapped(stacked_params, xs)  # (m, mb, ...)
    return outs.reshape(x.shape)
