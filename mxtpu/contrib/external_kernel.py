"""External-kernel hook: run externally-built kernels as first-class ops.

Reference analog: the TVM bridge (src/nnvm/tvm_bridge.cc:54-178), which
wraps TVM-compiled PackedFuncs as engine-scheduled async ops — external
compute participating in MXNet's dependency graph with correct read/write
vars and stream handoff.

TPU-native re-design: the "engine" is XLA's program, so an external kernel
joins the graph by being jax-traceable. Two classes cover the TVM bridge's
use cases:

* **device kernels** — anything jax-traceable (a Pallas ``pallas_call``,
  an ``lax`` composition, a ``jax.ffi`` custom call): registering it makes
  it a registry op, so it works through ``mx.nd.*``, NDArray autograd,
  ``mx.sym`` composition, and ``hybridize`` (it inlines into the jitted
  program the way TVM funcs joined the engine's graph).
* **host kernels** — a numpy/cffi/ctypes function runs inside the compiled
  program via ``jax.pure_callback`` (the async-dispatch handoff the bridge
  did with stream synchronization); gradients come from an optional user
  ``vjp``.

Unlike the reference's bridge (forward-only PackedFuncs), a registered
kernel may declare a gradient, making it usable in training graphs.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..ops.registry import REGISTRY, register

__all__ = ["register_external_kernel", "register_host_kernel"]


def _attach_vjp(fn, vjp):
    """Bind attrs BEFORE the custom_vjp boundary: jax.custom_vjp rejects
    keyword arguments that cannot resolve to positions, so the
    differentiable inner function must close over them."""

    def kernel(*arrays, **attrs):
        @jax.custom_vjp
        def inner(*arrs):
            return fn(*arrs, **attrs)

        def fwd(*arrs):
            return fn(*arrs, **attrs), arrs

        def bwd(res, g):
            grads = vjp(g, *res, **attrs)
            if not isinstance(grads, (list, tuple)):
                grads = (grads,)
            if len(grads) != len(res):
                raise MXNetError(
                    "external kernel vjp returned %d gradients for %d "
                    "inputs" % (len(grads), len(res)))
            return tuple(grads)

        inner.defvjp(fwd, bwd)
        return inner(*arrays)

    return kernel


def register_external_kernel(name, fn, vjp=None, aliases=()):
    """Register a jax-traceable kernel as a framework op.

    ``fn(*arrays, **attrs)`` must be traceable (Pallas kernels, lax/jnp
    compositions, ``jax.ffi`` custom calls). ``vjp(cotangent, *arrays,
    **attrs) -> grads`` supplies the gradient; without it the op is a
    non-differentiable leaf unless jax can differentiate ``fn`` itself.

    Returns the NDArray-level callable (also reachable as ``mx.nd.<name>``
    and via ``mx.sym.<name>`` composition).
    """
    for nm in (name,) + tuple(aliases):
        if nm in REGISTRY:
            raise MXNetError("op name %r is already registered" % nm)
    kernel = fn if vjp is None else _attach_vjp(fn, vjp)
    kernel = functools.wraps(fn)(kernel) if hasattr(fn, "__name__") else kernel
    return register(name, aliases=aliases)(kernel)


def register_host_kernel(name, fn, out_shape_fn=None, vjp=None, aliases=()):
    """Register a HOST function (numpy/cffi/ctypes) as a framework op.

    The function runs on the host inside the compiled program via
    ``jax.pure_callback`` — the modern form of the bridge's async handoff
    (XLA inserts the device<->host transfers and sequencing that
    ``fset_stream`` managed manually). ``out_shape_fn(*shaped_inputs,
    **attrs)`` returns a ShapeDtypeStruct (default: same shape/dtype as
    the first input). ``fn`` itself must be pure (pure_callback may cache,
    elide, or replay calls).
    """

    def device_side(*arrays, **attrs):
        if out_shape_fn is None:
            spec = jax.ShapeDtypeStruct(arrays[0].shape, arrays[0].dtype)
        else:
            spec = out_shape_fn(*[jax.ShapeDtypeStruct(a.shape, a.dtype)
                                  for a in arrays], **attrs)
        return jax.pure_callback(functools.partial(fn, **attrs), spec,
                                 *arrays, vmap_method="sequential")

    device_side.__name__ = name
    device_side.__doc__ = fn.__doc__
    return register_external_kernel(name, device_side, vjp=vjp,
                                    aliases=aliases)
