"""Sharded, optionally-async checkpointing over orbax.

The reference's checkpoint story is single-writer files (SURVEY §5:
"No async/sharded checkpoint" — mx.model.save_checkpoint and Gluon
save_parameters serialize the full value from one process). On TPU pods
that is the wrong shape twice over: parameters live sharded across the
mesh (gathering to one host can exceed host RAM), and synchronous writes
stall every chip for the IO. This module is the TPU-native upgrade:

* each process writes only the shards it owns (orbax OCDBT format),
* ``async_save`` returns as soon as device arrays are snapshotted —
  training continues while the write completes in the background,
* restore is sharding-aware: arrays come back distributed according to a
  target block/TrainStep without materializing the full value per host.

API mirrors the Gluon surface it augments::

    from mxtpu.contrib import async_checkpoint as ackpt
    mgr = ackpt.save_block(net, "/ckpt/dir", step=100, async_save=True)
    mgr.wait_until_finished()        # or let the next save barrier
    ackpt.load_block(net, "/ckpt/dir", step=100)

The reference file formats (save_checkpoint / export) remain available
for interchange; this is for large-scale training loops.
"""
from __future__ import annotations

import jax

from ..base import MXNetError

__all__ = ["save_block", "load_block", "save_train_step",
           "load_train_step", "save_trainer", "load_trainer",
           "load_trainer_fallback", "latest_step",
           "load_trainer_params_into_block", "CheckpointCorrupt"]


class CheckpointCorrupt(MXNetError):
    """A checkpoint step's restored bytes disagree with the checksum
    manifest written at save time (disk corruption, a torn write, or the
    injected fault kind ``ckpt_corrupt``). The step is tombstoned on
    raise, so every later ``latest_step`` scan / tiered restore skips it
    without re-reading the bytes."""


def _param_tree(block):
    params = list(block.collect_params().values())
    if any(p._data is None for p in params):
        raise MXNetError("initialize the block before checkpointing")
    tree = _keyed([p.data()._data for p in params])
    if not tree:
        raise MXNetError("block has no initialized parameters to checkpoint")
    return tree


_ASYNC_CKPTR = None  # ONE shared instance: orbax's save only barriers on
# previous saves of the SAME AsyncCheckpointer, so per-call instances would
# break the "next save waits" contract and leak background threads


def _mp_options():
    """Orbax multiprocessing options for this process.

    Orbax's save/restore barriers default to the psum-based
    ``sync_global_devices``, which XLA:CPU cannot run across processes
    at all ("Multiprocess computations aren't implemented on the CPU
    backend") — a multi-process fleet on the forced-CPU tier could
    never checkpoint. There every array is host-local anyway (the fleet
    runs per-host local meshes, coupled through the fleet board), so
    each process runs orbax in SINGLE-PROCESS mode: it is its own
    primary host, its barrier set is itself, and the sync-key prefix is
    rank-tagged so two processes touching the same step directory never
    collide on a coordination-service barrier key. The fleet tier's
    single-writer discipline (rank 0 saves, peers only restore behind
    the resume board barrier) is what makes this sound. Backends with
    global compute keep orbax's stock multi-host protocol."""
    import orbax.checkpoint as ocp

    from .. import distributed
    if jax.process_count() <= 1 or distributed.global_compute_supported():
        return {}
    return {"multiprocessing_options": ocp.options.MultiprocessingOptions(
        primary_host=jax.process_index(),
        active_processes={jax.process_index()},
        barrier_sync_key_prefix="mxtpu_host%d" % jax.process_index())}


def _serializable(tree):
    """Orbax refuses jax Arrays whose sharding spans only this host's
    devices while the runtime has more processes ("Cannot serialize host
    local arrays") — exactly what every array IS on the CPU fleet tier
    (per-host local meshes). Same tier as :func:`_mp_options`: fetch
    those leaves to host numpy, which orbax serializes without a global
    sharding story. Values are identical (the fleet tier replicates
    state host-to-host); single-process and global-compute backends
    return the tree untouched, keeping sharded zero-copy saves."""
    from .. import distributed
    if jax.process_count() <= 1 or distributed.global_compute_supported():
        return tree
    import numpy as np
    return jax.tree_util.tree_map(
        lambda x: np.asarray(x) if isinstance(x, jax.Array) else x, tree)


def _checkpointer(async_save):
    import orbax.checkpoint as ocp
    if async_save:
        global _ASYNC_CKPTR
        if _ASYNC_CKPTR is None:
            import atexit
            _ASYNC_CKPTR = ocp.AsyncCheckpointer(
                ocp.PyTreeCheckpointHandler(), **_mp_options())
            atexit.register(_ASYNC_CKPTR.close)  # drain pending writes
        # a background write that DIED must fail the next save loudly, not
        # rot silently in the async thread: re-raise its exception here
        # (wait_until_finished re-raises on its own)
        check = getattr(_ASYNC_CKPTR, "check_for_errors", None)
        if check is not None:
            check()
        return _ASYNC_CKPTR
    return ocp.Checkpointer(ocp.PyTreeCheckpointHandler(), **_mp_options())


def _guard_overwrite(step_dir, force):
    """Refuse to clobber a finalized checkpoint step unless ``force=True``
    — an interval save landing on a step that already exists is almost
    always a bookkeeping bug, and the old bytes may be the only good copy."""
    from etils import epath
    if not force and epath.Path(step_dir).exists():
        raise MXNetError(
            "checkpoint step directory %s already exists; pass force=True "
            "to overwrite it" % step_dir)


def _step_dir(directory, step):
    import os
    if "://" not in str(directory):  # URL-style (gs://, s3://) pass through
        directory = os.path.abspath(directory)
    return os.path.join(str(directory), "step_%d" % step)


def _meta_path(step_dir):
    # sidecar lives NEXT TO the orbax dir, not inside it: async saves
    # materialize the step dir atomically at finalize time, so a file
    # written inside it would race/vanish
    from etils import epath
    p = epath.Path(step_dir)
    return p.parent / (p.name + ".mxtpu_meta.json")


def _write_meta(step_dir, meta):
    import json
    if jax.process_index() != 0:  # one writer; returns 0 single-process
        return
    _meta_path(step_dir).write_text(json.dumps(meta))


def _read_meta(step_dir):
    import json
    from etils import epath
    p = _meta_path(step_dir)
    if not p.exists():
        return None
    if not epath.Path(step_dir).exists():
        # sidecar without a finalized orbax dir: either an async save died
        # mid-write (orphan) or one is still in flight — in both cases the
        # fingerprint must not be trusted yet. Tolerate, do NOT delete:
        # unlinking here would race an in-flight save and strip a valid
        # checkpoint of its fingerprint.
        return None
    return json.loads(p.read_text())


# -------------------------------------------------- integrity bookkeeping
def _crc_host(x):
    """crc32 of an array's host bytes — THE canonical blob checksum both
    sides of the manifest use (save computes it from the live value,
    restore from the staged restored value; dtype/shape ride the orbax
    tree, so bytes are the one thing left to pin)."""
    import zlib

    import numpy as np

    # fleet meshes make some arrays non-fully-addressable (ZeRO shards);
    # host_value allgathers those collectively — EVERY process runs this
    # same manifest walk, so the collective is symmetric by construction
    from ..parallel.mesh import host_value
    arr = host_value(x)
    return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF


def _tombstone_path(step_dir):
    from etils import epath
    p = epath.Path(step_dir)
    return p.parent / (p.name + ".corrupt.json")


def _is_tombstoned(step_dir):
    try:
        return _tombstone_path(step_dir).exists()
    except Exception:  # noqa: BLE001 — unreadable backend: assume clean
        return False


def _write_tombstone(step_dir, reason):
    """Mark a step known-corrupt (idempotent). The bytes stay on disk for
    forensics; every scan from now on skips the step without re-reading
    them, and the retention GC stops counting it as a keeper."""
    import json
    import time
    try:
        _tombstone_path(step_dir).write_text(
            json.dumps({"reason": str(reason), "t": time.time()}))
    except Exception:  # noqa: BLE001 — best effort: the raise still lands
        pass


def _clear_tombstone(step_dir):
    try:
        p = _tombstone_path(step_dir)
        if p.exists():
            p.unlink()
    except Exception:  # noqa: BLE001
        pass


def _finalized_steps(directory):
    """Ascending step indices with a FINALIZED (atomically materialized)
    orbax directory — tombstoned or not; callers filter."""
    from etils import epath
    d = epath.Path(str(directory))
    steps = []
    try:
        for p in d.iterdir():
            if p.name.startswith("step_") and p.is_dir():
                try:
                    steps.append(int(p.name[5:]))
                except ValueError:
                    pass
    except Exception:  # noqa: BLE001 — missing/unreadable directory
        return []
    return sorted(steps)


def _gc_steps(directory, keep):
    """Bounded checkpoint retention (``MXTPU_CKPT_KEEP``): delete
    finalized step dirs strictly OLDER than the newest ``keep`` intact
    (finalized, non-tombstoned) steps. Mid-write steps (sidecar without a
    finalized dir) are invisible here and tombstoned steps never count as
    keepers, so the newest restorable checkpoint survives even at
    ``keep=1`` with the latest save in flight or known-corrupt. With no
    provably-intact keeper at all, nothing is deleted. Returns the
    deleted step list."""
    if not keep or keep <= 0:
        return []
    if jax.process_index() != 0:  # one writer deletes; 0 single-process
        return []
    from etils import epath
    steps = _finalized_steps(directory)
    intact = [s for s in steps
              if not _is_tombstoned(_step_dir(directory, s))]
    keepers = intact[-int(keep):]
    if not keepers:
        return []
    floor = keepers[0]
    deleted = []
    for s in steps:
        if s >= floor:
            continue
        sd = _step_dir(directory, s)
        try:
            epath.Path(sd).rmtree()
        except Exception:  # noqa: BLE001 — a busy/garbled dir stays
            continue
        for side in (_meta_path(sd), _tombstone_path(sd)):
            try:
                if side.exists():
                    side.unlink()
            except Exception:  # noqa: BLE001
                pass
        deleted.append(s)
    if deleted:
        import logging
        logging.getLogger("mxtpu.resilience").info(
            "checkpoint GC: deleted steps %s (keep=%d, newest intact %s)",
            deleted, keep, keepers[-1])
    return deleted


def latest_step(directory):
    """Newest RESUMABLE step in a checkpoint directory (or None): the
    ``latest.json`` pointer if its step dir finalized (async orbax
    materializes step dirs atomically, so existence == durable) AND is
    not tombstoned as corrupt, else the newest finalized non-tombstoned
    ``step_*`` directory — the cheap tiers of the integrity story (full
    checksum verification runs inside the restore itself, see
    :func:`load_trainer_fallback`). Shared by
    :class:`mxtpu.resilience.ResilientLoop` (training resume) and
    :meth:`mxtpu.serving.Predictor.from_trainer_checkpoint` (serving
    restore); epath-routed so gs://-style directories resolve from a
    fresh host too."""
    import json

    from etils import epath
    d = epath.Path(str(directory))
    try:
        candidate = int(json.loads((d / "latest.json").read_text())["step"])
    except Exception:  # missing, torn, or backend error: fall back to scan
        candidate = None
    if candidate is not None and (d / ("step_%d" % candidate)).is_dir() \
            and not _is_tombstoned(_step_dir(directory, candidate)):
        return candidate
    steps = [s for s in _finalized_steps(directory)
             if not _is_tombstoned(_step_dir(directory, s))]
    return max(steps) if steps else None


def _keyed(datas):
    """THE positional-key scheme shared by every save/load here: gluon's
    global name counters differ between runs (dense0 vs dense2), so
    name-keyed trees would not match a freshly built model at restore."""
    return {"p%d" % j: d for j, d in enumerate(datas)}


def save_block(block, directory, step=0, async_save=False, force=False):
    """Write the block's parameters sharded-per-process; returns the
    checkpointer (call ``wait_until_finished()`` on async saves before
    relying on the files). Overwriting an existing step requires
    ``force=True``."""
    sd = _step_dir(directory, step)
    _guard_overwrite(sd, force)
    ckptr = _checkpointer(async_save)
    ckptr.save(sd, _param_tree(block), force=True)
    return ckptr


def load_block(block, directory, step=0):
    """Restore parameters in place, preserving each parameter's CURRENT
    sharding (restore is distributed: a host only reads its shards)."""
    import orbax.checkpoint as ocp
    params = list(block.collect_params().values())
    if any(p._data is None for p in params):
        # positional keys only align when BOTH sides enumerate every param
        raise MXNetError("initialize the block (and settle deferred shapes) "
                         "before load_block")
    targets = _keyed([jax.ShapeDtypeStruct(p.data()._data.shape,
                                           p.data()._data.dtype,
                                           sharding=p.data()._data.sharding)
                      for p in params])
    ckptr = _checkpointer(async_save=False)
    restored = ckptr.restore(
        _step_dir(directory, step),
        args=ocp.args.PyTreeRestore(
            restore_args=jax.tree_util.tree_map(
                lambda t: ocp.ArrayRestoreArgs(sharding=t.sharding,
                                               global_shape=t.shape),
                targets),
            item=targets))
    for j, p in enumerate(params):
        p.data()._set_data(restored["p%d" % j])
    return block


def _state_leaves(st):
    """Flat leaves of one param's optimizer state. States live in the rule
    registry's structure — None | array | tuple nest (ShardedTrainStep
    shares mxtpu.optimizer_fused's update rules) — and the on-disk layout
    keys them positionally (``p<j>__<i>``), which enumerates identically
    for the old always-a-tuple layout, so pre-ISSUE-7 checkpoints restore
    unchanged."""
    return jax.tree_util.tree_leaves(st)


def save_train_step(train_step, directory, step=0, async_save=False,
                    force=False):
    """Checkpoint a ShardedTrainStep: parameters AND optimizer state, each
    written with its live sharding (ZeRO-1 state stays sharded on disk).
    Overwriting an existing step requires ``force=True``."""
    tree = {
        "params": _keyed(train_step._param_datas),
        "opt": {("p%d__%d" % (j, i)): s
                for j, st in enumerate(train_step._opt_states)
                for i, s in enumerate(_state_leaves(st))},
        "meta": {"num_update": train_step._num_update},
    }
    _guard_overwrite(_step_dir(directory, step), force)
    ckptr = _checkpointer(async_save)
    ckptr.save(_step_dir(directory, step), tree, force=True)
    # state-structure fingerprint as a sidecar (read BEFORE restore so a
    # mismatched trainer gets a clear refusal, not an orbax tree error).
    # For async saves the orbax dir may not exist yet when this is written;
    # _read_meta treats a sidecar whose step dir is absent as an orphan
    # (deleted on read), so a crashed background write cannot leave a
    # misleading fingerprint behind.
    _write_meta(_step_dir(directory, step),
                {"state_counts": [len(_state_leaves(st))
                                  for st in train_step._opt_states]})
    return ckptr


def load_train_step(train_step, directory, step=0):
    """Restore a ShardedTrainStep in place with live shardings."""
    import orbax.checkpoint as ocp

    def _target(d):
        return jax.ShapeDtypeStruct(d.shape, d.dtype, sharding=d.sharding)

    live_counts = [len(_state_leaves(st)) for st in train_step._opt_states]
    meta = _read_meta(_step_dir(directory, step))
    if meta is not None and meta.get("state_counts") != live_counts:
        raise MXNetError(
            "optimizer state structure mismatch: checkpoint has %s states "
            "per param, this trainer expects %s — momentum/optimizer "
            "settings must match the run that saved (silently dropping "
            "state would fork the trajectory)"
            % (meta.get("state_counts"), live_counts))
    targets = {
        "params": _keyed([_target(d) for d in train_step._param_datas]),
        "opt": {("p%d__%d" % (j, i)): _target(s)
                for j, st in enumerate(train_step._opt_states)
                for i, s in enumerate(_state_leaves(st))},
        "meta": {"num_update": 0},
    }
    def _ra(t):
        return ocp.ArrayRestoreArgs(sharding=t.sharding,
                                    global_shape=t.shape)

    restore_args = {
        "params": {k: _ra(t) for k, t in targets["params"].items()},
        "opt": {k: _ra(t) for k, t in targets["opt"].items()},
        "meta": {"num_update": ocp.RestoreArgs()},
    }
    ckptr = _checkpointer(async_save=False)
    restored = ckptr.restore(
        _step_dir(directory, step),
        args=ocp.args.PyTreeRestore(restore_args=restore_args,
                                    item=targets))
    new_datas = [restored["params"]["p%d" % j]
                 for j in range(len(train_step._params))]
    train_step._param_datas = new_datas
    for p, d in zip(train_step._params, new_datas):
        p.data()._set_data(d)
    # rebuild each state in its live structure from the flat leaves
    train_step._opt_states = [
        jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(st),
            [restored["opt"]["p%d__%d" % (j, i)]
             for i in range(len(_state_leaves(st)))])
        for j, st in enumerate(train_step._opt_states)]
    train_step._num_update = int(restored["meta"]["num_update"])
    return train_step


# ----------------------------------------------------------- gluon Trainer
def _trainer_updater(trainer):
    if not trainer._kv_initialized:
        trainer._init_kvstore()
    if trainer._update_on_kvstore:
        return trainer._kvstore._updater
    return trainer._updaters[0]


def save_trainer(trainer, directory, step=0, async_save=False, force=False):
    """Checkpoint a gluon Trainer: parameters (sharded orbax arrays) + the
    full updater/optimizer state blob (update counts, momentum/Adam state,
    and — through FusedUpdater.get_states — the loss-scaler scale/streak
    and the numerics guard's device step count) + the global RNG key.
    Everything :class:`mxtpu.resilience.ResilientLoop` needs for bit-exact
    resume, in one orbax step directory (finalized atomically, so a
    present ``step_N`` dir is always durable).

    Integrity (ISSUE 14): the sidecar meta carries a per-blob crc32
    manifest (every param, the updater blob, the RNG key) computed from
    the live values at save time; restore verifies the staged bytes
    against it BEFORE committing anything (:func:`_restore_trainer_tree`)
    and falls back a tier on mismatch. With ``MXTPU_CKPT_KEEP`` > 0,
    finalized steps older than the newest N intact ones are
    garbage-collected after the save dispatch (:func:`_gc_steps` — an
    in-flight async step and tombstoned steps never count as keepers).
    Fault kind ``ckpt_corrupt`` flips the saved updater blob's bytes
    AFTER the manifest is computed, so the verification/fallback tiers
    are exercised end-to-end."""
    import time

    import numpy as np

    from .. import random as _random
    from .. import telemetry
    from ..resilience import ckpt_keep, inject
    if inject("ckpt_io"):
        raise OSError("injected checkpoint IO failure (MXTPU_FAULT_INJECT)")
    upd = _trainer_updater(trainer)
    params = [p for p in trainer._params if p._data is not None]
    if not params:
        raise MXNetError("initialize the parameters before checkpointing")
    t0 = time.perf_counter()
    blob = np.frombuffer(upd.get_states(dump_optimizer=True),
                         np.uint8).copy()
    rng_data = np.asarray(_random.get_key_data())
    # per-blob checksum manifest: the save-time truth every restore tier
    # verifies against (one host fetch per param, at checkpoint cadence —
    # the save itself is already moving those bytes)
    crc = {"p%d" % j: _crc_host(p.data()._data)
           for j, p in enumerate(params)}
    crc["updater"] = _crc_host(blob)
    crc["rng"] = _crc_host(rng_data)
    if inject("ckpt_corrupt"):
        # flip bytes AFTER the manifest: the saved blob now disagrees
        # with its checksum exactly like real on-disk corruption would
        blob = blob.copy()
        blob[:1] ^= 0xFF
    tree = {
        "params": _keyed([p.data()._data for p in params]),
        "extra": {"updater": blob, "rng": rng_data},
    }
    sd = _step_dir(directory, step)
    _guard_overwrite(sd, force)
    ckptr = _checkpointer(async_save)
    ckptr.save(sd, _serializable(tree), force=True)
    # a force re-save over a known-corrupt step IS a fresh checkpoint:
    # drop the tombstone so the new bytes are restorable again
    _clear_tombstone(sd)
    _write_meta(sd, {"kind": "trainer", "n_params": len(params),
                     "crc": crc})
    # save latency into the registry: for async saves this is the
    # serialize+dispatch cost training actually pays; the background
    # write's durability cost shows up in wait_until_finished callers
    telemetry.observe("checkpoint.save_s", time.perf_counter() - t0)
    telemetry.inc("checkpoint.saves")
    _gc_steps(directory, ckpt_keep())
    return ckptr


def _check_trainer_meta(sd, params, who):
    meta = _read_meta(sd)
    if meta is not None and meta.get("n_params") not in (None, len(params)):
        raise MXNetError(
            "trainer checkpoint at %s holds %s parameters, this %s has %d "
            "— the model that saved must match the one restoring "
            "(positional keys)" % (sd, meta.get("n_params"), who,
                                   len(params)))


def _verify_restored(sd, params, restored):
    """Check every restored blob against the save-time crc manifest (a
    checkpoint without one — pre-ISSUE-14 — verifies vacuously). On a
    mismatch the step is tombstoned and :class:`CheckpointCorrupt`
    raises BEFORE anything was committed, naming the bad blobs; fault
    kind ``ckpt_corrupt`` lands here via the blob bytes
    :func:`save_trainer` flipped after manifesting."""
    import numpy as np
    meta = _read_meta(sd)
    crc = (meta or {}).get("crc")
    if not crc:
        return
    bad = []
    for j in range(len(params)):
        k = "p%d" % j
        if k in crc and _crc_host(restored["params"][k]) != crc[k]:
            bad.append(k)
    if "updater" in crc and _crc_host(
            np.asarray(restored["extra"]["updater"])) != crc["updater"]:
        bad.append("updater")
    if "rng" in crc and _crc_host(
            np.asarray(restored["extra"]["rng"])) != crc["rng"]:
        bad.append("rng")
    if bad:
        _write_tombstone(sd, "checksum mismatch: %s" % ",".join(bad))
        raise CheckpointCorrupt(
            "checkpoint %s failed integrity verification: restored bytes "
            "of %s disagree with the save-time checksum manifest (disk "
            "corruption or a torn write); the step is tombstoned — "
            "restore falls back to the next-newest intact step"
            % (sd, ", ".join(bad)))


def _restore_trainer_tree(params, sd, verify=True):
    """The restore core shared by :func:`load_trainer` (training resume)
    and :func:`load_trainer_params_into_block` (serving restore): read a
    :func:`save_trainer` step into a STAGED tree, verify it against the
    checksum manifest, and only then write the params back in place with
    their live shardings — a corrupt step must never half-overwrite a
    live trainer. Returns the full restored tree (the ``extra``
    updater/RNG blobs ride along for the caller that wants them)."""
    import orbax.checkpoint as ocp

    def _target(p):
        d = p.data()._data
        return jax.ShapeDtypeStruct(d.shape, d.dtype, sharding=d.sharding)

    targets = {"params": _keyed([_target(p) for p in params]),
               "extra": {"updater": 0, "rng": 0}}
    restore_args = {
        "params": {k: ocp.ArrayRestoreArgs(sharding=t.sharding,
                                           global_shape=t.shape)
                   for k, t in targets["params"].items()},
        "extra": {"updater": ocp.RestoreArgs(), "rng": ocp.RestoreArgs()},
    }
    ckptr = _checkpointer(async_save=False)
    restored = ckptr.restore(
        sd, args=ocp.args.PyTreeRestore(restore_args=restore_args,
                                        item=targets))
    if verify:
        _verify_restored(sd, params, restored)
    for j, p in enumerate(params):
        p.data()._set_data(restored["params"]["p%d" % j])
    return restored


def load_trainer(trainer, directory, step=0):
    """Restore a gluon Trainer in place from :func:`save_trainer` output —
    params with their live shardings, optimizer + loss-scaler + guard
    state, and the RNG key (bit-exact resume)."""
    import numpy as np

    from .. import random as _random
    upd = _trainer_updater(trainer)
    params = [p for p in trainer._params if p._data is not None]
    sd = _step_dir(directory, step)
    _check_trainer_meta(sd, params, "trainer")
    restored = _restore_trainer_tree(params, sd)
    upd.set_states(np.asarray(restored["extra"]["updater"],
                              np.uint8).tobytes())
    # the blob carried the pickled optimizer (counts, schedules, Nadam's
    # m_schedule): rebind the live trainer to it, exactly like load_states
    trainer._optimizer = upd.optimizer
    trainer._optimizer.param_dict = {
        i: p for i, p in enumerate(trainer._params)}
    for u in trainer._updaters:
        u.optimizer = trainer._optimizer
    upd_scaler = getattr(upd, "scaler", None)
    if trainer._loss_scaler is not None and upd_scaler is not None \
            and upd_scaler is not trainer._loss_scaler:
        trainer._loss_scaler.load_state_dict(upd_scaler.state_dict())
        upd.scaler = trainer._loss_scaler
    # re-place the restored state on the trainer's MeshPlan NOW that
    # param_dict is rebound: set_states ran its placement pass against
    # the blob's stripped param_dict, so ZeRO eligibility (which needs
    # the weight's dim 0) could not be decided there
    replace = getattr(upd, "_replace_states_on_plan", None)
    if replace is not None:
        replace()
    _random.set_key_data(np.asarray(restored["extra"]["rng"]))
    return trainer


def load_trainer_fallback(trainer, directory, logger=None):
    """Tiered trainer restore: try finalized, non-tombstoned steps newest
    first; a step that fails integrity verification
    (:class:`CheckpointCorrupt` — tombstoned by the verifier) or errors
    during restore falls back one tier, counted in
    ``checkpoint.restore_fallbacks{reason}``. Returns the step restored
    from, or None when the directory holds nothing restorable (fresh
    start). Structure mismatches (param count / optimizer state shape)
    still raise — that is a configuration error resuming older bytes
    would only hide."""
    import logging

    from .. import telemetry
    log = logger or logging.getLogger("mxtpu.resilience")
    steps = [s for s in _finalized_steps(directory)
             if not _is_tombstoned(_step_dir(directory, s))]
    for step in reversed(steps):
        try:
            load_trainer(trainer, directory, step=step)
            return step
        except CheckpointCorrupt as e:
            telemetry.inc("checkpoint.restore_fallbacks", tag="checksum")
            log.warning(
                "checkpoint step %d failed integrity verification; "
                "falling back one tier (%s)", step, e)
        except MXNetError:
            raise  # structure mismatch: a config error, not corruption
        except Exception as e:  # noqa: BLE001 — garbled step dir
            telemetry.inc("checkpoint.restore_fallbacks", tag="error")
            log.warning(
                "checkpoint step %d failed to restore (%s: %s); falling "
                "back one tier", step, type(e).__name__, e)
    return None


def load_trainer_params_into_block(block, directory, step=None):
    """Restore ONLY the parameter subtree of a :func:`save_trainer`
    checkpoint into ``block`` — the serving restore path: a training run
    promotes straight to a :class:`mxtpu.serving.Predictor` with no
    format hop, and the optimizer/updater blob + RNG key stay on disk
    (inference has no use for them, and overwriting the process RNG
    under a live server would be hostile).

    ``step=None`` resolves the newest finalized step via
    :func:`latest_step`. The block must enumerate the SAME parameters in
    the same order as the trainer that saved (positional keys — the
    usual case: ``Trainer(net.collect_params(), ...)`` on this net's
    architecture); the sidecar's ``n_params`` fingerprint is checked
    before the restore so a mismatch refuses loudly."""
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise MXNetError("no finalized checkpoint step under %s"
                             % directory)
    params = list(block.collect_params().values())
    if not params or any(p._data is None for p in params):
        raise MXNetError(
            "initialize the block (and settle deferred shapes with one "
            "forward) before load_trainer_params_into_block — positional "
            "keys only align when both sides enumerate every parameter")
    sd = _step_dir(directory, step)
    _check_trainer_meta(sd, params, "block")
    # the restored "extra" (updater blob, RNG key) is deliberately dropped
    _restore_trainer_tree(params, sd)
    return step
