"""ONNX export for mxtpu symbols / gluon blocks.

Reference: python/mxnet/contrib/onnx/mx2onnx/export_model.py +
_op_translations.py — per-op converters from the symbol graph to ONNX
nodes. Covers the model-zoo op surface (Conv, BatchNorm, Activation,
Pooling, Add, FullyConnected/Gemm, Flatten, Clip, Concat, Dropout,
LayerNorm, softmax); export is inference-mode (BatchNorm = moving stats),
matching the reference's deploy export.
"""
from __future__ import annotations

import numpy as np

from ...base import MXNetError
from ...ops.nn import _pair as _pairify  # one kernel/stride/pad normalizer
from ...symbol.symbol import _ARG, _topo
from . import proto

__all__ = ["export_model", "export_symbol"]


class _Ctx:
    def __init__(self):
        self.nodes = []
        self.initializers = []
        self.counter = 0

    def emit(self, op_type, inputs, outputs, name="", attrs=None):
        self.nodes.append(proto.node(op_type, inputs, outputs, name=name,
                                     attrs=attrs))

    def const(self, value, hint="const"):
        name = "%s_%d" % (hint, self.counter)
        self.counter += 1
        self.initializers.append(proto.tensor(name, np.asarray(value)))
        return name


def _conv(ctx, n, ins, outs, params):
    attrs = n.attrs
    if (attrs.get("layout") or "NCHW") not in ("NCHW", None):
        raise MXNetError("ONNX export requires NCHW convs; re-trace the "
                        "model outside a channels-last layout scope")
    kernel = _pairify(attrs.get("kernel"))
    stride = _pairify(attrs.get("stride"))
    dilate = _pairify(attrs.get("dilate"))
    pad = _pairify(attrs.get("pad") or 0)
    a = {"kernel_shape": list(kernel), "strides": list(stride),
         "dilations": list(dilate),
         "pads": list(pad) + list(pad),
         "group": int(attrs.get("num_group", 1))}
    ctx.emit("Conv", ins, outs, name=n.name, attrs=a)


def _batchnorm(ctx, n, ins, outs, params):
    # inference semantics: Y = gamma*(x-mean)/sqrt(var+eps)+beta
    x, gamma, beta, mean, var = ins
    if n.attrs.get("fix_gamma", True):
        g = params.get(gamma)
        ones = np.ones(g.shape if g is not None else
                       params[beta].shape, np.float32)
        gamma = ctx.const(ones, "fixed_gamma")
    ctx.emit("BatchNormalization", [x, gamma, beta, mean, var], outs,
             name=n.name,
             attrs={"epsilon": float(n.attrs.get("eps", 1e-3)),
                    "momentum": float(n.attrs.get("momentum", 0.9))})


_ACT = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
        "softrelu": "Softplus", "softsign": "Softsign"}


def _activation(ctx, n, ins, outs, params):
    act = n.attrs.get("act_type", "relu")
    if act not in _ACT:
        raise MXNetError("ONNX export: unsupported act_type %r" % act)
    ctx.emit(_ACT[act], ins, outs, name=n.name)


def _pooling(ctx, n, ins, outs, params):
    attrs = n.attrs
    ptype = attrs.get("pool_type", "max")
    if attrs.get("global_pool", False):
        op = {"max": "GlobalMaxPool", "avg": "GlobalAveragePool"}.get(ptype)
        if op is None:
            raise MXNetError("ONNX export: global %s pool" % ptype)
        ctx.emit(op, ins, outs, name=n.name)
        return
    kernel = _pairify(attrs.get("kernel"))
    stride = _pairify(attrs.get("stride") or 1)
    pad = _pairify(attrs.get("pad") or 0)
    a = {"kernel_shape": list(kernel), "strides": list(stride),
         "pads": list(pad) + list(pad)}
    if attrs.get("pooling_convention", "valid") == "full":
        a["ceil_mode"] = 1
    if ptype == "avg":
        a["count_include_pad"] = int(attrs.get("count_include_pad", True))
        ctx.emit("AveragePool", ins, outs, name=n.name, attrs=a)
    elif ptype == "max":
        ctx.emit("MaxPool", ins, outs, name=n.name, attrs=a)
    else:
        raise MXNetError("ONNX export: pool_type %r" % ptype)


def _fully_connected(ctx, n, ins, outs, params):
    data = ins[0]
    if n.attrs.get("flatten", True):
        flat = outs[0] + "_flat"
        ctx.emit("Flatten", [data], [flat], attrs={"axis": 1})
        data = flat
    gemm_ins = [data, ins[1]]
    if len(ins) > 2 and not n.attrs.get("no_bias", False):
        gemm_ins.append(ins[2])
    ctx.emit("Gemm", gemm_ins, outs, name=n.name,
             attrs={"alpha": 1.0, "beta": 1.0, "transB": 1})


def _clip(ctx, n, ins, outs, params):
    lo = ctx.const(np.float32(n.attrs.get("a_min", 0.0)), "clip_min")
    hi = ctx.const(np.float32(n.attrs.get("a_max", 0.0)), "clip_max")
    ctx.emit("Clip", [ins[0], lo, hi], outs, name=n.name)


def _softmax(ctx, n, ins, outs, params):
    ctx.emit("Softmax", ins, outs, name=n.name,
             attrs={"axis": int(n.attrs.get("axis", -1))})


def _concat(ctx, n, ins, outs, params):
    axis = int(n.attrs.get("dim", n.attrs.get("axis", 1)))
    ctx.emit("Concat", ins, outs, name=n.name, attrs={"axis": axis})


_CONVERTERS = {
    "Convolution": _conv,
    "BatchNorm": _batchnorm,
    "Activation": _activation,
    "Pooling": _pooling,
    "FullyConnected": _fully_connected,
    "clip": _clip,
    "softmax": _softmax,
    "Concat": _concat,
    "broadcast_add": lambda ctx, n, ins, outs, p:
        ctx.emit("Add", ins, outs, name=n.name),
    "broadcast_mul": lambda ctx, n, ins, outs, p:
        ctx.emit("Mul", ins, outs, name=n.name),
    "elemwise_sum": lambda ctx, n, ins, outs, p:
        ctx.emit("Sum", ins, outs, name=n.name),
    "Flatten": lambda ctx, n, ins, outs, p:
        ctx.emit("Flatten", ins, outs, name=n.name, attrs={"axis": 1}),
    "flatten": lambda ctx, n, ins, outs, p:
        ctx.emit("Flatten", ins, outs, name=n.name, attrs={"axis": 1}),
    "relu": lambda ctx, n, ins, outs, p:
        ctx.emit("Relu", ins, outs, name=n.name),
    "Dropout": lambda ctx, n, ins, outs, p:
        ctx.emit("Identity", ins, outs, name=n.name),
    "identity": lambda ctx, n, ins, outs, p:
        ctx.emit("Identity", ins, outs, name=n.name),
}


def export_symbol(sym, params, input_shapes, path=None):
    """Serialize a Symbol + params dict to ONNX ModelProto bytes.

    params: name -> NDArray/np array for every non-data variable.
    input_shapes: {input_name: shape} for the data inputs.
    """
    nodes = _topo(sym._heads)
    ctx = _Ctx()
    np_params = {}
    for k, v in params.items():
        np_params[k] = v.asnumpy() if hasattr(v, "asnumpy") else \
            np.asarray(v)

    names = {}  # (id(node), out_idx) -> onnx tensor name
    graph_inputs = []
    for n in nodes:
        if n.is_var():
            names[(id(n), 0)] = n.name
            if n.name in np_params:
                ctx.initializers.append(
                    proto.tensor(n.name, np_params[n.name]))
            elif n.name in input_shapes:
                graph_inputs.append(
                    proto.value_info(n.name, input_shapes[n.name]))
            else:
                raise MXNetError(
                    "export: variable %r has neither a parameter value nor "
                    "an input shape" % n.name)
            continue
        conv = _CONVERTERS.get(n.op)
        if conv is None:
            raise MXNetError(
                "ONNX export: no converter for op %r (supported: %s)"
                % (n.op, sorted(_CONVERTERS)))
        arrays = [names[(id(inp), idx)] for inp, idx in n.inputs]
        it = iter(arrays)
        ins = [next(it) for a in n.pos_template if a is _ARG]
        ins += [next(it) for _ in n.kw_arrays]
        outs = ["%s_out%d" % (n.name, i) for i in range(n.num_outputs)]
        for i in range(n.num_outputs):
            names[(id(n), i)] = outs[i]
        conv(ctx, n, ins, outs, np_params)

    out_infos = []
    graph_outputs = []
    for node_, idx in sym._heads:
        i = 0 if idx is None else idx
        nm = names[(id(node_), i)]
        out_infos.append(nm)
        # output shapes via infer_shape when derivable
    _, out_shapes, _ = sym.infer_shape(**input_shapes)
    for nm, shp in zip(out_infos, out_shapes):
        graph_outputs.append(proto.value_info(nm, shp or ()))

    g = proto.graph(ctx.nodes, "mxtpu_graph", ctx.initializers,
                    graph_inputs, graph_outputs)
    blob = proto.model(g)
    if path:
        with open(path, "wb") as f:
            f.write(blob)
    return blob


def export_model(block, path=None, input_shapes=None):
    """Export a (run-once) gluon HybridBlock to ONNX
    (ref: mx.contrib.onnx.export_model)."""
    from ...symbol.symbol import trace_block

    sym, _ = trace_block(block)
    params = {}
    for name, p in block.collect_params().items():
        params[name] = p.data()
    if input_shapes is None:
        specs = getattr(block, "_in_specs", None)
        if not specs:
            raise MXNetError("run the block once or pass input_shapes")
        data_names = [n for n in sym.list_inputs() if n not in params]
        input_shapes = {nm: tuple(s)
                        for nm, (s, _d) in zip(data_names, specs)}
    return export_symbol(sym, params, input_shapes, path=path)
