"""ONNX interchange (ref: python/mxnet/contrib/onnx/ — mx2onnx export +
onnx2mx import). Self-contained wire-format codec: no ``onnx`` package
needed to produce or consume valid ModelProto files; when the real ``onnx``
package IS available, tests additionally run onnx.checker over our bytes.
"""
from .export import export_model, export_symbol  # noqa: F401
from .import_model import import_model, import_model_bytes  # noqa: F401

__all__ = ["export_model", "export_symbol", "import_model",
           "import_model_bytes"]
