"""Minimal protobuf wire-format codec for the ONNX subset we emit/read.

The image ships no ``onnx`` package (and none may be installed), so the
exporter writes ModelProto bytes directly. Field numbers follow onnx.proto
(ONNX IR). The decoder is a generic wire-format parser (returns nested
{field_number: [values]} dicts), so export bugs can't be masked by a
mirrored reader.

Reference counterpart: python/mxnet/contrib/onnx/mx2onnx/ builds protos via
the onnx python package; the wire format here is identical.
"""
from __future__ import annotations

import struct

# ------------------------------------------------------------------ encode


def _varint(n: int) -> bytes:
    out = bytearray()
    n &= (1 << 64) - 1
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def field_varint(num: int, value: int) -> bytes:
    return _varint(num << 3 | 0) + _varint(int(value))


def field_bytes(num: int, payload: bytes) -> bytes:
    return _varint(num << 3 | 2) + _varint(len(payload)) + payload


def field_string(num: int, s: str) -> bytes:
    return field_bytes(num, s.encode("utf-8"))


def field_float(num: int, value: float) -> bytes:
    return _varint(num << 3 | 5) + struct.pack("<f", float(value))


def packed_int64s(num: int, values) -> bytes:
    payload = b"".join(_varint(int(v)) for v in values)
    return field_bytes(num, payload)


# ONNX enums
TENSOR_FLOAT = 1
ATTR_FLOAT = 1
ATTR_INT = 2
ATTR_STRING = 3
ATTR_TENSOR = 4
ATTR_FLOATS = 6
ATTR_INTS = 7


def attribute(name, value) -> bytes:
    """AttributeProto from a python value (int/float/str/list thereof)."""
    out = field_string(1, name)
    if isinstance(value, bool):
        value = int(value)
    if isinstance(value, int):
        out += field_varint(3, value) + field_varint(20, ATTR_INT)
    elif isinstance(value, float):
        out += field_float(2, value) + field_varint(20, ATTR_FLOAT)
    elif isinstance(value, str):
        out += field_bytes(4, value.encode()) + field_varint(20, ATTR_STRING)
    elif isinstance(value, (list, tuple)):
        if all(isinstance(v, int) for v in value):
            out += packed_int64s(8, value) + field_varint(20, ATTR_INTS)
        else:
            payload = b"".join(struct.pack("<f", float(v)) for v in value)
            out += field_bytes(7, payload) + field_varint(20, ATTR_FLOATS)
    else:
        raise TypeError("unsupported attribute %r" % (value,))
    return out


def tensor(name, np_array) -> bytes:
    """TensorProto (float32, raw_data)."""
    import numpy as np

    arr = np.ascontiguousarray(np_array, np.float32)
    out = b"".join(field_varint(1, d) for d in arr.shape)
    out += field_varint(2, TENSOR_FLOAT)
    out += field_string(8, name)
    out += field_bytes(9, arr.tobytes())
    return out


def value_info(name, shape) -> bytes:
    dims = b"".join(
        field_bytes(1, field_varint(1, int(d))) for d in shape)
    tensor_type = field_varint(1, TENSOR_FLOAT) + field_bytes(2, dims)
    type_proto = field_bytes(1, tensor_type)
    return field_string(1, name) + field_bytes(2, type_proto)


def node(op_type, inputs, outputs, name="", attrs=None) -> bytes:
    out = b"".join(field_string(1, i) for i in inputs)
    out += b"".join(field_string(2, o) for o in outputs)
    if name:
        out += field_string(3, name)
    out += field_string(4, op_type)
    for k in sorted(attrs or {}):
        out += field_bytes(5, attribute(k, attrs[k]))
    return out


def graph(nodes, name, initializers, inputs, outputs) -> bytes:
    out = b"".join(field_bytes(1, n) for n in nodes)
    out += field_string(2, name)
    out += b"".join(field_bytes(5, t) for t in initializers)
    out += b"".join(field_bytes(11, v) for v in inputs)
    out += b"".join(field_bytes(12, v) for v in outputs)
    return out


def model(graph_bytes, opset=13, producer="mxtpu") -> bytes:
    opset_id = field_string(1, "") + field_varint(2, opset)
    out = field_varint(1, 8)  # ir_version 8
    out += field_string(2, producer)
    out += field_bytes(7, graph_bytes)
    out += field_bytes(8, opset_id)
    return out


# ------------------------------------------------------------------ decode
def decode(buf: bytes):
    """Generic wire-format parse: {field: [value, ...]} — value is int for
    varint/fixed fields, bytes for length-delimited (decode nested messages
    by calling decode() again)."""
    out = {}
    i = 0
    n = len(buf)
    while i < n:
        tag, i = _read_varint(buf, i)
        num, wt = tag >> 3, tag & 7
        if wt == 0:
            v, i = _read_varint(buf, i)
        elif wt == 2:
            ln, i = _read_varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:
            v = struct.unpack("<I", buf[i:i + 4])[0]
            i += 4
        elif wt == 1:
            v = struct.unpack("<Q", buf[i:i + 8])[0]
            i += 8
        else:
            raise ValueError("unsupported wire type %d" % wt)
        out.setdefault(num, []).append(v)
    return out


def _read_varint(buf, i):
    shift = 0
    val = 0
    while True:
        b = buf[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, i
        shift += 7


def decode_packed_int64s(payload: bytes):
    vals = []
    i = 0
    while i < len(payload):
        v, i = _read_varint(payload, i)
        vals.append(v)
    return vals


def as_float(fixed32: int) -> float:
    return struct.unpack("<f", struct.pack("<I", fixed32))[0]
