"""ONNX import: ModelProto bytes -> (Symbol, arg_params, aux_params).

Reference: python/mxnet/contrib/onnx/onnx2mx/import_model.py. Covers the
same op subset the exporter emits, so export/import round-trips — and since
the decoder is a generic wire-format parser (proto.decode), a malformed
export fails here rather than being silently re-read.
"""
from __future__ import annotations

import numpy as np

from ...base import MXNetError
from . import proto

__all__ = ["import_model", "import_model_bytes"]


def _tensor_to_np(tbytes):
    t = proto.decode(tbytes)
    dims = [int(d) for d in t.get(1, [])]
    dtype = int(t.get(2, [proto.TENSOR_FLOAT])[0])
    if dtype != proto.TENSOR_FLOAT:
        raise MXNetError("import: only float32 tensors supported")
    name = t.get(8, [b""])[0].decode()
    if 9 in t:
        arr = np.frombuffer(t[9][0], np.float32).reshape(dims)
    else:
        arr = np.array([proto.as_float(v) if isinstance(v, int) else v
                        for v in t.get(4, [])], np.float32).reshape(dims)
    return name, arr


def _attrs_of(node_msg):
    out = {}
    for ab in node_msg.get(5, []):
        a = proto.decode(ab)
        name = a[1][0].decode()
        atype = int(a.get(20, [0])[0])
        if atype == proto.ATTR_INT:
            out[name] = int(a[3][0])
        elif atype == proto.ATTR_FLOAT:
            out[name] = proto.as_float(a[2][0])
        elif atype == proto.ATTR_STRING:
            out[name] = a[4][0].decode()
        elif atype == proto.ATTR_INTS:
            out[name] = proto.decode_packed_int64s(a[8][0]) if a.get(8) \
                else []
        elif atype == proto.ATTR_FLOATS:
            raw = a.get(7, [b""])[0]
            out[name] = list(np.frombuffer(raw, np.float32))
        else:
            out[name] = None
    return out


def import_model_bytes(blob):
    """Returns (sym, arg_params, aux_params) like the reference's
    import_model (onnx2mx/import_model.py)."""
    from ... import symbol as sym_api

    m = proto.decode(blob)
    g = proto.decode(m[7][0])
    inits = {}
    for tb in g.get(5, []):
        name, arr = _tensor_to_np(tb)
        inits[name] = arr

    env = {}  # onnx tensor name -> Symbol
    for vb in g.get(11, []):
        v = proto.decode(vb)
        name = v[1][0].decode()
        env[name] = sym_api.Variable(name)

    def sym_of(name):
        if name in env:
            return env[name]
        if name in inits:
            env[name] = sym_api.Variable(name)
            return env[name]
        raise MXNetError("import: undefined tensor %r" % name)

    for nb in g.get(1, []):
        n = proto.decode(nb)
        op = n[4][0].decode()
        ins = [i.decode() for i in n.get(1, [])]
        outs = [o.decode() for o in n.get(2, [])]
        attrs = _attrs_of(n)
        out_sym = _IMPORTERS.get(op)
        if out_sym is None:
            raise MXNetError("import: unsupported ONNX op %r" % op)
        res = out_sym(sym_of, ins, attrs, inits)
        if not isinstance(res, (list, tuple)):
            res = [res]
        for name, s in zip(outs, res):
            env[name] = s

    out_names = [proto.decode(vb)[1][0].decode() for vb in g.get(12, [])]
    outs = [env[nm] for nm in out_names]
    sym = outs[0] if len(outs) == 1 else sym_api.Group(outs)

    from ... import ndarray as nd
    arg_params, aux_params = {}, {}
    arg_names = set(sym.list_arguments())
    aux_names = set(sym.list_auxiliary_states())
    for name, arr in inits.items():
        if name in aux_names:
            aux_params[name] = nd.array(arr)
        elif name in arg_names:
            arg_params[name] = nd.array(arr)
        # consts folded into unused (e.g. fixed_gamma for used path) are
        # still arg_params if referenced; silently skip truly unused ones
    return sym, arg_params, aux_params


def import_model(path):
    with open(path, "rb") as f:
        return import_model_bytes(f.read())


# ------------------------------------------------------------ op importers
def _imp_conv(sym_of, ins, attrs, inits):
    from ... import symbol as sym_api
    kwargs = {"kernel": tuple(attrs.get("kernel_shape", ())),
              "stride": tuple(attrs.get("strides", (1, 1))),
              "dilate": tuple(attrs.get("dilations", (1, 1))),
              "num_group": int(attrs.get("group", 1)),
              "num_filter": int(inits[ins[1]].shape[0])}
    pads = attrs.get("pads")
    if pads:
        kwargs["pad"] = tuple(pads[:len(pads) // 2])
    args = [sym_of(i) for i in ins]
    if len(args) == 2:
        kwargs["no_bias"] = True
        return sym_api.Convolution(args[0], weight=args[1], **kwargs)
    return sym_api.Convolution(args[0], weight=args[1], bias=args[2],
                               **kwargs)


def _imp_bn(sym_of, ins, attrs, inits):
    from ... import symbol as sym_api
    x, g, b, mean, var = (sym_of(i) for i in ins)
    return sym_api.BatchNorm(x, gamma=g, beta=b, moving_mean=mean,
                             moving_var=var, fix_gamma=False,
                             use_global_stats=True,
                             eps=float(attrs.get("epsilon", 1e-5)))


def _imp_pool(op):
    def f(sym_of, ins, attrs, inits):
        from ... import symbol as sym_api
        kwargs = {"pool_type": "max" if "Max" in op else "avg"}
        if op.startswith("Global"):
            kwargs["global_pool"] = True
            kwargs["kernel"] = (1, 1)
        else:
            kwargs["kernel"] = tuple(attrs.get("kernel_shape", ()))
            kwargs["stride"] = tuple(attrs.get("strides", (1, 1)))
            pads = attrs.get("pads")
            if pads:
                kwargs["pad"] = tuple(pads[:len(pads) // 2])
            if attrs.get("ceil_mode"):
                kwargs["pooling_convention"] = "full"
            if "Average" in op:
                # ONNX spec default is 0 (exclude padding)
                kwargs["count_include_pad"] = bool(
                    attrs.get("count_include_pad", 0))
        return sym_api.Pooling(sym_of(ins[0]), **kwargs)
    return f


def _imp_gemm(sym_of, ins, attrs, inits):
    from ... import symbol as sym_api
    if not attrs.get("transB"):
        raise MXNetError("import: Gemm without transB unsupported")
    if attrs.get("transA") or attrs.get("alpha", 1.0) != 1.0 \
            or attrs.get("beta", 1.0) != 1.0:
        # refusing beats silently-wrong numerics (alpha scales A@B etc.)
        raise MXNetError("import: Gemm with transA/alpha/beta != defaults "
                         "unsupported")
    kwargs = {"num_hidden": int(inits[ins[1]].shape[0]), "flatten": False}
    args = [sym_of(i) for i in ins]
    if len(args) == 2:
        return sym_api.FullyConnected(args[0], weight=args[1],
                                      no_bias=True, **kwargs)
    return sym_api.FullyConnected(args[0], weight=args[1], bias=args[2],
                                  **kwargs)


def _imp_clip(sym_of, ins, attrs, inits):
    from ... import symbol as sym_api
    lo = float(np.ravel(inits[ins[1]])[0]) if len(ins) > 1 \
        else attrs.get("min")
    hi = float(np.ravel(inits[ins[2]])[0]) if len(ins) > 2 \
        else attrs.get("max")
    return sym_api.clip(sym_of(ins[0]), a_min=lo, a_max=hi)


def _unary(name):
    def f(sym_of, ins, attrs, inits):
        from ... import symbol as sym_api
        return getattr(sym_api, name)(sym_of(ins[0]))
    return f


_IMPORTERS = {
    "Conv": _imp_conv,
    "BatchNormalization": _imp_bn,
    "MaxPool": _imp_pool("MaxPool"),
    "AveragePool": _imp_pool("AveragePool"),
    "GlobalMaxPool": _imp_pool("GlobalMaxPool"),
    "GlobalAveragePool": _imp_pool("GlobalAveragePool"),
    "Gemm": _imp_gemm,
    "Clip": _imp_clip,
    "Relu": _unary("relu"),
    "Sigmoid": _unary("sigmoid"),
    "Tanh": _unary("tanh"),
    "Identity": _unary("identity"),
    "Flatten": _unary("Flatten"),
    "Add": lambda sym_of, ins, a, i:
        sym_of(ins[0]) + sym_of(ins[1]),
    "Mul": lambda sym_of, ins, a, i:
        sym_of(ins[0]) * sym_of(ins[1]),
    "Sum": lambda sym_of, ins, a, i:
        sym_of(ins[0]) + sym_of(ins[1]),
    "Softmax": lambda sym_of, ins, a, i: __import__(
        "mxtpu.symbol", fromlist=["softmax"]).softmax(
        sym_of(ins[0]), axis=int(a.get("axis", -1))),
}
