"""Contrib namespace (ref: python/mxnet/contrib/)."""
from . import quantization  # noqa: F401
