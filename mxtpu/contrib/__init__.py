"""Contrib namespace (ref: python/mxnet/contrib/)."""
from . import quantization  # noqa: F401
from . import svrg_optimization  # noqa: F401
from . import tensorboard  # noqa: F401
from . import text  # noqa: F401
from . import onnx  # noqa: F401
from . import async_checkpoint  # noqa: F401
from . import external_kernel  # noqa: F401
