"""Contrib namespace (ref: python/mxnet/contrib/)."""
from . import quantization  # noqa: F401
from . import svrg_optimization  # noqa: F401
from . import tensorboard  # noqa: F401
from . import text  # noqa: F401
from . import onnx  # noqa: F401
from . import async_checkpoint  # noqa: F401
from . import external_kernel  # noqa: F401
from . import autograd  # noqa: F401
from . import io  # noqa: F401
from . import quantization as quant  # noqa: F401  (ref alias)

# mx.contrib.ndarray / mx.contrib.symbol (+ nd/sym aliases): the contrib
# op namespaces (ref: python/mxnet/contrib/__init__.py:21-25)
from ..ndarray import contrib as ndarray  # noqa: F401
from ..ndarray import contrib as nd  # noqa: F401
from ..symbol import contrib as symbol  # noqa: F401
from ..symbol import contrib as sym  # noqa: F401
