"""mx.contrib.autograd (ref: python/mxnet/contrib/autograd.py): the
pre-1.0 experimental autograd spellings, kept as thin delegates to
:mod:`mxtpu.autograd` so old tutorials/scripts run unmodified."""
from __future__ import annotations

import functools

from .. import autograd as _ag
from ..ndarray import NDArray

__all__ = ["set_is_training", "train_section", "test_section",
           "mark_variables", "backward", "compute_gradient",
           "grad_and_loss", "grad"]


def set_is_training(is_train):
    """(ref: contrib/autograd.py:32) — returns the previous state."""
    prev_t = _ag.set_training(bool(is_train))
    _ag.set_recording(bool(is_train))
    return prev_t


def train_section():
    """``with train_section():`` == ``with autograd.record():``
    (ref: contrib/autograd.py:74)."""
    return _ag.record()


def test_section():
    """(ref: contrib/autograd.py:88)"""
    return _ag.pause()


def mark_variables(variables, gradients, grad_reqs="write"):
    """Attach gradient buffers to leaves (ref: contrib/autograd.py:102) —
    the single-NDArray convenience form over autograd.mark_variables."""
    if isinstance(variables, NDArray):
        variables, gradients = [variables], [gradients]
    _ag.mark_variables(variables, gradients, grad_reqs)


def backward(outputs, out_grads=None, retain_graph=False):
    """(ref: contrib/autograd.py:123)"""
    _ag.backward(outputs, head_grads=out_grads, retain_graph=retain_graph)


def compute_gradient(outputs):
    """(ref: contrib/autograd.py:158)"""
    backward(outputs)


def grad_and_loss(func, argnum=None):
    """Returns fn computing (gradients, loss) (ref: contrib/autograd.py:163)."""
    @functools.wraps(func)
    def wrapped(*args):
        variables = list(args)
        if argnum is not None:
            argnums = [argnum] if isinstance(argnum, int) else list(argnum)
            variables = [args[i] for i in argnums]
        for v in variables:
            assert isinstance(v, NDArray), "type of autograd input should "\
                "be NDArray."
            v.attach_grad()
        with train_section():
            outputs = func(*args)
        backward([outputs] if isinstance(outputs, NDArray) else outputs)
        grads = [v.grad for v in variables]
        return grads, outputs
    return wrapped


def grad(func, argnum=None):
    """Returns fn computing just the gradients (ref: contrib/autograd.py:195)."""
    grad_with_loss_func = grad_and_loss(func, argnum)

    @functools.wraps(grad_with_loss_func)
    def wrapped(*args):
        return grad_with_loss_func(*args)[0]
    return wrapped
