"""Convert torch / torchvision checkpoints into the model-zoo weight store.

The reference ships hash-checked pretrained weights from its S3 bucket
(model_store.py); this build has no network path, so the practical way to
get real pretrained vision weights is to convert a torch checkpoint the
user already has (``torch.hub`` cache, torchvision download on another
machine, or any ``state_dict`` file). The layouts agree almost everywhere
— torch Conv2d weights are OIHW like the reference, Linear weights are
(out, in) like FullyConnected — so conversion is a NAME mapping plus the
BatchNorm field renames (weight/bias -> gamma/beta).

    import torch
    from mxtpu.contrib import torch_zoo
    from mxtpu.gluon.model_zoo import vision

    net = vision.resnet18_v1()
    sd = torch.load("resnet18.pth", map_location="cpu")
    torch_zoo.load_torch_parameters(net, sd,
                                    torch_zoo.torchvision_resnet_map(18))
    net.save_parameters("~/.mxtpu/models/resnet18_v1.params")  # store it

NOTE on semantics: torchvision's bottleneck resnets are "v1.5" (stride-2
on the 3x3 conv); the reference's ``resnet*_v1`` strides the first 1x1.
Shapes convert either way, but bottleneck (50/101/152) torch weights
reach their published accuracy only under v1.5 semantics — prefer the
basic-block depths (18/34), where the two definitions coincide.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError

__all__ = ["load_torch_parameters", "torchvision_resnet_map",
           "convert_state_dict"]

_BN_FIELDS = {"weight": "gamma", "bias": "beta",
              "running_mean": "running_mean",
              "running_var": "running_var"}


def torchvision_resnet_map(num_layers):
    """torchvision resnet state_dict names -> this zoo's resnet_v1 names.

    Layout recap — torchvision: conv1/bn1, layer{1-4}.{i}.(conv|bn){1,2,3}
    + .downsample.{0,1}, fc.  This zoo (resnet.py): features.0 conv,
    features.1 bn, features.{4-7}.{i}.body.{0,1,3,4[,6,7]} +
    .downsample.{0,1}, output."""
    blocks = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
              101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}.get(num_layers)
    if blocks is None:
        raise MXNetError("no torchvision resnet with %s layers"
                         % num_layers)
    bottleneck = num_layers >= 50
    n_convs = 3 if bottleneck else 2
    m = {"conv1.weight": "features.0.weight", "fc.weight": "output.weight",
         "fc.bias": "output.bias"}
    for tf, of in _BN_FIELDS.items():
        m["bn1.%s" % tf] = "features.1.%s" % of
    for stage, n in enumerate(blocks):
        for i in range(n):
            t = "layer%d.%d." % (stage + 1, i)
            o = "features.%d.%d." % (stage + 4, i)
            for c in range(n_convs):
                # body indices: conv at 3c, bn at 3c+1 (relu between)
                m[t + "conv%d.weight" % (c + 1)] = \
                    o + "body.%d.weight" % (3 * c)
                for tf, of in _BN_FIELDS.items():
                    m[t + "bn%d.%s" % (c + 1, tf)] = \
                        o + "body.%d.%s" % (3 * c + 1, of)
            if i == 0 and (stage > 0 or bottleneck):
                # only the first block of a stage changes stride/width;
                # stage 1 keeps channels in the basic-block nets
                m[t + "downsample.0.weight"] = o + "downsample.0.weight"
                for tf, of in _BN_FIELDS.items():
                    m[t + "downsample.1.%s" % tf] = \
                        o + "downsample.1.%s" % of
    return m


def convert_state_dict(state_dict, name_map, strict=True):
    """Map a torch state_dict through ``name_map`` -> {our_name: ndarray}.
    Unmapped torch entries raise unless they are torch bookkeeping
    (num_batches_tracked) or ``strict=False``."""
    out = {}
    for tname, tensor in state_dict.items():
        if tname.endswith("num_batches_tracked"):
            continue  # torch-only BN counter; the reference has no analog
        oname = name_map.get(tname)
        if oname is None:
            if strict:
                raise MXNetError("no mapping for torch parameter %s"
                                 % tname)
            continue
        a = tensor.detach().cpu()
        if str(a.dtype) == "torch.bfloat16":
            a = a.float()
        out[oname] = _np.ascontiguousarray(a.numpy())
    return out


def load_torch_parameters(net, state_dict, name_map, strict=True):
    """Load a torch state_dict into an (initialized or shape-settled)
    block via ``name_map``; every block parameter must be covered when
    ``strict``."""
    from ..ndarray import array

    converted = convert_state_dict(state_dict, name_map, strict=strict)
    params = net._collect_params_with_prefix()
    if strict:
        missing = [n for n in params if n not in converted]
        if missing:
            raise MXNetError("torch checkpoint covers %d/%d parameters; "
                             "missing e.g. %s" % (len(converted),
                                                  len(params), missing[:5]))
    for name, a in converted.items():
        if name not in params:
            if strict:
                raise MXNetError("mapped name %s not found in block" % name)
            continue
        params[name].set_data(array(a))
    return net
