"""TensorBoard logging callback (ref: python/mxnet/contrib/tensorboard.py
LogMetricsCallback).

The reference depends on the dmlc `tensorboard` pip package; this build
uses torch.utils.tensorboard (torch is in the image) when available and
falls back to a plain JSONL event log otherwise — training code keeps one
callback either way.
"""
from __future__ import annotations

import json
import os
import time

__all__ = ["LogMetricsCallback"]


class LogMetricsCallback:
    """Batch-end callback logging eval metrics as tensorboard scalars
    (ref: tensorboard.py:LogMetricsCallback).

    Use: ``mod.fit(..., batch_end_callback=LogMetricsCallback(logdir))``.
    """

    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        self._step = 0
        os.makedirs(logging_dir, exist_ok=True)
        try:
            from torch.utils.tensorboard import SummaryWriter
            self._writer = SummaryWriter(logging_dir)
            self._jsonl = None
        except Exception:
            self._writer = None
            self._jsonl = open(os.path.join(
                logging_dir, "metrics-%d.jsonl" % int(time.time())), "a")

    def __call__(self, param=None, **kwargs):
        """Accepts a BatchEndParam-style object or keyword form."""
        metric = getattr(param, "eval_metric", None) \
            or kwargs.get("eval_metric")
        if metric is None:
            return
        self._step += 1
        names, values = metric.get()
        if not isinstance(names, (list, tuple)):
            names, values = [names], [values]
        for name, value in zip(names, values):
            if self.prefix:
                name = "%s-%s" % (self.prefix, name)
            if self._writer is not None:
                self._writer.add_scalar(name, value, self._step)
            else:
                self._jsonl.write(json.dumps(
                    {"step": self._step, "metric": name,
                     "value": float(value)}) + "\n")
                self._jsonl.flush()

    def flush(self):
        if self._writer is not None:
            self._writer.flush()

    def close(self):
        if self._writer is not None:
            self._writer.close()
        if self._jsonl is not None:
            self._jsonl.close()
