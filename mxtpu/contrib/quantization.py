"""INT8 post-training quantization driver
(ref: python/mxnet/contrib/quantization.py + quantize_graph_pass.cc).

The reference rewrites the NNVM graph: FP32 conv/FC nodes become
quantized_conv/quantized_fully_connected bracketed by quantize/dequantize,
with thresholds from a calibration pass (min/max or KL-entropy over a
calibration dataset). TPU-native: the same three phases, expressed on gluon
blocks instead of graph nodes —

1. ``quantize_net(net)`` structurally swaps every Dense/Conv2D for a
   Quantized* wrapper (the graph pass),
2. ``calibrate(qnet, data_iter)`` runs FP32 forwards recording per-layer
   input ranges (the calibration pass; ``mode="naive"`` min/max like the
   reference's default),
3. ``freeze(qnet)`` quantizes weights per-tensor symmetric int8 and flips
   the wrappers to the int8 kernels (mxtpu/ops/quantization.py), which XLA
   fuses into MXU int8 dot/conv with int32 accumulation.

The wrappers stay HybridBlocks, so a frozen net hybridizes/exports like any
other.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from ..gluon import nn
from ..gluon.block import HybridBlock

__all__ = ["quantize_net", "calibrate", "freeze", "quantize_model_gluon"]


class _QuantizedLayer(HybridBlock):
    """Shared calibrate/freeze machinery for wrapped FLOP layers."""

    def __init__(self, inner, **kwargs):
        super().__init__(**kwargs)
        self._mode = "calib"
        self._data_range = 0.0
        self._w_range = None
        self._wq = None
        self._collect_samples = False
        self._samples = []
        with self.name_scope():
            self.inner = inner

    def _observe(self, x):
        a = x.asnumpy()
        self._data_range = max(self._data_range,
                               float(np.abs(a).max()) or 1e-6)
        if getattr(self, "_collect_samples", False):
            # entropy calibration: total retained samples per layer are
            # bounded (~4M floats) — beyond that, reservoir-style thinning
            flat = a.ravel()
            if flat.size > 65536:
                flat = flat[:: flat.size // 65536 + 1]
            self._samples.append(flat.astype(np.float32))
            self._sample_count = getattr(self, "_sample_count", 0) \
                + flat.size
            if self._sample_count > 4 * 1024 * 1024:
                merged = np.concatenate(self._samples)[::2]
                self._samples = [merged]
                self._sample_count = merged.size

    def freeze(self):
        from .. import nd
        w = self.inner.weight.data()
        self._w_range = float(np.abs(w.asnumpy()).max()) or 1e-6
        q, _, _ = nd.quantize(w, -self._w_range, self._w_range)
        self._wq = q
        self._mode = "int8"

    def hybrid_forward(self, F, x, **params):
        if self._mode == "calib":
            self._observe(x)
            return self.inner(x)
        if self._mode != "int8":
            raise MXNetError("call freeze() after calibration")
        r = self._data_range
        xq, _, _ = F.quantize(x, -r, r)
        out = self._int8_forward(F, xq, r)
        if getattr(self.inner, "act", None) is not None:
            out = self.inner.act(out)
        return out


class QuantizedDense(_QuantizedLayer):
    def _int8_forward(self, F, xq, r):
        inner = self.inner
        bias = None if inner.bias is None else inner.bias.data()
        return F.quantized_fully_connected(
            xq, self._wq, bias, min_data=-r, max_data=r,
            min_weight=-self._w_range, max_weight=self._w_range,
            no_bias=bias is None, flatten=inner._flatten,
            num_hidden=inner._units)


class QuantizedConv2D(_QuantizedLayer):
    def _int8_forward(self, F, xq, r):
        inner = self.inner
        bias = None if inner.bias is None else inner.bias.data()
        kw = inner._kwargs
        return F.quantized_conv(
            xq, self._wq, bias, min_data=-r, max_data=r,
            min_weight=-self._w_range, max_weight=self._w_range,
            kernel=kw["kernel"], stride=kw["stride"], dilate=kw["dilate"],
            pad=kw["pad"], num_filter=kw["num_filter"],
            num_group=kw["num_group"], no_bias=bias is None,
            layout=kw["layout"])


def quantize_net(net, exclude=(), quiet=False):
    """Swap quantizable leaves in place; returns the same net
    (the quantize_graph_pass analog). ``exclude``: layer name substrings to
    keep FP32 (the reference's excluded_sym_names).

    Coverage is Dense + Conv2D only (int8 MXU paths); every OTHER
    parameterized layer type encountered is reported loudly — silent
    fp32 passthrough hides accuracy/perf surprises (VERDICT r2 weak #9).
    """
    import logging
    skipped = {}
    for parent, name, child in _walk(net):
        if any(s in child.name for s in exclude):
            continue
        if isinstance(child, nn.Dense):
            _swap(parent, name, QuantizedDense(child))
        elif isinstance(child, nn.Conv2D) and type(child) is nn.Conv2D:
            _swap(parent, name, QuantizedConv2D(child))
        elif getattr(child, "_reg_params", None) and \
                type(child).__name__ not in ("QuantizedDense",
                                             "QuantizedConv2D"):
            skipped.setdefault(type(child).__name__, []).append(child.name)
    if skipped and not quiet:
        for cls_name, names in sorted(skipped.items()):
            logging.getLogger(__name__).warning(
                "quantize_net: %s layer(s) stay float32 (no int8 lowering "
                "for %s): %s", len(names), cls_name, ", ".join(names[:5])
                + ("..." if len(names) > 5 else ""))
    return net


def _walk(block):
    for name, child in list(block._children.items()):
        yield block, name, child
        yield from _walk(child)


def _swap(parent, name, wrapper):
    parent._children[name] = wrapper
    # attribute access (net.fc1) must resolve to the wrapper too
    for attr, val in list(vars(parent).items()):
        if val is wrapper.inner:
            object.__setattr__(parent, attr, wrapper)


def calibrate(net, calib_data, num_batches=None, mode="naive",
              num_bins=8001, num_quantized_bins=255):
    """Run FP32 forwards so every wrapper records its input range
    (ref: quantization.py _collect_layer_statistics).

    mode="naive"   — per-layer min/max range (the reference default).
    mode="entropy" — KL-divergence-optimal thresholds (the reference's
    _get_optimal_thresholds, after the TensorRT int8 calibration method):
    clipping outliers at the threshold that minimizes the KL divergence
    between the fp32 activation distribution and its 255-bin quantized
    projection usually beats raw min/max when activations are heavy-tailed.
    """
    wrappers = [c for _, _, c in _walk(net)
                if isinstance(c, _QuantizedLayer)]
    if mode == "entropy":
        for w in wrappers:
            w._collect_samples = True
            w._samples = []
            w._sample_count = 0
    elif mode != "naive":
        raise MXNetError("calibrate mode must be 'naive' or 'entropy'")
    for i, batch in enumerate(calib_data):
        if num_batches is not None and i >= num_batches:
            break
        data = batch.data[0] if hasattr(batch, "data") else batch
        net(data)
    if mode == "entropy":
        for w in wrappers:
            if w._samples:
                w._data_range = _optimal_threshold(
                    np.concatenate(w._samples), num_bins,
                    num_quantized_bins)
            w._collect_samples = False
            w._samples = []
            w._sample_count = 0
    return net


def _optimal_threshold(arr, num_bins=8001, num_quantized_bins=255):
    """KL-optimal symmetric clipping threshold for int8 quantization
    (ref: quantization.py _get_optimal_threshold; method from the TensorRT
    8-bit inference calibration talk).

    Sweeps candidate thresholds t over the activation histogram; for each,
    the clipped distribution P (outliers folded into the edge bins) is
    compared against Q, P re-binned to ``num_quantized_bins`` levels and
    expanded back; the t minimizing KL(P||Q) wins.
    """
    from scipy import stats

    if num_bins % 2 == 0 or num_quantized_bins % 2 == 0:
        raise MXNetError("num_bins and num_quantized_bins must be odd "
                         "(symmetric histogram around zero)")
    th = float(np.abs(arr).max()) or 1e-6
    hist, edges = np.histogram(arr, bins=num_bins, range=(-th, th))
    zero = num_bins // 2
    half_q = num_quantized_bins // 2

    best_div = np.inf
    best_th = th
    for i in range(half_q, zero + 1):
        lo, hi = zero - i, zero + i + 1
        sliced = hist[lo:hi].astype(np.float64)
        p = sliced.copy()
        p[0] += hist[:lo].sum()        # fold left outliers
        p[-1] += hist[hi:].sum()       # fold right outliers
        nonzero = p != 0               # after folding (reference semantics)

        merge = p.size // num_quantized_bins
        # Q: re-bin the (unclipped) slice to the quantized resolution, then
        # spread each bucket uniformly over its nonzero positions —
        # vectorized with reduceat (a python inner loop here costs ~1M
        # iterations per layer at the default bin counts)
        bounds = np.arange(num_quantized_bins) * merge
        bucket = np.add.reduceat(sliced, bounds)
        counts = np.add.reduceat(nonzero.astype(np.int64), bounds)
        per_bin = np.where(counts > 0, bucket / np.maximum(counts, 1), 0.0)
        owner = np.minimum(np.arange(p.size) // merge,
                           num_quantized_bins - 1)
        q = per_bin[owner]
        q[~nonzero] = 0.0
        p = _smooth(p)
        q = _smooth(q)
        if q is None or p is None:
            continue
        div = stats.entropy(p, q)
        if div < best_div:
            best_div = div
            best_th = edges[hi]
    return float(best_th)


def _smooth(dist, eps=0.0001):
    """Laplace-style smoothing so KL is finite (ref: quantization.py
    _smooth_distribution)."""
    is_zero = dist == 0
    n_zero = int(is_zero.sum())
    n_nonzero = dist.size - n_zero
    if n_nonzero == 0:
        return None
    shift = eps * n_zero / n_nonzero
    out = dist.astype(np.float64)
    out[is_zero] = eps
    out[~is_zero] -= shift
    if (out[~is_zero] <= 0).any():
        return None
    return out


def freeze(net):
    """Quantize weights and flip wrappers to the int8 kernels."""
    n = 0
    for _, _, child in _walk(net):
        if isinstance(child, _QuantizedLayer):
            child.freeze()
            n += 1
    if not n:
        raise MXNetError("freeze: no quantized layers found; "
                         "call quantize_net first")
    return net


def quantize_model_gluon(net, calib_data, exclude=(), num_batches=None):
    """One-call flow (ref: quantize_model): pass -> calibrate -> freeze."""
    quantize_net(net, exclude=exclude)
    calibrate(net, calib_data, num_batches=num_batches)
    return freeze(net)
