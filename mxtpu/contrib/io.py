"""mx.contrib.io (ref: python/mxnet/contrib/io.py): bridge a gluon
DataLoader into the DataIter interface so classic Module code can consume
gluon datasets (incl. the multiprocess shared-memory loader)."""
from __future__ import annotations

from ..io import DataDesc, DataIter
from ..ndarray import zeros

__all__ = ["DataLoaderIter"]


class DataLoaderIter(DataIter):
    """Iterator over a ``gluon.data.DataLoader`` (ref: contrib/io.py:25).
    The first batch is drawn at construction to learn shapes; short final
    batches are zero-padded with ``DataBatch.pad`` reporting the filler
    rows."""

    def __init__(self, loader, data_name="data", label_name="softmax_label",
                 dtype="float32"):
        super().__init__()
        self._loader = loader
        self._iter = iter(self._loader)
        try:
            data, label = next(self._iter)
        except StopIteration:
            raise ValueError("DataLoaderIter needs a non-empty DataLoader "
                             "(shapes are learned from its first batch)") \
                from None
        self.batch_size = data.shape[0]
        self.dtype = dtype
        self.provide_data = [DataDesc(data_name, data.shape, dtype)]
        self.provide_label = [DataDesc(label_name, label.shape, dtype)]
        self._current_batch = None
        self.reset()

    def reset(self):
        self._iter = iter(self._loader)

    def iter_next(self):
        try:
            self._current_batch = next(self._iter)
        except StopIteration:
            self._current_batch = None
        return self._current_batch is not None

    def _padded(self, arr):
        if not self.getpad():
            return [arr.astype(self.dtype)]
        full = zeros((self.batch_size,) + tuple(arr.shape[1:]),
                     dtype=self.dtype)
        full[:arr.shape[0]] = arr.astype(self.dtype)
        return [full]

    def getdata(self):
        return self._padded(self._current_batch[0])

    def getlabel(self):
        return self._padded(self._current_batch[1])

    def getpad(self):
        return self.batch_size - self._current_batch[0].shape[0]

    def getindex(self):
        return None
