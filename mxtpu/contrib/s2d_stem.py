"""Space-to-depth stem transform for conv nets (the MLPerf ResNet trick).

The first conv of ImageNet nets (7x7, stride 2, 3 input channels) wastes
the MXU: 3 channels against the 8x128 tiling leaves most lanes idle. The
standard fix reshapes the input into 2x2 blocks (224x224x3 -> 112x112x12)
and runs an EXACTLY equivalent 4x4 stride-1 convolution whose weights are
a zero-padded re-indexing of the original 7x7 kernel — same function, same
gradients, 4x the input channels on the MXU.

This implementation derives the 4x4 weights from the ORIGINAL 7x7
parameter inside the traced forward (a scatter of 9,408 elements — free),
so the wrapped model keeps its parameter structure: checkpoints
round-trip, gradients flow to the original weight, and the transform can
be toggled per run (bench: BENCH_S2D_STEM=1).

Derivation (NHWC, block b=2, original stride 2 pad 3): output row y reads
input rows R = 2y + k' for k' = ky-3 in [-3, 3]. With R = 2r + py,
py = k' mod 2 and r = y + floor(k'/2) in [y-2, y+1] — a 4-tap kernel over
s2d rows at stride 1 with padding (2, 1); columns identically. The s2d
channel of (py, px, c) is (py*2 + px)*3 + c.
"""
from __future__ import annotations

import os

import jax.numpy as jnp

from ..base import MXNetError

__all__ = ["space_to_depth_nhwc", "embed_stem_weight", "apply_to_resnet",
           "stem_mode"]

_B = 2  # block size of the transform (fixed by the stride-2 stem)


def stem_mode():
    """The first-class stem lever, promoted from bench-env-only (round 7):
    ``MXTPU_S2D_STEM`` = 0 (plain 7x7s2 stem), 1 (single s2d), 2 (double
    s2d, the staged MXU-shaped variant). Read at TRACE time by a
    policy-mode ``_StemFn`` (mode=None), and part of
    ``registry.policy_key`` — so a per-run flip recompiles every jit
    cache (CachedOp, executors) instead of silently reusing the other
    stem's executable, and it composes with the MXTPU_PALLAS_CONV gate in
    one cache key. bench.py maps its BENCH_S2D_STEM knob onto this env."""
    v = os.environ.get("MXTPU_S2D_STEM", "0")
    if v not in ("0", "1", "2"):
        raise MXNetError("MXTPU_S2D_STEM=%r: valid values are 0 (plain "
                         "stem), 1 (s2d), 2 (double-s2d)" % (v,))
    return int(v)


def space_to_depth_nhwc(x):
    """(N, H, W, C) -> (N, H/2, W/2, 4C), channel-major in (py, px)."""
    n, h, w, c = x.shape
    y = x.reshape(n, h // _B, _B, w // _B, _B, c)
    y = y.transpose(0, 1, 3, 2, 4, 5)  # n, r, s, py, px, c
    return y.reshape(n, h // _B, w // _B, _B * _B * c)


def embed_stem_weight(w):
    """Zero-embed a (7, 7, C, F) HWIO stem kernel into the equivalent
    (4, 4, 4C, F) kernel for the s2d input (see module derivation)."""
    kh, kw, c, f = w.shape
    if (kh, kw) != (7, 7):
        raise MXNetError("s2d stem embedding expects a 7x7 kernel, got %s"
                         % ((kh, kw),))
    out = jnp.zeros((4, 4, _B * _B * c, f), w.dtype)
    for ky in range(7):
        kyp = ky - 3
        py = kyp % _B
        a = (kyp - py) // _B + 2
        for kx in range(7):
            kxp = kx - 3
            px = kxp % _B
            b = (kxp - px) // _B + 2
            ch = (py * _B + px) * c
            out = out.at[a, b, ch:ch + c, :].set(w[ky, kx])
    return out


def space_to_depth4_nhwc(x):
    """(N, H, W, C) -> (N, H/4, W/4, 16C), channel-major in (rho, sigma)."""
    n, h, w, c = x.shape
    y = x.reshape(n, h // 4, 4, w // 4, 4, c)
    y = y.transpose(0, 1, 3, 2, 4, 5)
    return y.reshape(n, h // 4, w // 4, 16 * c)


def depth_to_space2_nhwc(y, f):
    """(N, H, W, 4F) with channel layout (py, px, f) -> (N, 2H, 2W, F)."""
    n, h, w, _ = y.shape
    y = y.reshape(n, h, w, 2, 2, f)
    y = y.transpose(0, 1, 3, 2, 4, 5)
    return y.reshape(n, 2 * h, 2 * w, f)


def embed_stem_weight4(w):
    """Zero-embed a (7, 7, C, F) stem kernel into the (3, 3, 16C, 4F)
    kernel of the DOUBLE-s2d stem (mode 2).

    Derivation: output row Y = 2y + py (py in {0,1}) reads input rows
    R = 2Y + ky - 3 = 4y + t with t = 2py + ky - 3 in [-3, 5]. Writing
    R = 4(y + a - 1) + rho gives a = t//4 + 1 in {0,1,2} and rho = t % 4
    — a 3-tap kernel over 4-row input blocks at stride 1 with SYMMETRIC
    padding 1 (t = -4, i.e. block row -1 tap 0, never occurs, so no
    asymmetric padding is needed, unlike mode 1). Columns identically.
    The output packs the 2x2 output-pixel block into channels
    (py*2 + px)*F + f, un-packed by depth_to_space2_nhwc.

    Why: mode 1's conv is K=192 (im2col), N=64 — both underfill the MXU
    (half the lanes, 1.5 contraction passes) and it measured no faster
    than the plain 7x7 in isolation (perf_followup.log stem phase). This
    shape is K=432, N=256: full lanes both sides, ~3.4 contraction
    passes, at 56x56 spatial. ~2.9x padded FLOPs, but at large-matmul
    efficiency the net is the win the stem needs (PERF.md stem table)."""
    kh, kw, c, f = w.shape
    if (kh, kw) != (7, 7):
        raise MXNetError("s2d stem embedding expects a 7x7 kernel, got %s"
                         % ((kh, kw),))
    out = jnp.zeros((3, 3, 16 * c, 4 * f), w.dtype)
    for py in range(2):
        for ky in range(7):
            t = 2 * py + ky - 3
            a, rho = t // 4 + 1, t % 4
            for px in range(2):
                for kx in range(7):
                    u = 2 * px + kx - 3
                    b, sig = u // 4 + 1, u % 4
                    ch = (rho * 4 + sig) * c
                    fo = (py * 2 + px) * f
                    out = out.at[a, b, ch:ch + c, fo:fo + f].set(w[ky, kx])
    return out


class _StemFn:
    """Callable forward for the wrapped stem (kept tiny and pickle-free).
    mode 1: single 2x2 s2d + 4x4 conv; mode 2: 4x4 s2d + 3x3 conv +
    2x2 depth-to-space (see embed_stem_weight4); mode 0: the plain 7x7s2
    conv (byte-identical semantics to the unwrapped stem, so a wrapped
    net is a no-op at mode 0); mode None: POLICY mode — the mode is read
    from MXTPU_S2D_STEM at trace time (stem_mode), making the stem a
    per-run lever that recompiles through registry.policy_key."""

    def __init__(self, weight_param, bias_param, mode=1):
        # strings/typos must not silently run mode 1; None = policy mode
        if mode not in (None, 0, 1, 2):
            raise MXNetError("s2d stem mode must be None, 0, 1 or 2, "
                             "got %r" % (mode,))
        self._w = weight_param
        self._b = bias_param
        self._mode = mode

    def __call__(self, x):
        from ..ops.conv_acc import conv_fast
        mode = self._mode if self._mode is not None else stem_mode()
        if mode == 0:
            # the untransformed stem (the conv the wrap replaced) — bias
            # rides conv_fast so the Pallas gate can fuse it
            return conv_fast(x, self._w, strides=(2, 2),
                             padding=[(3, 3), (3, 3)],
                             lhs_dilation=(1, 1), rhs_dilation=(1, 1),
                             dims=("NHWC", "HWIO", "NHWC"), groups=1,
                             bias=self._b)
        if mode == 2:
            s = space_to_depth4_nhwc(x)
            w2 = embed_stem_weight4(self._w)
            out = conv_fast(s, w2, strides=(1, 1),
                            padding=[(1, 1), (1, 1)],
                            lhs_dilation=(1, 1), rhs_dilation=(1, 1),
                            dims=("NHWC", "HWIO", "NHWC"), groups=1)
            out = depth_to_space2_nhwc(out, self._w.shape[-1])
        else:
            s = space_to_depth_nhwc(x)
            w4 = embed_stem_weight(self._w)
            out = conv_fast(s, w4, strides=(1, 1), padding=[(2, 1), (2, 1)],
                            lhs_dilation=(1, 1), rhs_dilation=(1, 1),
                            dims=("NHWC", "HWIO", "NHWC"), groups=1)
        if self._b is not None:
            out = out + self._b
        return out


def apply_to_resnet(net, mode=None):
    """Swap the stem Conv2D of an NHWC zoo resnet for the s2d-equivalent
    path, in place. The conv's Parameters are untouched — only its forward
    is re-routed — so checkpoints and trainers keep working. Returns net.
    mode None (default) = POLICY mode: the variant is picked per trace
    from MXTPU_S2D_STEM (0 = plain stem, so wrapping is free), letting
    one wrapped net A/B all three stems through policy_key recompiles;
    mode 1 = single s2d (112^2 x 12 conv4x4); mode 2 = double s2d
    (56^2 x 48 conv3x3 -> 256ch -> depth-to-space; MXU-shaped, see
    embed_stem_weight4)."""
    if mode not in (None, 0, 1, 2):
        raise MXNetError("s2d stem mode must be None, 0, 1 or 2, got %r"
                         % (mode,))
    feats = list(net.features._children.values())
    conv = feats[0]
    if type(conv).__name__ != "Conv2D":
        raise MXNetError("expected the first feature block to be the stem "
                         "Conv2D; got %s" % type(conv).__name__)
    if getattr(conv, "_layout", None) not in ("NHWC",):
        raise MXNetError("s2d stem transform supports NHWC nets (build the "
                         "zoo model under mx.layout('NHWC'))")
    # the derivation hardcodes the ImageNet stem: 7x7, stride 2, pad 3,
    # no dilation/groups/activation — anything else would be silently
    # transformed into a DIFFERENT function
    bad = []
    if tuple(getattr(conv, "_kwargs", {}).get("kernel", ())) != (7, 7):
        bad.append("kernel != 7x7")
    if tuple(conv._kwargs.get("stride", ())) != (2, 2):
        bad.append("stride != 2")
    if tuple(conv._kwargs.get("pad", ())) != (3, 3):
        bad.append("pad != 3")
    if tuple(conv._kwargs.get("dilate", (1, 1))) != (1, 1):
        bad.append("dilate != 1")
    if conv._kwargs.get("num_group", 1) != 1:
        bad.append("grouped")
    if getattr(conv, "act", None) is not None:
        bad.append("fused activation")
    if bad:
        raise MXNetError("stem conv not s2d-transformable: %s"
                         % ", ".join(bad))

    from ..ndarray.ndarray import _apply

    def hybrid_forward(self, F, x, weight=None, bias=None):
        return _apply(
            lambda xd, wd, *rest: _StemFn(wd, rest[0] if rest else None,
                                          mode=mode)(xd),
            (x, weight) + (() if bias is None else (bias,)),
            name="s2d_stem")

    conv.hybrid_forward = hybrid_forward.__get__(conv, type(conv))
    return net
