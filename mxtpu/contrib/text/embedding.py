"""Token embeddings loaded from pretrained files
(ref: python/mxnet/contrib/text/embedding.py).

This environment has zero egress, so the reference's auto-download of
GloVe/fastText archives becomes explicit local-file loading:
``CustomEmbedding(path)`` reads any ``token<delim>v1<delim>v2...`` text
file (the GloVe .txt and fastText .vec layouts both parse; .vec's
count/dim header line is auto-skipped). The vocabulary-attachment and
lookup surface (``get_vecs_by_tokens``/``update_token_vectors``/
``CompositeEmbedding``) matches the reference.
"""
from __future__ import annotations

import io
import logging

import numpy as np

from ...base import MXNetError
from ...ndarray import NDArray, array
from .vocab import Vocabulary

__all__ = ["TokenEmbedding", "CustomEmbedding", "CompositeEmbedding",
           "register", "create", "get_pretrained_file_names"]

_REGISTRY = {}


def register(cls):
    """Register an embedding class under its lowercase name
    (ref: embedding.py:register)."""
    _REGISTRY[cls.__name__.lower()] = cls
    return cls


def create(embedding_name, **kwargs):
    name = embedding_name.lower()
    if name not in _REGISTRY:
        raise MXNetError("unknown embedding %r (registered: %s)"
                         % (embedding_name, sorted(_REGISTRY)))
    return _REGISTRY[name](**kwargs)


def get_pretrained_file_names(embedding_name=None):
    """The reference lists downloadable archives; this build is offline, so
    the answer documents the local-file path instead."""
    return {name: "offline build: pass file_path= to %s" % name
            for name in sorted(_REGISTRY)
            if embedding_name in (None, name)}


class TokenEmbedding(Vocabulary):
    """Embedding matrix indexed by a Vocabulary
    (ref: embedding.py:_TokenEmbedding)."""

    def __init__(self, unknown_token="<unk>", init_unknown_vec=None):
        super().__init__(counter=None, unknown_token=unknown_token)
        self._init_unknown_vec = init_unknown_vec or (lambda d: np.zeros(d))
        self._vec_len = 0
        self._idx_to_vec = None

    # -------------------------------------------------------------- loading
    def _load_embedding_file(self, path, elem_delim=" ", encoding="utf-8"):
        vecs = []
        with io.open(path, encoding=encoding) as f:
            for lineno, line in enumerate(f):
                parts = line.rstrip().split(elem_delim)
                if lineno == 0 and len(parts) == 2 and \
                        all(p.isdigit() for p in parts):
                    continue  # fastText .vec header: "<count> <dim>"
                if len(parts) < 2:
                    continue
                tok = parts[0]
                try:
                    vec = [float(v) for v in parts[1:] if v]
                except ValueError:
                    logging.getLogger(__name__).warning(
                        "skipping unparseable embedding line %d", lineno)
                    continue
                if self._vec_len == 0:
                    self._vec_len = len(vec)
                elif len(vec) != self._vec_len:
                    raise MXNetError(
                        "inconsistent vector length at line %d: %d vs %d"
                        % (lineno, len(vec), self._vec_len))
                if tok in self._token_to_idx:
                    continue  # first occurrence wins (reference behavior)
                self._token_to_idx[tok] = len(self._idx_to_token)
                self._idx_to_token.append(tok)
                vecs.append(vec)
        if not vecs:
            raise MXNetError("no embedding vectors parsed from %s" % path)
        unk = np.asarray(self._init_unknown_vec(self._vec_len), np.float32)
        self._idx_to_vec = array(
            np.vstack([unk[None, :], np.asarray(vecs, np.float32)]))

    # --------------------------------------------------------------- lookup
    @property
    def vec_len(self):
        return self._vec_len

    @property
    def idx_to_vec(self) -> NDArray:
        return self._idx_to_vec

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        """Vector(s) for token(s); unknown tokens get the unknown vector
        (ref: embedding.py:get_vecs_by_tokens)."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        idxs = []
        for t in toks:
            i = self._token_to_idx.get(t)
            if i is None and lower_case_backup:
                i = self._token_to_idx.get(t.lower())
            idxs.append(0 if i is None else i)
        vecs = self._idx_to_vec.asnumpy()[idxs]
        return array(vecs[0]) if single else array(vecs)

    def update_token_vectors(self, tokens, new_vectors):
        toks = [tokens] if isinstance(tokens, str) else tokens
        vals = new_vectors.asnumpy() if isinstance(new_vectors, NDArray) \
            else np.asarray(new_vectors, np.float32)
        vals = vals.reshape(len(toks), self._vec_len)
        mat = np.array(self._idx_to_vec.asnumpy())  # writable copy
        for t, v in zip(toks, vals):
            if t not in self._token_to_idx:
                raise MXNetError("token %r not indexed" % t)
            mat[self._token_to_idx[t]] = v
        self._idx_to_vec = array(mat)


@register
class CustomEmbedding(TokenEmbedding):
    """Embedding from a user file of ``token v1 v2 ...`` lines
    (ref: embedding.py:CustomEmbedding; also loads GloVe .txt and
    fastText .vec layouts)."""

    def __init__(self, file_path, elem_delim=" ", encoding="utf-8",
                 **kwargs):
        super().__init__(**kwargs)
        self._load_embedding_file(file_path, elem_delim, encoding)


@register
class CompositeEmbedding(TokenEmbedding):
    """Concatenation of several embeddings over one vocabulary
    (ref: embedding.py:CompositeEmbedding)."""

    def __init__(self, vocabulary, token_embeddings):
        if not isinstance(token_embeddings, (list, tuple)):
            token_embeddings = [token_embeddings]
        super().__init__(unknown_token=vocabulary.unknown_token)
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._token_to_idx = dict(vocabulary.token_to_idx)
        mats = []
        for emb in token_embeddings:
            mats.append(emb.get_vecs_by_tokens(self._idx_to_token)
                        .asnumpy())
        full = np.concatenate(mats, axis=1)
        self._vec_len = full.shape[1]
        self._idx_to_vec = array(full)
