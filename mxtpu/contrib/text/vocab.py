"""Text vocabulary (ref: python/mxnet/contrib/text/vocab.py Vocabulary).

Indexes tokens by frequency with an unknown token at index 0 and optional
reserved tokens, exactly the reference's layout so downstream embedding
matrices line up."""
from __future__ import annotations

from collections import Counter

from ...base import MXNetError

__all__ = ["Vocabulary"]


class Vocabulary:
    """Frequency-ordered token index.

    Parameters mirror the reference: ``counter`` token->count,
    ``most_freq_count`` cap on indexed tokens (excluding unknown/reserved),
    ``min_freq`` threshold, ``unknown_token`` at index 0, and
    ``reserved_tokens`` right after it.
    """

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        if min_freq < 1:
            raise MXNetError("min_freq must be >= 1")
        reserved_tokens = list(reserved_tokens or [])
        if unknown_token in reserved_tokens:
            raise MXNetError("unknown_token cannot also be reserved")
        if len(set(reserved_tokens)) != len(reserved_tokens):
            raise MXNetError("reserved_tokens must be unique")
        self._unknown_token = unknown_token
        self._reserved_tokens = reserved_tokens
        self._idx_to_token = [unknown_token] + reserved_tokens
        if counter is not None:
            special = set(self._idx_to_token)
            # frequency-major, then insertion order for ties (the
            # reference sorts by (-freq, token))
            pairs = sorted(Counter(counter).items(),
                           key=lambda kv: (-kv[1], kv[0]))
            budget = most_freq_count if most_freq_count is not None \
                else len(pairs)
            for tok, freq in pairs:
                if budget <= 0:
                    break
                if freq < min_freq or tok in special:
                    continue
                self._idx_to_token.append(tok)
                budget -= 1
        self._token_to_idx = {t: i for i, t in enumerate(self._idx_to_token)}

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens):
        """Token(s) -> index(es); unknown tokens map to index 0."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        out = [self._token_to_idx.get(t, 0) for t in toks]
        return out[0] if single else out

    def to_tokens(self, indices):
        single = isinstance(indices, int)
        idxs = [indices] if single else indices
        out = []
        for i in idxs:
            if not 0 <= i < len(self._idx_to_token):
                raise MXNetError("token index %d out of range" % i)
            out.append(self._idx_to_token[i])
        return out[0] if single else out
