"""Text token indexing + embeddings
(ref: python/mxnet/contrib/text/)."""
from . import embedding, utils, vocab  # noqa: F401
from .vocab import Vocabulary  # noqa: F401

__all__ = ["Vocabulary", "embedding", "utils", "vocab"]
