"""SVRGModule: Module trained with stochastic variance-reduced gradients.

Reference: python/mxnet/contrib/svrg_optimization/svrg_module.py — every
``update_freq`` epochs it snapshots the weights w~, computes the
full-dataset gradient mu = grad f(w~), and per batch replaces the gradient
with ``grad f_i(w) - grad f_i(w~) + mu`` (Johnson & Zhang 2013), shrinking
gradient variance as w approaches w~.

TPU-native simplification: the reference plumbs the full-gradient
accumulation through a special KVStore optimizer pair
(_SVRGOptimizer/_AssignmentOptimizer); here the snapshot model is simply a
second bound executor over the same symbol, and the variance-reduced
combination happens on the gradient arrays before the normal updater runs
— same math, no optimizer-registry tricks.
"""
from __future__ import annotations

import logging

from ...base import MXNetError
from ...module.module import Module
from ...ndarray import NDArray

__all__ = ["SVRGModule"]


class SVRGModule(Module):
    """Module with SVRG gradient updates (same construction signature as
    Module plus ``update_freq`` — epochs between full-gradient snapshots).
    """

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), update_freq=2, **kwargs):
        super().__init__(symbol, data_names=data_names,
                         label_names=label_names, **kwargs)
        if update_freq < 1:
            raise MXNetError("update_freq must be >= 1")
        self.update_freq = update_freq
        self._mod_aux = Module(symbol, data_names=data_names,
                               label_names=label_names, **kwargs)
        self._full_grads = None  # name -> NDArray (mu)

    # ------------------------------------------------------------ plumbing
    def bind(self, data_shapes, label_shapes=None, **kwargs):
        super().bind(data_shapes, label_shapes=label_shapes, **kwargs)
        self._mod_aux.bind(data_shapes, label_shapes=label_shapes, **kwargs)

    def init_params(self, *args, **kwargs):
        super().init_params(*args, **kwargs)
        self._sync_aux_params()

    def _sync_aux_params(self):
        """Snapshot: w~ <- w."""
        arg, aux = self.get_params()
        self._mod_aux.init_params(arg_params=arg, aux_params=aux,
                                  force_init=True, allow_missing=False)

    # ---------------------------------------------------------------- SVRG
    def update_full_grads(self, train_data):
        """Snapshot the weights and accumulate mu = mean over the dataset
        of grad f(w~) (ref: svrg_module.py:update_full_grads)."""
        self._sync_aux_params()
        sums = {}
        nbatch = 0
        train_data.reset()
        for batch in train_data:
            self._mod_aux.forward_backward(batch)
            for name in self._param_names:
                g = self._mod_aux._exec.grad_dict.get(name)
                if g is None:
                    continue
                if name in sums:
                    sums[name] = sums[name] + g._data
                else:
                    sums[name] = g._data
            nbatch += 1
        if nbatch == 0:
            raise MXNetError("update_full_grads: empty data iterator")
        self._full_grads = {k: NDArray(v / nbatch) for k, v in sums.items()}
        train_data.reset()

    def forward_backward(self, data_batch):
        """fwd+bwd on the live weights AND the snapshot weights."""
        super().forward_backward(data_batch)
        if self._full_grads is not None:
            self._mod_aux.forward_backward(data_batch)

    def update(self):
        """Apply the variance-reduced gradient
        g <- g - g_snapshot + mu, then the normal optimizer step
        (ref: svrg_module.py:_svrg_grads_update_rule)."""
        if self._full_grads is not None:
            for name in self._param_names:
                g = self._exec.grad_dict.get(name)
                if g is None or name not in self._full_grads:
                    continue
                g_snap = self._mod_aux._exec.grad_dict.get(name)
                g._set_data(g._data - g_snap._data
                            + self._full_grads[name]._data)
        super().update()

    # ----------------------------------------------------------------- fit
    def fit(self, train_data, eval_data=None, eval_metric="acc",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            initializer=None, num_epoch=None, kvstore=None,
            batch_end_callback=None, begin_epoch=0, force_rebind=False):
        """Training loop with the periodic full-gradient pass
        (ref: svrg_module.py:fit)."""
        assert num_epoch is not None, "please specify number of epochs"
        from ...initializer import Uniform
        from ...model import BatchEndParam
        from ...module.base_module import _as_metric

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label, for_training=True,
                  force_rebind=force_rebind)
        self.init_params(initializer=initializer or Uniform(0.01))
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        self._mod_aux.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                                     optimizer_params=optimizer_params)
        metric = _as_metric(eval_metric)
        for epoch in range(begin_epoch, num_epoch):
            if epoch % self.update_freq == 0:
                self.update_full_grads(train_data)
            metric.reset()
            train_data.reset()
            for nbatch, batch in enumerate(train_data):
                self.forward_backward(batch)
                self.update()
                self.update_metric(metric, batch.label)
                if batch_end_callback is not None:
                    # positional BatchEndParam, list-of-callbacks supported
                    # (same convention as base_module.py fit)
                    param = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                          eval_metric=metric, locals=None)
                    cbs = batch_end_callback if isinstance(
                        batch_end_callback, (list, tuple)) \
                        else [batch_end_callback]
                    for cb in cbs:
                        cb(param)
            logging.getLogger(__name__).info(
                "Epoch[%d] SVRG train %s", epoch, metric.get())
            if eval_data is not None:
                vmetric = _as_metric(eval_metric)
                self.score(eval_data, vmetric)
                logging.getLogger(__name__).info(
                    "Epoch[%d] SVRG validation %s", epoch, vmetric.get())
        return metric
