"""SVRG (stochastic variance-reduced gradient) optimization
(ref: python/mxnet/contrib/svrg_optimization/)."""
from .svrg_module import SVRGModule  # noqa: F401

__all__ = ["SVRGModule"]
