"""Runtime kernel compilation: user-authored Pallas kernels as ops.

Reference: python/mxnet/rtc.py ``CudaModule`` — runtime-compiled CUDA
source (NVRTC, src/common/rtc.cc) launched on NDArrays. The TPU-native
escape hatch is Pallas (SURVEY §2.2 "rtc/NVRTC maps to inline Pallas"):
``PallasModule`` execs a Python source string that defines Pallas kernel
function(s) (``*_ref`` arguments, last ref(s) are outputs), and
``Kernel.launch`` wraps it in ``pl.pallas_call`` + jit on NDArrays.

The API shape mirrors the reference —
``module.get_kernel(name, signature).launch(args, ...)`` — with TPU-shaped
launch parameters (out_shapes + optional grid/block specs) instead of CUDA
grid/block dims.
"""
from __future__ import annotations

import numpy as _np

from .base import MXNetError
from .ndarray import NDArray

__all__ = ["PallasModule", "Kernel", "CudaModule"]


def CudaModule(source, options=(), exports=()):
    """Reference-name entry point (ref: rtc.py:CudaModule). CUDA C++
    source cannot run on a TPU; raises with the migration path unless the
    source is actually Python (then it routes to PallasModule)."""
    head = source.lstrip()[:64]
    looks_like_cuda = ("__global__" in source or "#include" in head
                       or "extern \"C\"" in source)
    if looks_like_cuda:
        raise MXNetError(
            "mx.rtc.CudaModule received CUDA C++ source; this runtime has "
            "no NVRTC/GPU. Rewrite the kernel as a Pallas function (Refs "
            "in, last args are outputs) and use mx.rtc.PallasModule — see "
            "mxtpu/rtc.py and the examples in tests/test_contrib_python.py.")
    return PallasModule(source, exports=list(exports) or None)


class PallasModule:
    """Compile Pallas kernel source at runtime (ref: rtc.py:CudaModule).

    Parameters
    ----------
    source : str
        Python source. Each kernel is a function taking pallas Refs; by
        convention the final ``num_outputs`` arguments are output Refs.
        The namespace is pre-seeded with ``pl`` (jax.experimental.pallas),
        ``jnp``, and ``jax``.
    exports : list of str, optional
        Kernel names; default = every top-level function defined.
    """

    def __init__(self, source, exports=None):
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        self._namespace = {"pl": pl, "jnp": jnp, "jax": jax}
        seeded = set(self._namespace)
        try:
            exec(compile(source, "<mxtpu.rtc>", "exec"), self._namespace)
        except SyntaxError as e:
            raise MXNetError("PallasModule source failed to compile: %s"
                             % e) from e
        import inspect
        fns = {k: v for k, v in self._namespace.items()
               if inspect.isfunction(v) and k not in seeded
               and not k.startswith("__")}
        if exports is not None:
            missing = [e for e in exports if e not in fns]
            if missing:
                raise MXNetError("exports not found in source: %s" % missing)
            fns = {k: fns[k] for k in exports}
        if not fns:
            raise MXNetError("no kernel functions found in source")
        self._kernels = fns

    def get_kernel(self, name, num_outputs=1):
        """Kernel by name (ref: rtc.py:get_kernel — the signature string is
        unnecessary here: Refs carry shapes/dtypes)."""
        if name not in self._kernels:
            raise MXNetError("kernel %r not in module (have: %s)"
                             % (name, sorted(self._kernels)))
        return Kernel(self._kernels[name], name, num_outputs)


class Kernel:
    """A launchable Pallas kernel (ref: rtc.py:CudaModule.Kernel)."""

    def __init__(self, fn, name, num_outputs=1):
        self._fn = fn
        self.name = name
        self._num_outputs = num_outputs

    def launch(self, args, out_shapes, out_dtypes=None, grid=None,
               in_specs=None, out_specs=None, interpret=None):
        """Run the kernel (ref: rtc.py:Kernel.launch — CUDA grid/block dims
        become the pallas grid/BlockSpecs; XLA owns scheduling).

        args : list of NDArray inputs.
        out_shapes : shape tuple or list of shape tuples.
        grid/in_specs/out_specs : forwarded to ``pl.pallas_call``.
        interpret : force interpreter mode (defaults to True off-TPU so
            kernels stay testable on CPU, matching how the test suite runs).
        """
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        if isinstance(out_shapes, (tuple, list)) and (
                not out_shapes or isinstance(out_shapes[0], int)):
            out_shapes = [tuple(out_shapes)]
        n_out = len(out_shapes)
        if out_dtypes is None:
            out_dtypes = [args[0].dtype if args else _np.float32] * n_out
        elif isinstance(out_dtypes, (str, type)) or not hasattr(
                out_dtypes, "__len__"):
            out_dtypes = [out_dtypes] * n_out
        if len(out_dtypes) != n_out:
            raise MXNetError("launch: %d out_dtypes for %d out_shapes"
                             % (len(out_dtypes), n_out))
        if interpret is None:
            interpret = jax.devices()[0].platform != "tpu"
        out_shape = [jax.ShapeDtypeStruct(tuple(s), jnp.dtype(d))
                     for s, d in zip(out_shapes, out_dtypes)]
        if len(out_shapes) != self._num_outputs:
            raise MXNetError(
                "kernel %r declared num_outputs=%d but launch got %d "
                "out_shapes" % (self.name, self._num_outputs,
                                len(out_shapes)))
        from . import compile_service as csvc
        # the compile service is the cache (LRU-bounded — the old
        # per-kernel dict was unbounded under launch-signature churn),
        # keyed by kernel source identity + the full launch signature.
        # The source digest is memoized: getsource+sha per LAUNCH would
        # tax the eager-loop use case this API serves
        fn_id = getattr(self, "_fn_token", None)
        if fn_id is None:
            fn_id = self._fn_token = "%s:%s" % (
                self.name, csvc.source_token(self._fn))
        datas = [a._data if isinstance(a, NDArray) else jnp.asarray(a)
                 for a in args]
        # a launch nested under an outer trace (tracer inputs) keys a
        # SEPARATE plain-jit entry: an AOT executable compiled by an
        # earlier eager launch of the same signature cannot be invoked
        # with tracers — the variant keeps both worlds correct
        example = csvc.concrete_args(tuple(datas))
        key = csvc.canonical_key(
            site="rtc",
            fn_id=fn_id,
            signature=(tuple((tuple(a.shape), str(a.dtype))
                             for a in args),
                       tuple(tuple(s) for s in out_shapes),
                       tuple(str(d) for d in out_dtypes), repr(grid),
                       bool(interpret), repr(in_specs), repr(out_specs))
            + (("traced",) if example is None else ()),
            device=csvc.device_token(), nonce=csvc.instance_nonce(self))

        def build():
            kwargs = {"out_shape": out_shape if n_out > 1 else out_shape[0],
                      "interpret": interpret}
            if grid is not None:
                kwargs["grid"] = grid
            if in_specs is not None:
                kwargs["in_specs"] = in_specs
            if out_specs is not None:
                kwargs["out_specs"] = out_specs
            return jax.jit(pl.pallas_call(self._fn, **kwargs))

        # retrace watchdog: user kernels compile once per launch
        # signature — a shape-unstable caller shows up here by name
        entry = csvc.get_or_build(
            key, build,
            provenance=lambda: {"kernel": self.name,
                                "args": [(tuple(a.shape), str(a.dtype))
                                         for a in args]},
            example_args=example)
        res = entry.fn(*datas)
        if isinstance(res, (list, tuple)):
            return [NDArray(r) for r in res]
        return NDArray(res)
