"""Shared performance model: datasheet peak tables, the ONE MFU
convention, and version-proof accessors over XLA's cost/memory analyses.

Before this module the chip peak-FLOPs table and the MFU convention lived
twice (``bench.py`` and ``tools/perf_peak.py``) and every consumer of
``Compiled.cost_analysis()`` hand-rolled the same "list-of-dicts vs dict
vs None" dance (``parallel/train.py``, ``tools/perf_bisect.py``). This
module is the single copy both the offline benches and the runtime
observatory (:mod:`mxtpu.xprof`) draw from.

**The MFU convention** (one convention, everywhere): model FLOPs counted
MAC=2 (a multiply-accumulate is 2 FLOPs — the standard convention, and
how XLA counts), divided by the *datasheet* chip peak for the compute
dtype. ``hfu`` uses XLA's executed-FLOP count against the same peak.
Rounds 1-3 of PERF.md mixed MAC=1 counts with MAC=2 peaks and understated
utilization 2x — routing every denominator through :func:`peak_flops`
makes that class of bug structural.

Import-light by design: no jax import at module load (the accessors take
already-materialized analysis objects), so ``tools/telemetry_report.py``
can use the tables offline.
"""
from __future__ import annotations

import os

__all__ = ["NOMINAL_PEAK_TFLOPS", "HBM_BANDWIDTH_GBPS",
           "nominal_tflops", "peak_flops", "peak_bandwidth",
           "critical_intensity", "mfu", "cost_dict", "flops_of",
           "bytes_accessed_of", "memory_dict", "roofline_verdict"]

# Datasheet dense bf16 peak per chip, TFLOP/s, matched by substring
# against ``device.device_kind`` (PJRT kinds look like "TPU v5 lite",
# "TPU v4", ...). MAC=2 convention — the number printed on the datasheet.
NOMINAL_PEAK_TFLOPS = {
    "v5 lite": 197.0,   # v5e PJRT device_kind spelling
    "v5e": 197.0,
    "v5p": 459.0,
    "v6 lite": 918.0,   # v6e (Trillium)
    "v6e": 918.0,
    "v4": 275.0,
    "v3": 123.0,
    "v2": 46.0,
}

# Datasheet HBM bandwidth per chip, GB/s — the roofline's other axis.
HBM_BANDWIDTH_GBPS = {
    "v5 lite": 819.0,
    "v5e": 819.0,
    "v5p": 2765.0,
    "v6 lite": 1640.0,
    "v6e": 1640.0,
    "v4": 1228.0,
    "v3": 900.0,
    "v2": 700.0,
}

_DEFAULT_TPU_PEAK_TFLOPS = 197.0   # unknown TPU kind: assume the fleet's
_DEFAULT_TPU_BW_GBPS = 819.0       # workhorse v5e rather than refusing


def _device_kind(device):
    """(platform, kind) of ``device`` (an int index, a jax Device, or
    None = device 0). Returns ("unknown", "") when no backend answers."""
    if isinstance(device, str):
        return ("tpu", device.lower())  # offline: caller names the kind
    try:
        import jax
        if device is None or isinstance(device, int):
            device = jax.devices()[device or 0]
        return (device.platform,
                str(getattr(device, "device_kind", "")).lower())
    except Exception:  # noqa: BLE001 — no backend / dead PJRT client
        return ("unknown", "")


def _lookup(table, kind, default):
    for sub, v in table.items():
        if sub in kind:
            return v
    return default


def nominal_tflops(device=None):
    """Datasheet peak TFLOP/s for ``device`` (bf16 dense, MAC=2), or None
    off-TPU. ``device`` may be a jax Device, an int index, a device-kind
    string (offline use), or None (device 0)."""
    platform, kind = _device_kind(device)
    if platform != "tpu":
        return None
    return _lookup(NOMINAL_PEAK_TFLOPS, kind, _DEFAULT_TPU_PEAK_TFLOPS)


def peak_flops(device=None):
    """Chip peak FLOP/s for the MFU denominator — ``MXTPU_PEAK_TFLOPS``
    override first (how a CPU-tier test or an unlisted chip pins the
    denominator), else the datasheet table. None when MFU is meaningless
    (CPU fallback, no override)."""
    env = os.environ.get("MXTPU_PEAK_TFLOPS")
    if env:
        try:
            return float(env) * 1e12
        except ValueError:
            pass
    t = nominal_tflops(device)
    return t * 1e12 if t else None


def peak_bandwidth(device=None):
    """Datasheet HBM bandwidth in bytes/s (``MXTPU_PEAK_GBPS`` override),
    or None off-TPU."""
    env = os.environ.get("MXTPU_PEAK_GBPS")
    if env:
        try:
            return float(env) * 1e9
        except ValueError:
            pass
    platform, kind = _device_kind(device)
    if platform != "tpu" and not env:
        return None
    return _lookup(HBM_BANDWIDTH_GBPS, kind, _DEFAULT_TPU_BW_GBPS) * 1e9


def critical_intensity(device=None):
    """The roofline ridge point, FLOPs/byte: executables whose arithmetic
    intensity sits below it are memory-bound on this chip (the fusion-gap
    methodology of arXiv:2301.13062 — the standing hand-kernel shortlist
    is exactly the memory-bound entries with the most FLOPs)."""
    pf, bw = peak_flops(device), peak_bandwidth(device)
    if not pf or not bw:
        return None
    return pf / bw


def mfu(flops_per_s, device=None, n_devices=1):
    """Achieved FLOP/s as a fraction of the datasheet peak across
    ``n_devices`` chips. None when the peak is unknown."""
    pf = peak_flops(device)
    if not pf or not flops_per_s:
        return None
    return float(flops_per_s) / (pf * max(int(n_devices), 1))


# ------------------------------------------------ XLA analysis accessors
def cost_dict(cost):
    """Normalize ``Compiled.cost_analysis()`` across jax versions: newer
    jax returns a dict, 0.4.x returns a singleton list-of-dicts, some
    backends return None or an empty list. Always a plain dict ({} when
    absent) — THE accessor every consumer routes through instead of raw
    ``cost[0]["flops"]`` indexing."""
    if cost is None:
        return {}
    if isinstance(cost, (list, tuple)):
        if not cost:
            return {}
        cost = cost[0]
    if cost is None:
        return {}
    try:
        return dict(cost)
    except (TypeError, ValueError):
        return {}


def flops_of(compiled):
    """XLA cost-model FLOPs of a compiled executable, or None when the
    backend exposes none (some report -1 for "unknown" — treated as
    absent, never as a negative MFU)."""
    c = cost_dict(compiled.cost_analysis())
    v = c.get("flops")
    if v is None or float(v) <= 0:
        return None
    return float(v)


def bytes_accessed_of(compiled):
    """XLA cost-model bytes accessed (HBM traffic estimate), or None."""
    c = cost_dict(compiled.cost_analysis())
    v = c.get("bytes accessed")
    if v is None or float(v) <= 0:
        return None
    return float(v)


_MEM_FIELDS = {
    "argument_bytes": "argument_size_in_bytes",
    "output_bytes": "output_size_in_bytes",
    "temp_bytes": "temp_size_in_bytes",
    "generated_code_bytes": "generated_code_size_in_bytes",
    # alias = donated input buffers reused for outputs: the bytes the
    # donation discipline saves vs a copy-in/copy-out executable
    "donated_bytes": "alias_size_in_bytes",
}


def memory_dict(mem_stats):
    """``Compiled.memory_analysis()`` (a CompiledMemoryStats) as a plain
    int dict with stable keys; {} when the backend returns None."""
    if mem_stats is None:
        return {}
    out = {}
    for key, attr in _MEM_FIELDS.items():
        v = getattr(mem_stats, attr, None)
        if v is None and isinstance(mem_stats, dict):
            v = mem_stats.get(attr)
        if v is not None:
            out[key] = int(v)
    return out


def roofline_verdict(flops, bytes_accessed, ridge):
    """"compute"- vs "memory"-bound call for one executable given its
    cost-model arithmetic intensity and the chip ridge point; None when
    either side is unknown."""
    if not flops or not bytes_accessed or not ridge:
        return None
    return "memory" if (flops / bytes_accessed) < ridge else "compute"
