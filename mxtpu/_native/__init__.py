"""Native library loader: builds src/*.cc into one shared object on first use.

The reference ships libmxnet.so built by CMake; here the native surface is
small enough to compile on demand with g++ (cached by source mtime) and bound
via ctypes — the framework's FFI convention (no pybind11 in the image).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_LOCK = threading.Lock()
_LIB = None
_BUILD_ERR = None

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC_DIR = os.path.normpath(os.path.join(_HERE, "..", "..", "src"))
_SO_PATH = os.path.join(_HERE, "_libmxtpu.so")


def _have_python_dev():
    import sysconfig
    inc = sysconfig.get_paths().get("include")
    return bool(inc) and os.path.exists(os.path.join(inc, "Python.h"))


def _sources():
    out = []
    skip_c_api = not _have_python_dev()
    for root, _dirs, files in os.walk(_SRC_DIR):
        # the C ABI needs Python.h; without it, still build the rest
        # (recordio etc.) rather than losing the whole native fast path
        if skip_c_api and os.path.basename(root) == "c_api":
            continue
        for f in sorted(files):
            if f.endswith(".cc"):
                out.append(os.path.join(root, f))
    return out


_STAMP_PATH = _SO_PATH + ".stamp"


def _build_stamp():
    """Cache key beyond source mtimes: the build bakes in this interpreter's
    include dir / libpython / rpath, so a different venv must rebuild."""
    import sys
    import sysconfig
    return "%s|%s|%s" % (sys.version, sysconfig.get_config_var("LIBDIR"),
                         sysconfig.get_config_var("LDVERSION"))


def _needs_build(sources):
    if not os.path.exists(_SO_PATH):
        return True
    try:
        with open(_STAMP_PATH) as f:
            if f.read() != _build_stamp():
                return True
    except OSError:
        return True
    so_mtime = os.path.getmtime(_SO_PATH)
    return any(os.path.getmtime(s) > so_mtime for s in sources)


def _python_flags():
    """Compile/link flags for the embedded-CPython C ABI (src/c_api/).

    The C ABI delegates to mxtpu.c_api_impl through the CPython API: inside
    a Python process the symbols resolve from the interpreter; a plain-C
    host gets them from the linked libpython (python3-config --embed).
    """
    import sysconfig
    inc = sysconfig.get_paths().get("include")
    cflags = ["-I" + inc] if inc else []
    libdir = sysconfig.get_config_var("LIBDIR")
    ver = sysconfig.get_config_var("LDVERSION") or sysconfig.get_config_var(
        "VERSION")
    ldflags = []
    if libdir and ver:
        ldflags = ["-L" + libdir, "-Wl,-rpath," + libdir, "-lpython" + ver]
    return cflags, ldflags


def _build(sources):
    if _have_python_dev():
        cflags, ldflags = _python_flags()
    else:
        cflags, ldflags = [], []
    cmd = (["g++", "-O3", "-shared", "-fPIC", "-std=c++17"] + cflags +
           ["-o", _SO_PATH] + sources + ldflags)
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError("native build failed:\n%s" % proc.stderr)
    with open(_STAMP_PATH, "w") as f:
        f.write(_build_stamp())


def get_lib():
    """Return the ctypes library, building it if needed; None when the
    toolchain is unavailable (callers fall back to pure python)."""
    global _LIB, _BUILD_ERR
    if _LIB is not None or _BUILD_ERR is not None:
        return _LIB
    with _LOCK:
        if _LIB is not None or _BUILD_ERR is not None:
            return _LIB
        try:
            sources = _sources()
            if not sources:
                raise RuntimeError("no native sources under %s" % _SRC_DIR)
            if _needs_build(sources):
                _build(sources)
            lib = ctypes.CDLL(_SO_PATH)
            _configure(lib)
            _LIB = lib
        except Exception as e:  # noqa: BLE001 - any failure => python fallback
            _BUILD_ERR = e
    return _LIB


def build_error():
    return _BUILD_ERR


def _configure(lib):
    u64 = ctypes.c_uint64
    if hasattr(lib, "MXTPUGetLastError"):  # absent when built w/o Python.h
        _configure_c_api(lib)
    lib.mxtpu_recordio_writer_create.restype = ctypes.c_void_p
    lib.mxtpu_recordio_writer_create.argtypes = [ctypes.c_char_p,
                                                 ctypes.c_char_p]
    lib.mxtpu_recordio_writer_write.restype = ctypes.c_int
    lib.mxtpu_recordio_writer_write.argtypes = [ctypes.c_void_p,
                                                ctypes.c_char_p, u64]
    lib.mxtpu_recordio_writer_tell.restype = u64
    lib.mxtpu_recordio_writer_tell.argtypes = [ctypes.c_void_p]
    lib.mxtpu_recordio_writer_close.restype = None
    lib.mxtpu_recordio_writer_close.argtypes = [ctypes.c_void_p]
    lib.mxtpu_recordio_reader_create.restype = ctypes.c_void_p
    lib.mxtpu_recordio_reader_create.argtypes = [ctypes.c_char_p]
    lib.mxtpu_recordio_reader_read.restype = ctypes.POINTER(ctypes.c_char)
    lib.mxtpu_recordio_reader_read.argtypes = [ctypes.c_void_p,
                                               ctypes.POINTER(u64)]
    lib.mxtpu_recordio_reader_seek.restype = None
    lib.mxtpu_recordio_reader_seek.argtypes = [ctypes.c_void_p, u64]
    lib.mxtpu_recordio_reader_tell.restype = u64
    lib.mxtpu_recordio_reader_tell.argtypes = [ctypes.c_void_p]
    lib.mxtpu_recordio_reader_close.restype = None
    lib.mxtpu_recordio_reader_close.argtypes = [ctypes.c_void_p]


def _configure_c_api(lib):
    """ctypes signatures for the flat C ABI (include/mxtpu/c_api.h)."""
    p = ctypes.c_void_p
    pp = ctypes.POINTER(p)
    i64p = ctypes.POINTER(ctypes.c_int64)
    ip = ctypes.POINTER(ctypes.c_int)
    fp = ctypes.POINTER(ctypes.c_float)
    ccp = ctypes.c_char_p
    cpp = ctypes.POINTER(ccp)
    lib.MXTPUGetLastError.restype = ctypes.c_char_p
    lib.MXTPUGetLastError.argtypes = []
    lib.MXTPURuntimeInit.restype = ctypes.c_int
    lib.MXTPURuntimeInit.argtypes = [ccp]
    lib.MXTPUNDArrayCreateFromBlob.restype = ctypes.c_int
    lib.MXTPUNDArrayCreateFromBlob.argtypes = [fp, i64p, ctypes.c_int, pp]
    lib.MXTPUNDArrayShape.restype = ctypes.c_int
    lib.MXTPUNDArrayShape.argtypes = [p, ip, i64p]
    lib.MXTPUNDArraySyncCopyToCPU.restype = ctypes.c_int
    lib.MXTPUNDArraySyncCopyToCPU.argtypes = [p, fp, ctypes.c_int64]
    lib.MXTPUNDArrayFree.restype = ctypes.c_int
    lib.MXTPUNDArrayFree.argtypes = [p]
    lib.MXTPUImperativeInvoke.restype = ctypes.c_int
    lib.MXTPUImperativeInvoke.argtypes = [ccp, pp, ctypes.c_int, cpp, cpp,
                                          ctypes.c_int, pp, ip]
    lib.MXTPUPredCreate.restype = ctypes.c_int
    lib.MXTPUPredCreate.argtypes = [ccp, ctypes.c_int, ccp, i64p,
                                    ctypes.c_int, pp]
    lib.MXTPUPredSetInput.restype = ctypes.c_int
    lib.MXTPUPredSetInput.argtypes = [p, fp, ctypes.c_int64]
    lib.MXTPUPredForward.restype = ctypes.c_int
    lib.MXTPUPredForward.argtypes = [p]
    lib.MXTPUPredGetOutputShape.restype = ctypes.c_int
    lib.MXTPUPredGetOutputShape.argtypes = [p, ctypes.c_int, ip, i64p]
    lib.MXTPUPredGetOutput.restype = ctypes.c_int
    lib.MXTPUPredGetOutput.argtypes = [p, ctypes.c_int, fp, ctypes.c_int64]
    lib.MXTPUPredFree.restype = ctypes.c_int
    lib.MXTPUPredFree.argtypes = [p]
