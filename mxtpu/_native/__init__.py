"""Native library loader: builds src/*.cc into one shared object on first use.

The reference ships libmxnet.so built by CMake; here the native surface is
small enough to compile on demand with g++ (cached by source mtime) and bound
via ctypes — the framework's FFI convention (no pybind11 in the image).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_LOCK = threading.Lock()
_LIB = None
_BUILD_ERR = None

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC_DIR = os.path.normpath(os.path.join(_HERE, "..", "..", "src"))
_SO_PATH = os.path.join(_HERE, "_libmxtpu.so")


def _sources():
    out = []
    for root, _dirs, files in os.walk(_SRC_DIR):
        for f in sorted(files):
            if f.endswith(".cc"):
                out.append(os.path.join(root, f))
    return out


def _needs_build(sources):
    if not os.path.exists(_SO_PATH):
        return True
    so_mtime = os.path.getmtime(_SO_PATH)
    return any(os.path.getmtime(s) > so_mtime for s in sources)


def _build(sources):
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
           "-o", _SO_PATH] + sources
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError("native build failed:\n%s" % proc.stderr)


def get_lib():
    """Return the ctypes library, building it if needed; None when the
    toolchain is unavailable (callers fall back to pure python)."""
    global _LIB, _BUILD_ERR
    if _LIB is not None or _BUILD_ERR is not None:
        return _LIB
    with _LOCK:
        if _LIB is not None or _BUILD_ERR is not None:
            return _LIB
        try:
            sources = _sources()
            if not sources:
                raise RuntimeError("no native sources under %s" % _SRC_DIR)
            if _needs_build(sources):
                _build(sources)
            lib = ctypes.CDLL(_SO_PATH)
            _configure(lib)
            _LIB = lib
        except Exception as e:  # noqa: BLE001 - any failure => python fallback
            _BUILD_ERR = e
    return _LIB


def build_error():
    return _BUILD_ERR


def _configure(lib):
    u64 = ctypes.c_uint64
    lib.mxtpu_recordio_writer_create.restype = ctypes.c_void_p
    lib.mxtpu_recordio_writer_create.argtypes = [ctypes.c_char_p,
                                                 ctypes.c_char_p]
    lib.mxtpu_recordio_writer_write.restype = ctypes.c_int
    lib.mxtpu_recordio_writer_write.argtypes = [ctypes.c_void_p,
                                                ctypes.c_char_p, u64]
    lib.mxtpu_recordio_writer_tell.restype = u64
    lib.mxtpu_recordio_writer_tell.argtypes = [ctypes.c_void_p]
    lib.mxtpu_recordio_writer_close.restype = None
    lib.mxtpu_recordio_writer_close.argtypes = [ctypes.c_void_p]
    lib.mxtpu_recordio_reader_create.restype = ctypes.c_void_p
    lib.mxtpu_recordio_reader_create.argtypes = [ctypes.c_char_p]
    lib.mxtpu_recordio_reader_read.restype = ctypes.POINTER(ctypes.c_char)
    lib.mxtpu_recordio_reader_read.argtypes = [ctypes.c_void_p,
                                               ctypes.POINTER(u64)]
    lib.mxtpu_recordio_reader_seek.restype = None
    lib.mxtpu_recordio_reader_seek.argtypes = [ctypes.c_void_p, u64]
    lib.mxtpu_recordio_reader_tell.restype = u64
    lib.mxtpu_recordio_reader_tell.argtypes = [ctypes.c_void_p]
    lib.mxtpu_recordio_reader_close.restype = None
    lib.mxtpu_recordio_reader_close.argtypes = [ctypes.c_void_p]
