"""One compile service: the unified jit-cache engine under every jit
surface (ROADMAP item 5).

Before this module, ten jit caches (``fused_optimizer``, ``cached_op``,
``executor``/``executor.backward``, ``subgraph_exec``,
``parallel.train_step``, ``rtc``, ``serving.predict``/``.r<i>``,
``serving.decode``) each reinvented keying, retrace reporting, and
warmup, and every process restart or replica scale-up paid full
recompilation on the critical path. This module is the one front door
they all resolve through:

* **Canonical key** — :func:`canonical_key` builds the one cache key
  shape every site speaks: ``(site, fn identity, abstract
  shapes/dtypes signature, registry.policy_key, sharding/MeshPlan
  fingerprint, donation discipline, device token)`` plus an in-memory
  instance ``nonce`` that is deliberately EXCLUDED from the on-disk
  digest — two live instances never alias each other's executables,
  but a restarted process (same function identity, same signature)
  warms from the previous process's artifacts.
* **Centralized reporting** — every cache miss routes its freshly-built
  executable through ``telemetry.record_retrace(site, provenance,
  compiled=...)`` exactly as the per-site caches did, so the retrace
  watchdog and the xprof executable ledger see identical surfaces; a
  disk-loaded executable registers ledger-only (``xprof.watch``) and
  bumps ``compile.disk.hits{site}`` instead — a load is not a compile
  and must not trip the watchdog.
* **LRU bound** — the store holds at most ``MXTPU_COMPILE_CACHE_ENTRIES``
  executables (default 1024, ``<= 0`` = unbounded); evictions count
  into ``compile.evictions{site}``. This bounds the previously
  unbounded per-site dicts (``rtc.Kernel._compiled``,
  ``subgraph_exec``, executor ``_jits``) under shape churn.
* **AOT warmup** — :func:`warmup` lowers/compiles a declared entry list
  CONCURRENTLY on a small thread pool (``MXTPU_COMPILE_CACHE_THREADS``)
  instead of the old serial per-replica loops. Python tracing is
  serialized under one lock (tracing executes model code against
  shared blocks — the old serving ``_TRACE_LOCK`` discipline,
  centralized); XLA compiles run in parallel outside it. Entries that
  share a ``group`` token share ONE built jit callable, and jax's
  jaxpr cache then shares the TRACE across per-device lowerings — N
  identical replicas trace once and compile per device
  (``compile.lowering_shares{site}``).
* **Persistent on-disk executable cache** — with
  ``MXTPU_COMPILE_CACHE_DIR`` set, every AOT-compiled executable is
  serialized (jax AOT ``serialize_executable``) into a self-describing
  blob committed tmp+rename, with a best-effort ``manifest.json``
  index. A fresh process probes the digest before building: a hit
  deserializes in milliseconds with ZERO compiles. Every mismatch —
  truncated/corrupt blob, format/jax/backend version skew, key-repr
  collision — degrades to a silent recompile and counts into
  ``compile.disk.drops{reason}``; the cache can never crash a run and
  can never serve a stale-policy executable (the full canonical key
  repr is verified inside the blob, and policy/sharding/donation flips
  change the digest itself).

Degradation matrix, key anatomy, and the disk format live in
``docs/compile_cache.md``.
"""
from __future__ import annotations

import collections
import hashlib
import inspect
import json
import logging
import os
import pickle
import re
import tempfile
import threading
import time

from . import telemetry

__all__ = ["Key", "Entry", "WarmupEntry", "canonical_key", "device_token",
           "source_token", "instance_nonce", "cache_dir", "cache_entries",
           "cache_threads", "get", "get_or_build", "warmup", "drop",
           "stats", "reset", "trace_lock", "digest_of", "disk_path_of",
           "concrete_args", "manifest"]

_log = logging.getLogger("mxtpu.compile_service")

# disk blob format version: bump on any layout change — old blobs then
# drop as version_mismatch and silently recompile
FORMAT_VERSION = 1
_MAGIC = "MXTPU-CC"

_LOCK = threading.Lock()            # store/group/inflight structural ops
_STORE = collections.OrderedDict()  # Key -> Entry (LRU: newest at end)
_GROUPS = collections.OrderedDict()  # group token -> (jit_fn, meta)
_GROUP_BOUND = 64                   # groups hold build closures: keep small
_INFLIGHT = {}                      # Key -> threading.Event

# ONE python-trace lock for the whole process: tracing executes model
# code (shared gluon blocks, deferred init, format cells) that is not
# safe to run concurrently — the serving-layer ``_TRACE_LOCK`` made
# first-class. XLA compilation happens OUTSIDE it, in parallel.
_TRACE_LOCK = threading.RLock()

_HEX_ADDR = re.compile(r"0x[0-9a-fA-F]+")


class Key(collections.namedtuple(
        "Key", ["site", "fn_id", "signature", "policy", "sharding",
                "donation", "device", "nonce"])):
    """The canonical compile-cache key. ``site`` names the retrace
    watchdog site; ``fn_id`` is a STABLE function identity (symbol
    JSON digest, block repr + forward source hash, optimizer class —
    never ``id()``); ``signature`` holds the abstract shapes/dtypes
    and per-site static config; ``policy`` is ``registry.policy_key``;
    ``sharding`` the MeshPlan fingerprint / per-buffer sharding
    tokens; ``donation`` the donate-argnums discipline; ``device`` the
    placement token. ``nonce`` isolates live instances in memory and
    is excluded from the on-disk digest."""

    __slots__ = ()

    def digest_material(self):
        """The stable string the disk digest hashes: everything except
        ``site`` (reporting-only — a replaced replica r9 on device 2
        may reuse retired r2's device-2 artifact) and ``nonce``
        (process-local)."""
        return "|".join((
            "fmt%d" % FORMAT_VERSION, self.fn_id or "",
            repr(self.signature), repr(self.policy), repr(self.sharding),
            repr(self.donation), self.device or ""))


Entry = collections.namedtuple("Entry", ["fn", "meta", "origin"])

# warmup declaration: key + build + example args (concrete or
# ShapeDtypeStruct — anything ``jit.lower`` accepts); ``group`` tokens
# mark entries whose lowering is identical up to device placement
WarmupEntry = collections.namedtuple(
    "WarmupEntry", ["key", "build", "example_args", "provenance", "group"],
    defaults=(None, None))


# ------------------------------------------------------------------ levers
def cache_dir():
    """``MXTPU_COMPILE_CACHE_DIR``: the persistent executable cache home
    (empty/unset = disk cache off)."""
    return os.environ.get("MXTPU_COMPILE_CACHE_DIR") or None


# jax's own persistent compilation cache rides along under <dir>/xla: it
# catches the compiles the service cannot key (deferred-init eager ops,
# initializers, incidental library jits) so a warm dir accelerates the
# WHOLE process start, not just the ten declared sites
_XLA_CACHE = {"configured": None}


def _ensure_xla_cache():
    d = cache_dir()
    if _XLA_CACHE["configured"] == d:   # unlocked fast path (hot sites
        return                          # call this per dispatch miss)
    with _LOCK:
        if _XLA_CACHE["configured"] == d:
            return
        _XLA_CACHE["configured"] = d
    try:
        import jax
        if d is None:
            jax.config.update("jax_compilation_cache_dir", None)
            return
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(d, "xla"))
        # the eager tier is all sub-second compiles — persist them too
        # (the dir is opt-in; without these the thresholds skip exactly
        # the compiles a cold process start is made of)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:  # noqa: BLE001 — acceleration only, never fatal
        pass


def cache_entries():
    """``MXTPU_COMPILE_CACHE_ENTRIES``: LRU bound on in-memory
    executables (default 1024; ``<= 0`` = unbounded)."""
    try:
        return int(os.environ.get("MXTPU_COMPILE_CACHE_ENTRIES", "1024"))
    except ValueError:
        return 1024


def cache_threads():
    """``MXTPU_COMPILE_CACHE_THREADS``: AOT warmup pool width (default
    ``min(4, cpu_count)``)."""
    try:
        n = int(os.environ.get("MXTPU_COMPILE_CACHE_THREADS", "0"))
    except ValueError:
        n = 0
    if n > 0:
        return n
    return max(1, min(4, os.cpu_count() or 1))


# -------------------------------------------------------------- key helpers
def canonical_key(site, fn_id, signature, policy=None, sharding=None,
                  donation=None, device=None, nonce=None):
    """Build the canonical :class:`Key`. Every component must be
    hashable and have a process-stable ``repr`` (tuples of
    str/int/bool — never live objects)."""
    return Key(site, fn_id, signature, policy, sharding, donation,
               device, nonce)


def _local_ordinal(d):
    """A device's ordinal within its OWN process's device set. Global ids
    bake the host rank into the token (host 1's only CPU device is global
    id 1), which would stop a replacement host from warm-starting off the
    blobs an identical peer spilled; process-local ordinals make
    equivalent per-host placements token-equal across hosts while a
    device-2 mesh still differs from a device-0 mesh on one host."""
    import jax
    try:
        peers = [x.id for x in jax.devices()
                 if x.process_index == d.process_index]
        return int(d.id) - min(peers)
    except Exception:  # noqa: BLE001 — exotic backend: raw id is a token too
        return int(d.id)


def device_token(device=None, mesh=None):
    """Stable placement token: backend kind + device ordinal (or the
    mesh's device-ordinal tuple). Executables are device-pinned — the
    token keeps a device-2 artifact from being offered to a device-0
    restore — but pinned per host, not per fleet (see
    :func:`_local_ordinal`)."""
    import jax
    backend = jax.default_backend()
    if mesh is not None:
        ids = tuple(_local_ordinal(d) for d in mesh.devices.flat)
        return "%s:mesh%s" % (backend, ids)
    if device is not None:
        return "%s:d%d" % (backend, _local_ordinal(device))
    return "%s:default" % backend


def source_token(obj):
    """Best-effort code-identity digest: sha1 of ``inspect.getsource``
    (falls back to an address-stripped repr). Folded into ``fn_id`` so
    an edited model/kernel across restarts misses the disk cache
    instead of replaying stale code."""
    try:
        src = inspect.getsource(obj)
    except (OSError, TypeError):
        src = _HEX_ADDR.sub("0x", repr(obj))
    return hashlib.sha1(src.encode("utf-8", "replace")).hexdigest()[:16]


_NONCES = {"next": 0}


def instance_nonce(obj):
    """Process-local instance isolation token (in-memory key only —
    never part of the disk digest). Monotonic and cached on the
    instance: a raw ``id()`` would recycle after GC and let a fresh
    instance silently inherit a dead one's executables."""
    tok = getattr(obj, "_csvc_nonce", None)
    if tok is None:
        with _LOCK:
            _NONCES["next"] += 1
            tok = "i%d" % _NONCES["next"]
        try:
            obj._csvc_nonce = tok
        except (AttributeError, TypeError):  # __slots__ etc.: degrade to id
            tok = "i%x" % id(obj)
    return tok


def digest_of(key):
    """The on-disk digest for ``key`` (site/nonce excluded)."""
    return hashlib.sha256(
        key.digest_material().encode("utf-8", "replace")).hexdigest()[:32]


def disk_path_of(key, root=None):
    root = root or cache_dir()
    if not root:
        return None
    return os.path.join(root, digest_of(key) + ".mxc")


def concrete_args(args):
    """``args`` when every leaf is concrete (lowerable), else None — a
    site invoked UNDER an outer trace (tracer inputs) must not hand the
    service tracers as example args: the AOT path would try to lower
    against values owned by someone else's trace."""
    import jax

    tracer = jax.core.Tracer
    for leaf in jax.tree_util.tree_leaves(args):
        if isinstance(leaf, tracer):
            return None
    return args


def trace_lock():
    """The process-wide python-trace lock (reentrant). Sites that trace
    outside the service (first dispatch of a cold plain-jit entry)
    serialize here — the centralized successor of the serving-layer
    ``_TRACE_LOCK``."""
    return _TRACE_LOCK


# ------------------------------------------------------------------- store
def _lookup_locked(key):
    e = _STORE.get(key)
    if e is not None:
        _STORE.move_to_end(key)
    return e


def _store_locked(key, entry):
    _STORE[key] = entry
    _STORE.move_to_end(key)
    bound = cache_entries()
    while bound > 0 and len(_STORE) > bound:
        old_key, _old = _STORE.popitem(last=False)
        telemetry.inc("compile.evictions", tag=old_key.site)
    telemetry.gauge("compile.service.entries", len(_STORE))


def get(key):
    """In-memory lookup only (refreshes LRU position)."""
    with _LOCK:
        return _lookup_locked(key)


def drop(site=None, fn_id=None, nonce=None):
    """Evict matching entries (and group artifacts when a ``fn_id``
    filter is given) WITHOUT counting ``compile.evictions`` — this is
    the explicit invalidation path (test resets, instance teardown),
    not cache pressure. Returns the number dropped."""
    with _LOCK:
        victims = [k for k in _STORE
                   if (site is None or k.site == site
                       or k.site.startswith(site + "."))
                   and (fn_id is None or k.fn_id == fn_id)
                   and (nonce is None or k.nonce == nonce)]
        for k in victims:
            del _STORE[k]
        if fn_id is not None or site is None:
            for g in [g for g in _GROUPS
                      if fn_id is None or (isinstance(g, tuple)
                                           and fn_id in g)]:
                del _GROUPS[g]
        telemetry.gauge("compile.service.entries", len(_STORE))
    return len(victims)


def reset():
    """Drop every in-memory entry, group artifact, and in-flight marker
    (tests). The disk cache is untouched."""
    with _LOCK:
        _STORE.clear()
        _GROUPS.clear()
        _INFLIGHT.clear()
        telemetry.gauge("compile.service.entries", 0)


def stats():
    with _LOCK:
        per_site = {}
        for k in _STORE:
            per_site[k.site] = per_site.get(k.site, 0) + 1
    return {"entries": sum(per_site.values()), "per_site": per_site,
            "groups": len(_GROUPS), "disk_dir": cache_dir(),
            "bound": cache_entries()}


# ------------------------------------------------------------- disk cache
def _env_material():
    """The environment fingerprint a blob must match to load: blob
    format, jax/jaxlib versions (serialized executables are not
    ABI-stable across them), and the backend kind."""
    import jax
    import jaxlib
    return {"format": FORMAT_VERSION, "jax": jax.__version__,
            "jaxlib": jaxlib.__version__,
            "backend": jax.default_backend()}


def _drop_blob(reason, site, path=None):
    telemetry.inc("compile.disk.drops", tag=reason)
    _log.debug("compile disk cache: dropped %s (%s)", path, reason)
    return None


def _marker_path(path):
    return path + ".unloadable"


def _known_unloadable(path):
    """True when a previous process marked this digest as
    non-restorable in THIS environment (some backends — XLA CPU with
    certain fusions — serialize executables whose generated-code
    symbols do not survive deserialization). The marker stops every
    later restart from re-paying the failed load AND the re-spill; an
    environment change invalidates it."""
    try:
        with open(_marker_path(path), "r", encoding="utf-8") as f:
            return json.load(f) == _env_material()
    except (OSError, ValueError):
        return False


def _mark_unloadable(path):
    try:
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path),
                                   suffix=".tmp")
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            json.dump(_env_material(), f)
        os.replace(tmp, _marker_path(path))
    except Exception:  # noqa: BLE001 — advisory only
        pass


def _device_span(compiled):
    """Distinct device count an executable is bound to, read off its
    input shardings (0 when introspection fails — treated as unknown)."""
    try:
        import jax
        ins, _ = compiled.input_shardings
        devs = set()
        for s in jax.tree_util.tree_leaves(ins):
            devs |= set(getattr(s, "device_set", ()))
        return len(devs)
    except Exception:  # noqa: BLE001 — stages API moved / no inputs
        return 0


def _cpu_serialization_unsound(num_devices):
    """XLA:CPU cannot round-trip multi-device executables: the
    generated fusion symbols either fail to resolve at load ("Symbols
    not found" — the loud case ``_known_unloadable`` already handles)
    or, worse, resolve to the WRONG kernels and the deserialized
    executable silently computes garbage (measured: an sgd-momentum
    fused update over a 2-device mesh returns ~2x-scaled momentum
    terms after a round-trip; the same build on 1 device is bit-exact).
    Single-device CPU blobs are sound and stay served; multi-device
    ones are refused at write AND load. TPU/GPU are unaffected."""
    import jax
    return jax.default_backend() == "cpu" and num_devices != 1


def _disk_load(key):
    """Probe the disk cache for ``key``. Returns an :class:`Entry` or
    None. EVERY failure mode degrades to None (recompile) with a
    ``compile.disk.drops{reason}`` count — never an exception, never a
    stale executable (the blob's stored key material is compared
    against the probe's)."""
    path = disk_path_of(key)
    if path is None:
        return None
    if not os.path.exists(path):
        telemetry.inc("compile.disk.misses", tag=key.site)
        return None
    if _known_unloadable(path):
        return _drop_blob("unloadable", key.site, path)
    try:
        with open(path, "rb") as f:
            rec = pickle.load(f)
    except Exception:  # noqa: BLE001 — truncated/garbage blob
        return _drop_blob("corrupt", key.site, path)
    if not isinstance(rec, dict) or rec.get("magic") != _MAGIC:
        return _drop_blob("corrupt", key.site, path)
    if rec.get("env") != _env_material():
        return _drop_blob("version_mismatch", key.site, path)
    if rec.get("key") != key.digest_material():
        # digest collision or a forged rename: the executable was built
        # for a DIFFERENT canonical key (other policy/sharding/donation)
        return _drop_blob("key_mismatch", key.site, path)
    if _cpu_serialization_unsound(rec.get("devices") or 0):
        # a pre-guard blob (no recorded span) or a multi-device one on
        # XLA:CPU: deserializing risks SILENT numeric corruption, not
        # just a load error — never serve it (see the guard's docstring)
        return _drop_blob("cpu_multidevice", key.site, path)
    try:
        from jax.experimental import serialize_executable as se
        compiled = se.deserialize_and_load(
            rec["payload"], rec["in_tree"], rec["out_tree"])
    except Exception:  # noqa: BLE001 — topology/backends moved under us,
        # or a backend whose serialized form cannot restore (marked so
        # later restarts skip straight to the recompile)
        _mark_unloadable(path)
        return _drop_blob("load_error", key.site, path)
    from . import xprof
    prov = dict(rec.get("provenance") or {})
    prov["from_disk"] = True
    # ledger-only registration: a disk load is NOT a compile — the
    # retrace watchdog must stay silent (zero-compile warm start is the
    # acceptance pin), but the executable's cost/memory analyses and
    # call counts still feed the observatory
    fn = xprof.watch(key.site, compiled, prov)
    telemetry.inc("compile.disk.hits", tag=key.site)
    meta = rec.get("meta")
    return Entry(fn, dict(meta) if isinstance(meta, dict) else meta,
                 "disk")


def _disk_write(key, compiled, meta, provenance, compile_s):
    """Serialize ``compiled`` under ``key``'s digest, committed
    tmp+rename so a concurrent writer or a mid-write crash can never
    leave a half-blob under the final name. Serialization failures
    count and degrade — the in-memory entry is already good."""
    root = cache_dir()
    if not root:
        return False
    path = disk_path_of(key, root)
    if _known_unloadable(path):
        # a rewrite cannot help: this digest's executables do not
        # restore in this environment — skip BEFORE paying the
        # serialization (that cost per restart is the exact churn the
        # marker exists to stop)
        return False
    span = _device_span(compiled)
    if _cpu_serialization_unsound(span):
        # refuse BEFORE paying serialization: the blob would load as
        # garbage (or not at all) on every warm start
        telemetry.inc("compile.disk.drops", tag="cpu_multidevice")
        return False
    try:
        from jax.experimental import serialize_executable as se
        payload, in_tree, out_tree = se.serialize(compiled)
        rec = {"magic": _MAGIC, "env": _env_material(),
               "key": key.digest_material(), "site": key.site,
               "devices": span,
               "payload": payload, "in_tree": in_tree,
               "out_tree": out_tree, "meta": meta,
               "provenance": _json_safe(provenance),
               "compile_s": compile_s, "created": time.time()}
        blob = pickle.dumps(rec)
    except Exception:  # noqa: BLE001 — backend without AOT serialization
        telemetry.inc("compile.disk.drops", tag="serialize")
        return False
    try:
        os.makedirs(root, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=root, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
    except Exception:  # noqa: BLE001 — disk full / perms / races
        telemetry.inc("compile.disk.drops", tag="io")
        return False
    telemetry.inc("compile.disk.writes", tag=key.site)
    _manifest_note(root, digest_of(key), key, len(blob))
    return True


def _json_safe(v):
    try:
        json.dumps(v)
        return v
    except (TypeError, ValueError):
        return repr(v)


def _manifest_note(root, digest, key, nbytes):
    """Best-effort ``manifest.json`` index row (version + key anatomy
    per digest). The manifest is for humans and reports — per-entry
    blobs are self-describing and authoritative, so a lost
    read-modify-write race here costs nothing but a stale index
    line."""
    path = os.path.join(root, "manifest.json")
    try:
        try:
            with open(path, "r", encoding="utf-8") as f:
                man = json.load(f)
        except (OSError, ValueError):
            man = {}
        if not isinstance(man, dict) or "entries" not in man:
            man = {"format": FORMAT_VERSION, "entries": {}}
        man["format"] = FORMAT_VERSION
        man["env"] = _env_material()
        man["entries"][digest] = {
            "site": key.site, "fn_id": key.fn_id,
            "key": key.digest_material(), "bytes": nbytes,
            "created": time.time()}
        fd, tmp = tempfile.mkstemp(dir=root, suffix=".tmp")
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            json.dump(man, f, indent=1, default=repr)
        os.replace(tmp, path)
    except Exception:  # noqa: BLE001 — advisory only
        pass


def manifest(root=None):
    """The on-disk manifest dict (empty when absent/unreadable)."""
    root = root or cache_dir()
    if not root:
        return {}
    try:
        with open(os.path.join(root, "manifest.json"),
                  "r", encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


# -------------------------------------------------------------- build path
def _group_jit(group, build, site):
    """One built jit callable per lowering group: entries differing only
    in device placement reuse the SAME python callable, so jax's jaxpr
    cache shares the trace across their per-device lowerings."""
    with _LOCK:
        hit = _GROUPS.get(group)
        if hit is not None:
            _GROUPS.move_to_end(group)
    if hit is not None:
        telemetry.inc("compile.lowering_shares", tag=site)
        return hit
    with _TRACE_LOCK:
        # re-check under the trace lock: a concurrent group member may
        # have built while we waited
        with _LOCK:
            hit = _GROUPS.get(group)
        if hit is not None:
            telemetry.inc("compile.lowering_shares", tag=site)
            return hit
        built = _split_build(build())
        with _LOCK:
            _GROUPS[group] = built
            while len(_GROUPS) > _GROUP_BOUND:
                _GROUPS.popitem(last=False)
        return built


def _split_build(raw):
    """``build()`` returns the jit callable, or ``(jit, meta)`` where
    ``meta`` is the site's picklable side-cell (output formats etc.) —
    persisted next to the executable so a disk-warm process needs no
    trace to reconstruct it."""
    if isinstance(raw, tuple):
        jit_fn, meta = raw
        return jit_fn, meta
    return raw, None


def _report(site, provenance, compiled, compile_s, companion):
    """The one watchdog/ledger handoff: companions (a forward's paired
    backward sharing the site's single retrace count) register
    ledger-only; everything else reports the compile."""
    from . import xprof
    if companion:
        return xprof.watch(site, compiled, provenance,
                           compile_s=compile_s)
    return telemetry.record_retrace(site, provenance, compiled=compiled,
                                    compile_s=compile_s)


def _build_entry(key, build, provenance, example_args, aot, companion,
                 group):
    if callable(provenance):
        # lazy provenance: hot sites hand a thunk so the dict is only
        # materialized on a real miss, never on the per-call hit path
        provenance = provenance()
    if group is not None:
        jit_fn, meta = _group_jit(group, build, key.site)
    else:
        jit_fn, meta = _split_build(build())
    do_aot = aot if aot is not None \
        else (example_args is not None and cache_dir() is not None)
    if do_aot and example_args is not None:
        t0 = time.perf_counter()
        with _TRACE_LOCK:
            # python trace serialized; the jaxpr cache makes a grouped
            # re-lower at a new device placement trace-free
            lowered = jit_fn.lower(*example_args)
        compiled = lowered.compile()   # parallel-safe: outside the lock
        dt = time.perf_counter() - t0
        fn = _report(key.site, provenance, compiled, dt, companion)
        _disk_write(key, compiled, meta, provenance, dt)
        return Entry(fn if fn is not None else compiled, meta, "built")
    fn = _report(key.site, provenance, jit_fn, None, companion)
    return Entry(fn if fn is not None else jit_fn, meta, "built")


def get_or_build(key, build, provenance=None, example_args=None,
                 aot=None, companion=False, group=None):
    """THE cache front door. Resolution order: in-memory LRU store →
    on-disk executable cache (zero compiles) → ``build()`` (one
    reported compile). ``example_args`` (anything ``jit.lower``
    accepts) enables the AOT path: explicit lower+compile — required
    for disk spill, and the path :func:`warmup` drives concurrently.
    Without it (or with the disk cache off and ``aot`` unset) the
    freshly-built plain jit is returned exactly as the per-site caches
    did — first dispatch traces and compiles.

    Concurrent misses on the same key build once: losers wait on the
    winner's in-flight event and adopt its entry."""
    _ensure_xla_cache()
    with _LOCK:
        e = _lookup_locked(key)
    if e is not None:
        return e
    registered = False
    while True:
        with _LOCK:
            e = _lookup_locked(key)
            if e is not None:
                return e
            waiter = _INFLIGHT.get(key)
            if waiter is None:
                _INFLIGHT[key] = threading.Event()
                registered = True
                break
        if getattr(_TRACE_LOCK, "_is_owned", lambda: False)():
            # lock-order-inversion guard: we hold the process trace
            # lock (a site resolving keys mid-trace/warmup) while the
            # in-flight builder may be BLOCKED waiting for it inside
            # its AOT lower — waiting on its event here would deadlock.
            # Build our own copy instead (the store write is
            # idempotent; a rare duplicate compile beats a wedge).
            break
        waiter.wait()
    try:
        entry = _disk_load(key)
        if entry is None:
            entry = _build_entry(key, build, provenance, example_args,
                                 aot, companion, group)
        with _LOCK:
            _store_locked(key, entry)
        return entry
    finally:
        if registered:
            with _LOCK:
                ev = _INFLIGHT.pop(key, None)
            if ev is not None:
                ev.set()


# ------------------------------------------------------------------ warmup
def warmup(entries, threads=None):
    """AOT-warm a declared entry list concurrently: every entry resolves
    through :func:`get_or_build` with the AOT path forced, so each one
    lands as disk hit (zero compiles), a shared-lowering build (trace
    once per ``group``, compile per device), or a plain reported
    compile. Returns a summary dict; the FIRST entry failure re-raises
    after all entries settle (warmup must not half-succeed
    silently)."""
    entries = list(entries)
    t0 = time.perf_counter()
    # preload tuned Pallas block plans BEFORE any entry traces: warmup is
    # how ReplicaSet/Trainer ship executables fleet-wide, and the traced
    # programs must bake the plans a serving process will run under
    # (no-op unless MXTPU_AUTOTUNE=1)
    try:
        from .ops.pallas import autotune as _autotune
        _autotune.ensure_loaded()
    except Exception:  # noqa: BLE001 — plan preload must never block warmup
        pass
    summary = {"entries": len(entries), "built": 0, "disk": 0,
               "cached": 0, "errors": 0, "wall_s": 0.0}
    if not entries:
        return summary
    n = threads or cache_threads()
    first_err = None

    def one(e):
        pre = get(e.key)
        entry = get_or_build(e.key, e.build, provenance=e.provenance,
                             example_args=e.example_args, aot=True,
                             group=e.group)
        return "cached" if pre is not None else entry.origin

    if len(entries) == 1 or n <= 1:
        results = map(_catching(one), entries)   # no pool spin-up
    else:
        from concurrent.futures import ThreadPoolExecutor
        pool = ThreadPoolExecutor(
            max_workers=max(1, min(n, len(entries))),
            thread_name_prefix="mxtpu-compile")
        results = pool.map(_catching(one), entries)
    for res in results:
        if isinstance(res, BaseException):
            summary["errors"] += 1
            first_err = first_err or res
        elif res == "disk":
            summary["disk"] += 1
        elif res == "cached":
            summary["cached"] += 1
        else:
            summary["built"] += 1
    if len(entries) > 1 and n > 1:
        pool.shutdown(wait=True)
    summary["wall_s"] = time.perf_counter() - t0
    telemetry.observe("compile.warmup_s", summary["wall_s"])
    if first_err is not None:
        raise first_err
    return summary


def _catching(fn):
    def run(e):
        try:
            return fn(e)
        except BaseException as exc:  # noqa: BLE001 — collected, re-raised
            return exc
    return run


# configure the riding XLA cache at import when the dir is already set:
# a fresh process's deferred-init eager compiles happen BEFORE any
# service call, and they are exactly what a warm start wants cached
_ensure_xla_cache()
