"""Python guts of the C ABI (src/c_api/c_api.cc delegates here).

The reference's src/c_api/*.cc marshals C arguments into its C++ engine;
the TPU-native runtime's orchestrator is this package, so the C layer
marshals into these functions instead. Every function takes/returns only
plain C-friendly values (bytes, tuples, opaque objects used as handles).

Env: set ``MXTPU_JAX_PLATFORMS`` (e.g. ``cpu``) before the first call to pin
the jax platform from a C host — the axon sitecustomize would otherwise
override ``JAX_PLATFORMS``.
"""
from __future__ import annotations

import ast
import os

_PLATFORM_PIN = os.environ.get("MXTPU_JAX_PLATFORMS")
if _PLATFORM_PIN:
    import jax

    jax.config.update("jax_platforms", _PLATFORM_PIN)

import numpy as np  # noqa: E402

from . import ndarray as nd  # noqa: E402
from . import ops  # noqa: E402
from .base import MXNetError  # noqa: E402
from .model import load_checkpoint  # noqa: E402
from .ndarray import NDArray  # noqa: E402


def runtime_init(platform=None):
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
    import jax

    jax.devices()  # force backend bring-up so later calls are fast
    return True


def ndarray_from_blob(data: bytes, shape: tuple) -> NDArray:
    arr = np.frombuffer(data, dtype=np.float32).reshape(shape)
    return nd.array(arr)


def ndarray_shape(handle: NDArray) -> tuple:
    return tuple(int(d) for d in handle.shape)


def ndarray_to_bytes(handle: NDArray) -> bytes:
    return np.ascontiguousarray(handle.asnumpy().astype(np.float32)).tobytes()


def _parse_attr(v: str):
    try:
        return ast.literal_eval(v)
    except (ValueError, SyntaxError):
        return v


def imperative_invoke(name: str, inputs: list, attrs: dict) -> list:
    kwargs = {k: _parse_attr(v) for k, v in attrs.items()}
    out = ops.invoke(name, *inputs, **kwargs)
    if isinstance(out, (list, tuple)):
        return list(out)
    return [out]


class _Predictor:
    """C-predict-API state (ref: src/c_api/c_predict_api.cc:59-213 — the
    reference binds a static executor; here bind = jit-compiled Symbol
    executor over the same checkpoint format)."""

    def __init__(self, prefix, epoch, input_name, shape):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        if symbol is None:
            raise MXNetError("no symbol file for prefix %r" % prefix)
        self.input_name = input_name
        self.shape = tuple(int(d) for d in shape)
        args = dict(arg_params)
        args[input_name] = nd.zeros(self.shape)
        self.executor = symbol.bind(args=args, aux_states=aux_params,
                                    grad_req="null")
        self._input = None
        self.outputs = []

    def set_input(self, data: bytes):
        arr = np.frombuffer(data, dtype=np.float32).reshape(self.shape)
        self._input = nd.array(arr)

    def forward(self):
        kwargs = {}
        if self._input is not None:
            kwargs[self.input_name] = self._input
        self.outputs = self.executor.forward(is_train=False, **kwargs)


def pred_create(prefix, epoch, input_name, shape) -> _Predictor:
    return _Predictor(prefix, epoch, input_name, shape)


def pred_set_input(pred: _Predictor, data: bytes):
    pred.set_input(data)
    return True


def pred_forward(pred: _Predictor):
    pred.forward()
    return True


def pred_output_shape(pred: _Predictor, index: int) -> tuple:
    return tuple(int(d) for d in pred.outputs[index].shape)


def pred_output_bytes(pred: _Predictor, index: int) -> bytes:
    return ndarray_to_bytes(pred.outputs[index])


# ---- autograd (ref: c_api_ndarray.cc MXAutogradSetIsRecording /
# MarkVariables / Backward; SURVEY §2.1 imperative+autograd) ----

def autograd_set_recording(flag: int) -> int:
    from . import autograd
    return int(autograd.set_recording(bool(flag)))


def autograd_set_training(flag: int) -> int:
    from . import autograd
    return int(autograd.set_training(bool(flag)))


def ndarray_attach_grad(handle: NDArray) -> None:
    handle.attach_grad()


def ndarray_grad(handle: NDArray) -> NDArray:
    g = handle.grad
    if g is None:
        raise MXNetError("no gradient: attach_grad() was not called or "
                         "backward has not run")
    return g


def ndarray_backward(handle: NDArray, retain_graph: int) -> None:
    handle.backward(retain_graph=bool(retain_graph))


# ---- KVStore (ref: c_api.cc MXKVStoreCreate / Init / Push / Pull /
# SetOptimizer; SURVEY §2.3) ----

def kvstore_create(kind: str):
    from .kvstore import create
    return create(kind or "local")


def kvstore_init(kv, keys: tuple, vals: tuple) -> None:
    kv.init(list(keys), list(vals))


def kvstore_push(kv, keys: tuple, vals: tuple, priority: int) -> None:
    kv.push(list(keys), list(vals), priority=priority)


def kvstore_pull(kv, keys: tuple, outs: tuple, priority: int) -> None:
    kv.pull(list(keys), out=list(outs), priority=priority)


def kvstore_set_optimizer(kv, name: str, attrs: dict) -> None:
    from .optimizer import Optimizer
    kv.set_optimizer(Optimizer.create_optimizer(
        name, **{k: _parse_attr(v) for k, v in attrs.items()}))


# ---- Symbol + Executor (ref: c_api_symbolic.cc MXSymbolCreateVariable /
# CreateAtomicSymbol+Compose / ListArguments / CreateFromJSON;
# c_api_executor.cc MXExecutorBindEX / Forward / Backward / Outputs) ----

def symbol_create_variable(name: str):
    from .symbol import var
    return var(name)


def symbol_create_from_json(json_str: str):
    from .symbol import load_json
    return load_json(json_str)


def symbol_create_from_file(path: str):
    from .symbol import load as sym_load
    return sym_load(path)


def symbol_invoke(op_name: str, attrs: dict, name: str, inputs: tuple):
    """CreateAtomicSymbol + Compose in one call (the reference splits
    these only because nnvm composes lazily — ref c_api_symbolic.cc
    MXSymbolCreateAtomicSymbol + MXSymbolCompose)."""
    from . import symbol as sym_mod
    fn = getattr(sym_mod, op_name, None)
    if fn is None:
        raise MXNetError("unknown symbolic operator %r" % op_name)
    kwargs = {k: _parse_attr(v) for k, v in attrs.items()}
    if name:
        kwargs["name"] = name
    return fn(*inputs, **kwargs)


def symbol_list_arguments(sym) -> tuple:
    return tuple(sym.list_arguments())


def symbol_list_outputs(sym) -> tuple:
    return tuple(sym.list_outputs())


def symbol_tojson(sym) -> str:
    return sym.tojson()


def executor_bind(sym, arg_names: tuple, arg_vals: tuple,
                  grad_req: str):
    args = dict(zip(arg_names, arg_vals))
    return sym.bind(None, args, grad_req=grad_req or "write")


def executor_forward(ex, is_train: int) -> tuple:
    return tuple(ex.forward(is_train=bool(is_train)))


def executor_backward(ex) -> None:
    ex.backward()


def executor_outputs(ex) -> tuple:
    return tuple(ex.outputs)


def executor_arg_grad(ex, name: str) -> NDArray:
    grads = ex.grad_dict if hasattr(ex, "grad_dict") else None
    if grads is None or name not in grads or grads[name] is None:
        raise MXNetError("no gradient for argument %r" % name)
    return grads[name]


# ---- dtype-aware create / save / load (ref: MXNDArrayCreateEx,
# MXNDArraySave, MXNDArrayLoad over src/c_api/c_api.cc:1035-1120) ----

_DTYPE_FLAGS = {0: "float32", 1: "float64", 2: "float16", 3: "uint8",
                4: "int32", 5: "int8", 6: "int64"}
_FLAGS_BY_NAME = {v: k for k, v in _DTYPE_FLAGS.items()}


def ndarray_from_blob_ex(data: bytes, dtype_flag: int, shape: tuple):
    name = _DTYPE_FLAGS.get(int(dtype_flag))
    if name is None:
        raise MXNetError("unknown mshadow dtype flag %d" % dtype_flag)
    a = np.frombuffer(data, dtype=np.dtype(name)).reshape(shape)
    return nd.array(a, dtype=name)


def ndarray_dtype_flag(handle: NDArray) -> int:
    name = str(handle.dtype)
    if name == "bfloat16":  # no reference flag; surfaced as its f32 carrier
        return 0
    flag = _FLAGS_BY_NAME.get(name)
    if flag is None:
        raise MXNetError("dtype %s has no mshadow flag" % name)
    return flag


def ndarray_save(fname: str, handles: tuple, names: tuple) -> None:
    from .ndarray.utils import save as nd_save
    if names:
        if len(set(names)) != len(names):
            # the dict-keyed writer would silently drop all but the last
            # duplicate; refuse loudly instead (the reference would write
            # both records, which this engine's named files cannot)
            raise MXNetError("duplicate keys in NDArray save")
        nd_save(fname, dict(zip(names, handles)))
    else:
        nd_save(fname, list(handles))


def ndarray_load(fname: str):
    from .ndarray.utils import load as nd_load
    out = nd_load(fname)
    if isinstance(out, dict):
        return tuple(out.values()), tuple(out.keys())
    return tuple(out), ()


# ---- introspection / sync (ref: MXGetVersion, MXListAllOpNames,
# MXNDArrayWaitAll) ----

def get_version() -> int:
    """Reference packs MAJOR*10000 + MINOR*100 + PATCH (c_api.cc)."""
    import re
    from .libinfo import __version__
    parts = (__version__.split(".") + ["0", "0"])[:3]
    nums = []
    for part in parts:
        m = re.match(r"\d+", part)  # "0rc1" -> 0 (pre-release suffixes)
        nums.append(int(m.group()) if m else 0)
    return nums[0] * 10000 + nums[1] * 100 + nums[2]


def list_all_op_names() -> tuple:
    from .ops.registry import list_ops
    return tuple(list_ops())


def ndarray_wait_all() -> None:
    # NOT ndarray.waitall(), which swallows: the C contract is that
    # deferred async errors SURFACE here (-1 + MXTPUGetLastError), the
    # reference's MXNDArrayWaitAll semantics
    import jax
    jax.effects_barrier()
