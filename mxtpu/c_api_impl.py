"""Python guts of the C ABI (src/c_api/c_api.cc delegates here).

The reference's src/c_api/*.cc marshals C arguments into its C++ engine;
the TPU-native runtime's orchestrator is this package, so the C layer
marshals into these functions instead. Every function takes/returns only
plain C-friendly values (bytes, tuples, opaque objects used as handles).

Env: set ``MXTPU_JAX_PLATFORMS`` (e.g. ``cpu``) before the first call to pin
the jax platform from a C host — the axon sitecustomize would otherwise
override ``JAX_PLATFORMS``.
"""
from __future__ import annotations

import ast
import os

_PLATFORM_PIN = os.environ.get("MXTPU_JAX_PLATFORMS")
if _PLATFORM_PIN:
    import jax

    jax.config.update("jax_platforms", _PLATFORM_PIN)

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from . import ndarray as nd  # noqa: E402
from . import ops  # noqa: E402
from .base import MXNetError  # noqa: E402
from .model import load_checkpoint  # noqa: E402
from .ndarray import NDArray  # noqa: E402


def runtime_init(platform=None):
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
    import jax

    jax.devices()  # force backend bring-up so later calls are fast
    return True


def ndarray_from_blob(data: bytes, shape: tuple) -> NDArray:
    arr = np.frombuffer(data, dtype=np.float32).reshape(shape)
    return nd.array(arr)


def ndarray_shape(handle: NDArray) -> tuple:
    return tuple(int(d) for d in handle.shape)


def ndarray_to_bytes(handle: NDArray) -> bytes:
    return np.ascontiguousarray(handle.asnumpy().astype(np.float32)).tobytes()


def _parse_attr(v: str):
    try:
        return ast.literal_eval(v)
    except (ValueError, SyntaxError):
        return v


def imperative_invoke(name: str, inputs: list, attrs: dict) -> list:
    kwargs = {k: _parse_attr(v) for k, v in attrs.items()}
    out = ops.invoke(name, *inputs, **kwargs)
    if isinstance(out, (list, tuple)):
        return list(out)
    return [out]


class _Predictor:
    """C-predict-API state (ref: src/c_api/c_predict_api.cc:59-213 — the
    reference binds a static executor; here bind = jit-compiled Symbol
    executor over the same checkpoint format)."""

    def __init__(self, prefix, epoch, input_name, shape):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        if symbol is None:
            raise MXNetError("no symbol file for prefix %r" % prefix)
        self.input_name = input_name
        self.shape = tuple(int(d) for d in shape)
        args = dict(arg_params)
        args[input_name] = nd.zeros(self.shape)
        self.executor = symbol.bind(args=args, aux_states=aux_params,
                                    grad_req="null")
        self._input = None
        self.outputs = []

    def set_input(self, data: bytes):
        arr = np.frombuffer(data, dtype=np.float32).reshape(self.shape)
        self._input = nd.array(arr)

    def forward(self):
        kwargs = {}
        if self._input is not None:
            kwargs[self.input_name] = self._input
        self.outputs = self.executor.forward(is_train=False, **kwargs)


def pred_create(prefix, epoch, input_name, shape) -> _Predictor:
    return _Predictor(prefix, epoch, input_name, shape)


def pred_set_input(pred: _Predictor, data: bytes):
    pred.set_input(data)
    return True


def pred_forward(pred: _Predictor):
    pred.forward()
    return True


def pred_output_shape(pred: _Predictor, index: int) -> tuple:
    return tuple(int(d) for d in pred.outputs[index].shape)


def pred_output_bytes(pred: _Predictor, index: int) -> bytes:
    return ndarray_to_bytes(pred.outputs[index])


# ---- autograd (ref: c_api_ndarray.cc MXAutogradSetIsRecording /
# MarkVariables / Backward; SURVEY §2.1 imperative+autograd) ----

def autograd_set_recording(flag: int) -> int:
    from . import autograd
    return int(autograd.set_recording(bool(flag)))


def autograd_set_training(flag: int) -> int:
    from . import autograd
    return int(autograd.set_training(bool(flag)))


def ndarray_attach_grad(handle: NDArray) -> None:
    handle.attach_grad()


def ndarray_grad(handle: NDArray) -> NDArray:
    g = handle.grad
    if g is None:
        raise MXNetError("no gradient: attach_grad() was not called or "
                         "backward has not run")
    return g


def ndarray_backward(handle: NDArray, retain_graph: int) -> None:
    handle.backward(retain_graph=bool(retain_graph))


# ---- KVStore (ref: c_api.cc MXKVStoreCreate / Init / Push / Pull /
# SetOptimizer; SURVEY §2.3) ----

def kvstore_create(kind: str):
    from .kvstore import create
    return create(kind or "local")


def kvstore_init(kv, keys: tuple, vals: tuple) -> None:
    kv.init(list(keys), list(vals))


def kvstore_push(kv, keys: tuple, vals: tuple, priority: int) -> None:
    kv.push(list(keys), list(vals), priority=priority)


def kvstore_pull(kv, keys: tuple, outs: tuple, priority: int) -> None:
    kv.pull(list(keys), out=list(outs), priority=priority)


def kvstore_set_optimizer(kv, name: str, attrs: dict) -> None:
    from .optimizer import Optimizer
    kv.set_optimizer(Optimizer.create_optimizer(
        name, **{k: _parse_attr(v) for k, v in attrs.items()}))


# ---- Symbol + Executor (ref: c_api_symbolic.cc MXSymbolCreateVariable /
# CreateAtomicSymbol+Compose / ListArguments / CreateFromJSON;
# c_api_executor.cc MXExecutorBindEX / Forward / Backward / Outputs) ----

def symbol_create_variable(name: str):
    from .symbol import var
    return var(name)


def symbol_create_from_json(json_str: str):
    from .symbol import load_json
    return load_json(json_str)


def symbol_create_from_file(path: str):
    from .symbol import load as sym_load
    return sym_load(path)


def symbol_invoke(op_name: str, attrs: dict, name: str, inputs: tuple):
    """CreateAtomicSymbol + Compose in one call (the reference splits
    these only because nnvm composes lazily — ref c_api_symbolic.cc
    MXSymbolCreateAtomicSymbol + MXSymbolCompose)."""
    from . import symbol as sym_mod
    fn = getattr(sym_mod, op_name, None)
    if fn is None:
        raise MXNetError("unknown symbolic operator %r" % op_name)
    kwargs = {k: _parse_attr(v) for k, v in attrs.items()}
    if name:
        kwargs["name"] = name
    return fn(*inputs, **kwargs)


def symbol_list_arguments(sym) -> tuple:
    return tuple(sym.list_arguments())


def symbol_list_outputs(sym) -> tuple:
    return tuple(sym.list_outputs())


def symbol_tojson(sym) -> str:
    return sym.tojson()


def executor_bind(sym, arg_names: tuple, arg_vals: tuple,
                  grad_req: str):
    args = dict(zip(arg_names, arg_vals))
    return sym.bind(None, args, grad_req=grad_req or "write")


def executor_forward(ex, is_train: int) -> tuple:
    return tuple(ex.forward(is_train=bool(is_train)))


def executor_backward(ex) -> None:
    ex.backward()


def executor_outputs(ex) -> tuple:
    return tuple(ex.outputs)


def executor_arg_grad(ex, name: str) -> NDArray:
    grads = ex.grad_dict if hasattr(ex, "grad_dict") else None
    if grads is None or name not in grads or grads[name] is None:
        raise MXNetError("no gradient for argument %r" % name)
    return grads[name]


# ---- dtype-aware create / save / load (ref: MXNDArrayCreateEx,
# MXNDArraySave, MXNDArrayLoad over src/c_api/c_api.cc:1035-1120) ----

_DTYPE_FLAGS = {0: "float32", 1: "float64", 2: "float16", 3: "uint8",
                4: "int32", 5: "int8", 6: "int64"}
_FLAGS_BY_NAME = {v: k for k, v in _DTYPE_FLAGS.items()}


def ndarray_from_blob_ex(data: bytes, dtype_flag: int, shape: tuple):
    name = _DTYPE_FLAGS.get(int(dtype_flag))
    if name is None:
        raise MXNetError("unknown mshadow dtype flag %d" % dtype_flag)
    a = np.frombuffer(data, dtype=np.dtype(name)).reshape(shape)
    return nd.array(a, dtype=name)


def ndarray_dtype_flag(handle: NDArray) -> int:
    name = str(handle.dtype)
    if name == "bfloat16":  # no reference flag; surfaced as its f32 carrier
        return 0
    flag = _FLAGS_BY_NAME.get(name)
    if flag is None:
        raise MXNetError("dtype %s has no mshadow flag" % name)
    return flag


def ndarray_save(fname: str, handles: tuple, names: tuple) -> None:
    from .ndarray.utils import save as nd_save
    if names:
        if len(set(names)) != len(names):
            # the dict-keyed writer would silently drop all but the last
            # duplicate; refuse loudly instead (the reference would write
            # both records, which this engine's named files cannot)
            raise MXNetError("duplicate keys in NDArray save")
        nd_save(fname, dict(zip(names, handles)))
    else:
        nd_save(fname, list(handles))


def ndarray_load(fname: str):
    from .ndarray.utils import load as nd_load
    out = nd_load(fname)
    if isinstance(out, dict):
        return tuple(out.values()), tuple(out.keys())
    return tuple(out), ()


# ---- introspection / sync (ref: MXGetVersion, MXListAllOpNames,
# MXNDArrayWaitAll) ----

def get_version() -> int:
    """Reference packs MAJOR*10000 + MINOR*100 + PATCH (c_api.cc)."""
    import re
    from .libinfo import __version__
    parts = (__version__.split(".") + ["0", "0"])[:3]
    nums = []
    for part in parts:
        m = re.match(r"\d+", part)  # "0rc1" -> 0 (pre-release suffixes)
        nums.append(int(m.group()) if m else 0)
    return nums[0] * 10000 + nums[1] * 100 + nums[2]


def list_all_op_names() -> tuple:
    from .ops.registry import list_ops
    return tuple(list_ops())


def ndarray_wait_all() -> None:
    # NOT ndarray.waitall(), which swallows: the C contract is that
    # deferred async errors SURFACE here (-1 + MXTPUGetLastError), the
    # reference's MXNDArrayWaitAll semantics
    import jax
    jax.effects_barrier()


# ---- DataIter surface (ref: MXListDataIters/MXDataIterCreateIter/
# MXDataIterNext/MXDataIterGetData..., src/c_api/c_api.cc MXDataIter*) ----

_DATA_ITERS = None


def _data_iter_registry():
    global _DATA_ITERS
    if _DATA_ITERS is None:
        from . import io as io_mod
        from .image import ImageIter
        _DATA_ITERS = {
            "NDArrayIter": io_mod.NDArrayIter,
            "CSVIter": io_mod.CSVIter,
            "LibSVMIter": io_mod.LibSVMIter,
            "ImageRecordIter": ImageIter,  # the reference's registered name
            "ImageIter": ImageIter,
        }
    return _DATA_ITERS


def list_data_iters() -> tuple:
    return tuple(sorted(_data_iter_registry()))


class _CIter:
    """Iterator handle: owns the iter + the current batch (the reference's
    MXDataIterNext caches the batch the Get* calls then read)."""

    def __init__(self, it):
        self.it = it
        self.batch = None


def data_iter_create(name: str, attrs: dict):
    cls = _data_iter_registry().get(name)
    if cls is None:
        raise MXNetError("unknown data iter %r (have: %s)"
                         % (name, ", ".join(sorted(_data_iter_registry()))))
    kwargs = {k: _parse_attr(v) for k, v in attrs.items()}
    return _CIter(cls(**kwargs))


def data_iter_before_first(handle: "_CIter") -> None:
    handle.it.reset()
    handle.batch = None


def data_iter_next(handle: "_CIter") -> int:
    try:
        handle.batch = handle.it.next()
        return 1
    except StopIteration:
        handle.batch = None
        return 0


def _require_batch(handle):
    if handle.batch is None:
        raise MXNetError("no current batch: call MXTPUDataIterNext first")
    return handle.batch


def data_iter_get_data(handle: "_CIter") -> NDArray:
    return _require_batch(handle).data[0]


def data_iter_get_label(handle: "_CIter") -> NDArray:
    return _require_batch(handle).label[0]


def data_iter_get_pad_num(handle: "_CIter") -> int:
    return int(_require_batch(handle).pad or 0)


def data_iter_get_index(handle: "_CIter") -> tuple:
    idx = _require_batch(handle).index
    return tuple(int(i) for i in idx) if idx is not None else ()


# ---- RecordIO surface (ref: MXRecordIOWriterCreate/WriteRecord/Tell,
# MXRecordIOReaderCreate/ReadRecord/Seek, c_api.cc) ----

def recordio_writer_create(path: str):
    from .recordio import MXRecordIO
    return MXRecordIO(path, "w")


def recordio_writer_write(w, data: bytes) -> None:
    w.write(data)


def recordio_writer_tell(w) -> int:
    return int(w.tell())


def recordio_reader_create(path: str):
    from .recordio import MXRecordIO
    return MXRecordIO(path, "r")


def recordio_reader_read(r):
    """(has_record, payload): a zero-length RECORD is (1, b"") — distinct
    from EOF (0, b""), which bare bytes could not express."""
    out = r.read()
    if out is None:
        return (0, b"")
    return (1, bytes(out))


def recordio_reader_seek(r, pos: int) -> None:
    r.seek(pos)


def recordio_reader_tell(r) -> int:
    return int(r.tell())


def recordio_close(h) -> None:
    h.close()


# ---- Symbol attributes / breadth (ref: MXSymbolSetAttr/GetAttr/ListAttr,
# MXSymbolListAuxiliaryStates, MXSymbolInferShape, MXSymbolSaveToFile) ----

def symbol_set_attr(sym, key: str, value: str) -> None:
    if len(sym._heads) != 1:
        raise MXNetError("set_attr needs a single-output symbol")
    sym._heads[0][0].attrs[key] = value


def symbol_get_attr(sym, key: str) -> str:
    v = sym.attr(key)
    if v is None:
        raise MXNetError("symbol has no attribute %r" % key)
    return str(v)


def symbol_list_attr(sym) -> tuple:
    """Flattened (key, value, key, value, ...) like MXSymbolListAttr."""
    flat = []
    for k, v in sorted(sym.list_attr().items()):
        flat += [str(k), str(v)]
    return tuple(flat)


def symbol_list_auxiliary_states(sym) -> tuple:
    return tuple(sym.list_auxiliary_states())


def symbol_save_to_file(sym, path: str) -> None:
    sym.save(path)


def symbol_copy(sym):
    import copy
    return copy.deepcopy(sym)


def symbol_infer_shape(sym, names: tuple, shapes: tuple) -> tuple:
    """Returns (arg_shapes, out_shapes, aux_shapes) each as a flat tuple of
    ('name-free' nested) tuples; unknown shapes come back as ()."""
    hints = {n: tuple(s) for n, s in zip(names, shapes)}
    args, outs, auxs = sym.infer_shape(**hints)
    def _clean(lst):
        return tuple(tuple(s) if s is not None else () for s in (lst or []))
    return _clean(args), _clean(outs), _clean(auxs)


# ---- Executor monitor callback (ref: MXExecutorSetMonitorCallback,
# src/executor/graph_executor.cc:104 monitor path; powers mx.monitor) ----

def executor_set_monitor_callback(ex, pyfun) -> None:
    """pyfun(name: str, ndarray) is invoked for every output each forward
    — the C layer wraps the user's C function pointer in ``pyfun``."""
    ex.set_monitor_callback(pyfun)


# ---- KVStore breadth (ref: MXKVStoreGetRank/GetGroupSize/Barrier) ----

def kvstore_get_rank(kv) -> int:
    return int(kv.rank)


def kvstore_get_group_size(kv) -> int:
    return int(kv.num_workers)


def kvstore_barrier(kv) -> None:
    kv.barrier()


def kvstore_pushpull(kv, keys: tuple, vals: tuple, outs: tuple,
                     priority: int) -> None:
    kv.push(list(keys), list(vals), priority=priority)
    kv.pull(list(keys), list(outs), priority=priority)


# ---- misc breadth ----

def random_seed(seed: int) -> None:
    from . import random as rnd
    rnd.seed(int(seed))


def ndarray_slice(handle: NDArray, begin: int, end: int) -> NDArray:
    return handle[int(begin):int(end)]


def ndarray_reshape(handle: NDArray, shape: tuple) -> NDArray:
    return handle.reshape(tuple(int(s) for s in shape))


def ndarray_sync_copy_from_cpu(handle: NDArray, data: bytes) -> None:
    a = np.frombuffer(data, dtype=np.dtype(str(handle.dtype)))
    handle._set_data(jnp.asarray(a.reshape(handle.shape),
                                 dtype=handle._data.dtype))


def ndarray_context(handle: NDArray) -> str:
    return str(handle.context)


# ---- autograd breadth (ref: MXAutogradIsRecording / IsTraining /
# MarkVariables / MXAutogradBackwardEx, src/c_api/c_api_ndarray.cc) ----

def autograd_is_recording() -> int:
    from . import autograd
    return int(autograd.is_recording())


def autograd_is_training() -> int:
    from . import autograd
    return int(autograd.is_training())


_GRAD_REQ_FLAGS = {0: "null", 1: "write", 2: "add"}


def autograd_mark_variables(variables: tuple, grad_reqs: tuple) -> None:
    for v, r in zip(variables, grad_reqs):
        v.attach_grad(grad_req=_GRAD_REQ_FLAGS.get(int(r), "write"))


def autograd_backward(heads: tuple, ograds: tuple, retain_graph: int) -> None:
    """ograds may be empty (all ones-like seeds) or per-head entries where
    None means a ones-like seed for that head (ref MXAutogradBackwardEx
    NULL-entry semantics)."""
    from . import autograd
    hg = list(ograds) if ograds else None
    if hg is not None and all(g is None for g in hg):
        hg = None
    autograd.backward(list(heads), head_grads=hg,
                      retain_graph=bool(retain_graph))


# ---- CachedOp (ref: MXCreateCachedOpEx / MXInvokeCachedOpEx /
# MXFreeCachedOp, src/c_api/c_api_ndarray.cc; the engine-side analog is
# src/imperative/cached_op.cc — here the cache entry is a jit-compiled
# Executor per input-signature, XLA being the static planner). ----

class _CCachedOp:
    """Inputs are positional in ``symbol.list_inputs()`` order."""

    def __init__(self, sym, flags):
        self.sym = sym
        self.flags = dict(flags)        # static_alloc etc.: jit subsumes
        self.input_names = list(sym.list_inputs())
        self._aux_names = set(sym.list_auxiliary_states())
        self._cache = {}                # (shapes, dtypes) -> Executor

    def invoke(self, inputs):
        from . import autograd
        if len(inputs) != len(self.input_names):
            raise MXNetError(
                "CachedOp expects %d inputs (%s), got %d"
                % (len(self.input_names), ", ".join(self.input_names),
                   len(inputs)))
        feed = dict(zip(self.input_names, inputs))
        is_train = autograd.is_training()
        if autograd.is_recording():
            # eager per-op run: outputs land on the global tape so
            # MXTPUAutogradBackward works (ref MXInvokeCachedOpEx records
            # when Imperative::is_recording, c_api_ndarray.cc). Train-mode
            # BN aux updates write back into the CALLER's arrays (the
            # reference mutates aux in-kernel, batch_norm.cc).
            aux_updates = {} if is_train else None
            outs = tuple(self.sym._execute(feed, is_train=is_train,
                                           collect_aux=aux_updates))
            if aux_updates:
                for n, v in aux_updates.items():
                    feed[n]._set_data(v._data.astype(feed[n]._data.dtype))
            return outs
        sig = tuple((tuple(a.shape), str(a.dtype)) for a in inputs)
        ex = self._cache.get(sig)
        args = {n: v for n, v in feed.items() if n not in self._aux_names}
        aux = {n: v for n, v in feed.items() if n in self._aux_names}
        if ex is None:
            ex = self.sym.bind(None, args, aux_states=aux, grad_req="null")
            self._cache[sig] = ex
        else:
            for n, v in aux.items():  # refresh aux on a cache hit
                ex.aux_dict[n]._set_data(v._data)
        outs = tuple(ex.forward(is_train=is_train, **args))
        if is_train:
            # executor collected BN stat updates into its aux_dict;
            # propagate them to the caller's arrays
            for n, v in aux.items():
                v._set_data(ex.aux_dict[n]._data.astype(v._data.dtype))
        return outs


def cached_op_create(sym, flag_keys: tuple, flag_vals: tuple):
    return _CCachedOp(sym, zip(flag_keys, flag_vals))


def cached_op_invoke(op: _CCachedOp, inputs: tuple) -> tuple:
    return op.invoke(list(inputs))


# ---- NDArray breadth (ref: MXNDArrayCreateNone / At / Detach /
# WaitToRead / WaitToWrite / GetStorageType / SaveRawBytes /
# LoadFromRawBytes / LoadFromBuffer / SyncCopyFromNDArray /
# SyncCheckFormat / CreateSparseEx / GetAux* / GetDataNDArray) ----

def ndarray_create_none() -> NDArray:
    # the reference's deferred-alloc placeholder; here a 0-d f32 zero that
    # SyncCopyFromCPU / op outputs may later replace
    return nd.zeros(())


def ndarray_at(handle: NDArray, idx: int) -> NDArray:
    return handle[int(idx)]


def ndarray_detach(handle: NDArray) -> NDArray:
    return handle.detach()


def ndarray_wait_to_read(handle: NDArray) -> None:
    handle.wait_to_read()


def ndarray_wait_to_write(handle: NDArray) -> None:
    # one PJRT stream: readiness-to-write == readiness-to-read (the
    # reference separates them because its engine queues reads/writes
    # independently, threaded_engine.h:115)
    handle.wait_to_read()


_STYPE_FLAGS = {"default": 0, "row_sparse": 1, "csr": 2}  # ndarray.h:61
_STYPE_NAMES = {v: k for k, v in _STYPE_FLAGS.items()}


def ndarray_storage_type(handle) -> int:
    return _STYPE_FLAGS[getattr(handle, "stype", "default")]


def ndarray_save_raw_bytes(handle) -> bytes:
    """One NDArray as a single V2 record (ref MXNDArraySaveRawBytes —
    the chunk format without the 0x112 list header)."""
    from .ndarray import mxnet_format
    out = []
    if getattr(handle, "stype", "default") == "default":
        mxnet_format._write_dense(out, handle.asnumpy())
    else:
        raise MXNetError("save_raw_bytes: sparse handles unsupported; use "
                         "MXTPUNDArraySave")
    return b"".join(out)


def ndarray_load_from_raw_bytes(data: bytes):
    from .ndarray import mxnet_format
    r = mxnet_format._Reader(data)
    stype, payload = mxnet_format._read_ndarray(r)
    if stype != "default":
        raise MXNetError("load_from_raw_bytes: sparse record; use "
                         "MXTPUNDArrayLoad")
    return nd.array(payload)


def ndarray_load_from_buffer(data: bytes):
    """A whole .params file image from memory (ref MXNDArrayLoadFromBuffer;
    parsed in place — no filesystem round-trip)."""
    import struct
    from .ndarray import mxnet_format
    from .ndarray.utils import _load_mxnet
    if struct.unpack("<Q", data[:8].ljust(8, b"\0"))[0] == \
            mxnet_format.LIST_MAGIC:
        out = _load_mxnet(data)
        if isinstance(out, dict):
            return tuple(out.values()), tuple(out.keys())
        return tuple(out), ()
    # native MXTPU001 images are file-addressed; go through a temp file
    import tempfile
    with tempfile.NamedTemporaryFile(suffix=".params", delete=False) as f:
        f.write(data)
        path = f.name
    try:
        return ndarray_load(path)
    finally:
        os.unlink(path)


def ndarray_sync_copy_from_ndarray(dst: NDArray, src: NDArray) -> None:
    if tuple(dst.shape) != tuple(src.shape):
        raise MXNetError("SyncCopyFromNDArray: shape mismatch %s vs %s"
                         % (tuple(dst.shape), tuple(src.shape)))
    dst._set_data(jnp.asarray(src._data, dtype=dst._data.dtype))


def ndarray_sync_check_format(handle, full_check: int) -> None:
    if hasattr(handle, "check_format"):
        handle.check_format(full_check=bool(full_check))


def ndarray_create_sparse(stype_flag: int, data: NDArray,
                          aux: tuple, shape: tuple):
    from .ndarray import sparse as sp
    stype = _STYPE_NAMES.get(int(stype_flag))
    shape = tuple(int(s) for s in shape)
    if stype == "row_sparse":
        (indices,) = aux
        return sp.row_sparse_array((data, indices), shape=shape)
    if stype == "csr":
        indptr, indices = aux
        return sp.csr_matrix((data, indices, indptr), shape=shape)
    raise MXNetError("CreateSparseEx: unsupported stype flag %d" % stype_flag)


def ndarray_get_data_ndarray(handle) -> NDArray:
    if not hasattr(handle, "data"):
        raise MXNetError("GetDataNDArray: dense array has no data blob")
    return handle.data


def ndarray_get_aux_ndarray(handle, i: int) -> NDArray:
    names = (["indices"] if getattr(handle, "stype", None) == "row_sparse"
             else ["indptr", "indices"])
    if not hasattr(handle, "_aux") or i >= len(names):
        raise MXNetError("GetAuxNDArray: no aux %d" % i)
    return getattr(handle, names[i])


def ndarray_get_aux_type(handle, i: int) -> int:
    return ndarray_dtype_flag(ndarray_get_aux_ndarray(handle, i))


# ---- Symbol breadth (ref: MXSymbolCreateAtomicSymbol / CreateGroup /
# GetInternals / GetOutput / GetNumOutputs / GetName / GetChildren /
# InferType / InferShapePartial / ListAtomicSymbolCreators / Print) ----

def symbol_create_atomic(op_name: str, attrs: dict):
    """Uncomposed atomic symbol: compose with no inputs — argument
    variables are auto-created at compose time like the reference's
    nnvm lazy compose (c_api_symbolic.cc MXSymbolCreateAtomicSymbol)."""
    return symbol_invoke(op_name, attrs, "", ())


def symbol_create_group(syms: tuple):
    from .symbol import Group
    return Group(list(syms))


def symbol_get_internals(sym):
    return sym.get_internals()


def symbol_get_output(sym, index: int):
    return sym[int(index)]


def symbol_get_num_outputs(sym) -> int:
    return len(sym.list_outputs())


def symbol_get_name(sym) -> tuple:
    n = sym.name
    return (1, n) if n is not None else (0, "")


def symbol_get_children(sym):
    """Direct-input symbol group (ref MXSymbolGetChildren). Each input's
    (node, output-index) pair is preserved — two distinct outputs of one
    multi-output child are two children."""
    from .symbol.symbol import Symbol
    kids = []
    seen = set()
    for node, _ in sym._heads:
        for cn, idx in getattr(node, "inputs", ()):  # (node, idx) pairs
            key = (id(cn), idx)
            if key not in seen:
                seen.add(key)
                kids.append((cn, idx))
    return Symbol(kids)


def symbol_infer_type(sym, names: tuple, dtype_flags: tuple) -> tuple:
    """Unknowable slots are -1 (jax abstract-eval needs shapes to type
    nodes, symbol.py:_infer — hinted arguments always report their hint,
    so shape-less partial inference still answers for the inputs)."""
    hints = {n: _DTYPE_FLAGS[int(f)] for n, f in zip(names, dtype_flags)}
    args, outs, auxs = sym.infer_type(**hints)
    def _flags(lst):
        return [_FLAGS_BY_NAME.get(str(t), -1) if t is not None else -1
                for t in (lst or [])]
    arg_flags = _flags(args)
    arg_names = sym.list_arguments()
    if len(arg_flags) < len(arg_names):
        arg_flags += [-1] * (len(arg_names) - len(arg_flags))
    for i, n in enumerate(arg_names):
        if arg_flags[i] == -1 and n in hints:
            arg_flags[i] = _FLAGS_BY_NAME[hints[n]]
    return tuple(arg_flags), tuple(_flags(outs)), tuple(_flags(auxs))


def symbol_infer_shape_partial(sym, names: tuple, shapes: tuple) -> tuple:
    """Tolerant inference: unknown shapes come back () instead of raising
    (ref MXSymbolInferShapePartial). The out tuple always has one entry
    per symbol output so C callers can iterate positionally."""
    try:
        return symbol_infer_shape(sym, names, shapes)
    except Exception:
        known = {n: tuple(s) for n, s in zip(names, shapes)}
        args = tuple(known.get(n, ()) for n in sym.list_arguments())
        outs = tuple(() for _ in sym.list_outputs())
        return args, outs, ()


def symbol_list_atomic_creators() -> tuple:
    return list_all_op_names()


def symbol_print(sym) -> str:
    lines = ["Symbol Outputs:"]
    for o in sym.list_outputs():
        lines.append("\toutput[%s]" % o)
    for n in sym.list_arguments():
        lines.append("Variable:%s" % n)
    return "\n".join(lines)


# ---- Executor breadth (ref: MXExecutorSimpleBind / Reshape / Print) ----

def executor_simple_bind(sym, names: tuple, shapes: tuple, grad_req: str):
    from .symbol.executor import Executor
    hints = {n: tuple(int(d) for d in s) for n, s in zip(names, shapes)}
    return Executor.simple_bind(sym, grad_req=grad_req or "write", **hints)


def executor_reshape(ex, names: tuple, shapes: tuple):
    hints = {n: tuple(int(d) for d in s) for n, s in zip(names, shapes)}
    return ex.reshape(**hints)


def executor_print(ex) -> str:
    lines = ["Executor:"]
    for k, v in ex.arg_dict.items():
        lines.append("  arg %s %s %s" % (k, tuple(v.shape), v.dtype))
    for i, o in enumerate(ex.outputs or ()):
        lines.append("  out[%d] %s %s" % (i, tuple(o.shape), o.dtype))
    return "\n".join(lines)


# ---- KVStore breadth (ref: MXKVStoreGetType / SetUpdater /
# SetGradientCompression / PullRowSparse / GetNumDeadNode /
# IsWorkerNode / IsServerNode / IsSchedulerNode) ----

def kvstore_get_type(kv) -> str:
    return str(kv.type)


def kvstore_set_updater(kv, pyfun) -> None:
    """pyfun(key: str, recv: NDArray, local: NDArray) — the C layer wraps
    the user's function pointer; local is updated in place."""
    kv.set_updater(pyfun)


def kvstore_set_gradient_compression(kv, keys: tuple, vals: tuple) -> None:
    kv.set_gradient_compression(
        {k: _parse_attr(v) for k, v in zip(keys, vals)})


def kvstore_pull_row_sparse(kv, keys: tuple, outs: tuple, row_ids: tuple,
                            priority: int) -> None:
    kv.row_sparse_pull(list(keys), out=list(outs), priority=priority,
                       row_ids=list(row_ids))


def kvstore_get_num_dead_node(kv, node_id: int) -> int:
    return int(kv.get_num_dead_node(node_id))


def kvstore_is_worker_node() -> int:
    # symmetric-worker design: every process is a worker (the reference's
    # role env DMLC_ROLE decides; servers were ADR'd out, kvstore.py:272)
    return int(os.environ.get("DMLC_ROLE", "worker") == "worker")


def kvstore_is_server_node() -> int:
    return int(os.environ.get("DMLC_ROLE", "worker") == "server")


def kvstore_is_scheduler_node() -> int:
    return int(os.environ.get("DMLC_ROLE", "worker") == "scheduler")


# ---- profiler (ref: MXSetProfilerConfig / MXSetProfilerState /
# MXDumpProfile / MXProfilePause, src/c_api/c_api_profile.cc) ----

def profiler_set_config(keys: tuple, vals: tuple) -> None:
    from . import profiler
    kw = {}
    for k, v in zip(keys, vals):
        k = {"file_name": "filename", "filename": "filename",
             "profile_all": "profile_all"}.get(k, k)
        kw[k] = _parse_attr(v)
    profiler.set_config(**kw)


def profiler_set_state(state: int) -> None:
    from . import profiler
    if state:
        profiler.start()
    else:
        profiler.stop()


def profiler_dump(finished: int) -> None:
    from . import profiler
    profiler.dump(finished=bool(finished))


# ---- profiler object family (ref: MXProfileCreateDomain / CreateTask /
# CreateFrame / CreateEvent / CreateCounter / DurationStart / DurationStop
# / SetCounter / AdjustCounter / SetMarker / MXAggregateProfileStatsPrint,
# src/c_api/c_api_profile.cc — scoped user timing objects over
# mxtpu/profiler.py ProfileTask/Frame/Event) ----

class _ProfileDomain:
    def __init__(self, name):
        self.name = name


class _ProfileCounter:
    """One aggregate row per counter (its CURRENT value) — per-update
    events would make a 100k-update counter a 100k-row table. Updates are
    lock-guarded: += spans two bytecodes and the GIL may switch between
    them, so concurrent C threads would otherwise lose increments (the
    reference's MXProfileAdjustCounter is atomic for exactly this)."""

    def __init__(self, domain, name):
        import threading
        self.name = ("%s:%s" % (domain.name, name)) if domain else name
        self.value = 0
        self._lock = threading.Lock()
        _LIVE_COUNTERS[self.name] = self

    def set(self, value):
        with self._lock:
            self.value = int(value)

    def adjust(self, delta):
        with self._lock:
            self.value += int(delta)


_LIVE_COUNTERS = {}  # name -> _ProfileCounter (aggregate-stats rows)


def profile_create_domain(name: str):
    return _ProfileDomain(name)


def profile_create_task(domain, name: str):
    from . import profiler
    return profiler.ProfileTask(name, domain=domain)


def profile_create_frame(domain, name: str):
    from . import profiler
    return profiler.ProfileFrame(name, domain=domain)


def profile_create_event(name: str):
    from . import profiler
    return profiler.ProfileEvent(name)


def profile_create_counter(domain, name: str):
    return _ProfileCounter(domain, name)


def profile_duration_start(obj) -> None:
    obj.start()


def profile_duration_stop(obj) -> None:
    obj.stop()


def profile_set_counter(counter, value: int) -> None:
    counter.set(value)


def profile_adjust_counter(counter, delta: int) -> None:
    counter.adjust(delta)


def profile_set_marker(domain, name: str, scope: str) -> None:
    import time as _t
    from . import profiler
    if profiler.is_active():
        nm = ("%s:%s" % (domain.name, name)) if domain else name
        profiler.record_event(nm, "marker:%s" % (scope or "process"),
                              _t.perf_counter_ns() // 1000, 0)


def profile_destroy(obj) -> None:
    """Deregister (ref MXProfileDestroyHandle): a destroyed counter must
    leave the aggregate table — the registry's strong ref would otherwise
    keep every per-phase counter alive and listed forever."""
    name = getattr(obj, "name", None)
    if name is not None and _LIVE_COUNTERS.get(name) is obj:
        del _LIVE_COUNTERS[name]


def profile_aggregate_stats(reset: int) -> str:
    from . import profiler
    table = profiler.dumps(reset=bool(reset))
    if _LIVE_COUNTERS:
        lines = ["", "Counters:"]
        for name in sorted(_LIVE_COUNTERS):
            lines.append("%s=%d" % (name, _LIVE_COUNTERS[name].value))
        table += "\n".join(lines)
    return table


def profiler_pause(paused: int) -> None:
    from . import profiler
    if paused:
        profiler.pause()
    else:
        profiler.resume()


def executor_backward_ex(ex, ograds: tuple) -> None:
    """Backward with explicit head gradients; per-entry None = ones-like
    seed for that output (ref MXExecutorBackwardEx NULL entries)."""
    og = list(ograds) if ograds else None
    if og is not None and any(g is None for g in og):
        outs = ex.outputs or []
        # seed in the HEAD's dtype (ones_like semantics): a float32 seed on
        # a bf16/f16 head would promote every gradient downstream of it
        og = [g if g is not None
              else nd.ones(tuple(outs[i].shape), dtype=outs[i].dtype)
              for i, g in enumerate(og)]
    ex.backward(out_grads=og)


def ndarray_set_grad_state(handle, state: int) -> None:
    """fresh-grad marker (ref MXNDArraySetGradState / NDArray.fresh_grad:
    a frontend bookkeeping bit, stored as-is)."""
    handle._fresh_grad = bool(state)


def ndarray_get_grad_state(handle) -> int:
    return int(getattr(handle, "_fresh_grad", False))


# ---- runtime kernel compilation (ref: MXRtcCudaModuleCreate /
# MXRtcCudaKernelCreate / MXRtcCudaKernelCall, src/c_api/c_api.cc over
# src/common/rtc.cc NVRTC — here mxtpu/rtc.py PallasModule: the source
# string is Python defining Pallas kernel functions) ----

def rtc_module_create(source: str, exports: tuple):
    from .rtc import PallasModule
    return PallasModule(source, exports=list(exports) if exports else None)


def rtc_kernel_create(module, name: str, num_outputs: int):
    return module.get_kernel(name, num_outputs=num_outputs)


def rtc_kernel_call(kernel, inputs: tuple, out_shapes: tuple,
                    out_dtype_flags: tuple):
    dts = [_DTYPE_FLAGS[int(f)] for f in out_dtype_flags]
    outs = kernel.launch(list(inputs),
                         [tuple(int(d) for d in s) for s in out_shapes],
                         out_dtypes=dts)
    return tuple(outs) if isinstance(outs, list) else (outs,)


# ---- misc breadth (ref: MXGetGPUCount / MXGetGPUMemoryInformation64 /
# MXNotifyShutdown / MXEngineSetBulkSize / MXSetNumOMPThreads /
# MXRandomSeedContext / MXDataIterGetIterInfo) ----

def get_device_count() -> int:
    import jax
    return len(jax.devices())


def get_memory_information(dev_id: int) -> tuple:
    """(free, total) bytes for the device (ref MXGetGPUMemoryInformation64;
    here PJRT memory stats — absent stats raise, they don't guess).
    Reads through ``xprof.device_memory`` — the ONE normalizer the
    python-API ``util.get_gpu_memory`` and the ``memory.hbm_*`` gauges
    also use, so the C ABI can never disagree with them."""
    import jax
    devs = jax.devices()
    if dev_id >= len(devs):
        raise MXNetError("no device %d (have %d)" % (dev_id, len(devs)))
    from . import xprof
    m = xprof.device_memory(devs[dev_id])
    if not m["bytes_limit"]:
        raise MXNetError("device %d exposes no memory stats" % dev_id)
    return m["bytes_free"], m["bytes_limit"]


def notify_shutdown() -> None:
    # the reference tears its engine down (MXNotifyShutdown); PJRT clients
    # shut down at process exit — flush pending work so exit is clean
    ndarray_wait_all()


def engine_set_bulk_size(size: int) -> int:
    from . import engine
    prev = engine.set_bulk_size(int(size))
    return int(prev)


def set_num_omp_threads(n: int) -> None:
    # XLA:CPU fixes its thread pool at backend init; honor the call as the
    # documented no-op the engine module explains (engine.py bulk ADR)
    return None


def random_seed_context(seed: int, dev_type: int, dev_id: int) -> None:
    # one functional PRNG stream regardless of device (random.py design)
    random_seed(seed)


def ndarray_to_dlpack(handle):
    """NDArray -> "dltensor" capsule (the C layer unwraps the pointer)."""
    from .ndarray.dlpack import to_dlpack_for_read
    return to_dlpack_for_read(handle)


def ndarray_from_dlpack(capsule):
    from .ndarray.dlpack import from_dlpack
    return from_dlpack(capsule)


# ---- shared-memory NDArrays (ref: MXNDArrayCreateFromSharedMem /
# MXNDArrayGetSharedMemHandle, src/c_api/c_api.cc:1375 — the reference
# addresses segments by (pid, fd); POSIX shared memory is NAME-addressed,
# so this ABI exchanges segment names instead. The gluon multiprocess
# DataLoader workers use the same mechanism, gluon/data/_mp_worker.py.)

def ndarray_get_shared_mem_handle(handle) -> str:
    """Copy the array into a fresh POSIX shared-memory segment and return
    its name. Ownership transfers to the receiving process: the creating
    tracker is unregistered, and CreateFromSharedMem unlinks."""
    from multiprocessing import shared_memory
    a = np.ascontiguousarray(handle.asnumpy())
    seg = shared_memory.SharedMemory(create=True, size=max(1, a.nbytes))
    # direct memoryview copy — no tobytes() temporary (matters at GB sizes)
    seg.buf[:a.nbytes] = memoryview(a).cast("B")
    try:  # receiver owns the segment now (mirrors _mp_worker.to_shm)
        from multiprocessing import resource_tracker
        resource_tracker.unregister(seg._name, "shared_memory")
    except Exception:
        pass
    name = seg.name
    seg.close()
    return name


def ndarray_create_from_shared_mem(name: str, dtype_flag: int,
                                   shape: tuple):
    """Attach, copy to a device array, and unlink (one-shot transfer)."""
    from multiprocessing import shared_memory
    dt = _DTYPE_FLAGS.get(int(dtype_flag))
    if dt is None:
        raise MXNetError("unknown mshadow dtype flag %d" % dtype_flag)
    seg = shared_memory.SharedMemory(name=name)
    try:
        n = int(np.prod(shape)) if shape else 1
        a = np.frombuffer(seg.buf, dtype=np.dtype(dt),
                          count=n).reshape(shape).copy()
    finally:
        seg.close()
        try:
            seg.unlink()
        except FileNotFoundError:
            pass
    return nd.array(a, dtype=dt)


def data_iter_get_iter_info(name: str) -> tuple:
    cls = _data_iter_registry().get(name)
    if cls is None:
        raise MXNetError("unknown data iter %r" % name)
    doc = (cls.__doc__ or "").strip().split("\n")[0]
    return name, doc
