"""Python guts of the C ABI (src/c_api/c_api.cc delegates here).

The reference's src/c_api/*.cc marshals C arguments into its C++ engine;
the TPU-native runtime's orchestrator is this package, so the C layer
marshals into these functions instead. Every function takes/returns only
plain C-friendly values (bytes, tuples, opaque objects used as handles).

Env: set ``MXTPU_JAX_PLATFORMS`` (e.g. ``cpu``) before the first call to pin
the jax platform from a C host — the axon sitecustomize would otherwise
override ``JAX_PLATFORMS``.
"""
from __future__ import annotations

import ast
import os

_PLATFORM_PIN = os.environ.get("MXTPU_JAX_PLATFORMS")
if _PLATFORM_PIN:
    import jax

    jax.config.update("jax_platforms", _PLATFORM_PIN)

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from . import ndarray as nd  # noqa: E402
from . import ops  # noqa: E402
from .base import MXNetError  # noqa: E402
from .model import load_checkpoint  # noqa: E402
from .ndarray import NDArray  # noqa: E402


def runtime_init(platform=None):
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
    import jax

    jax.devices()  # force backend bring-up so later calls are fast
    return True


def ndarray_from_blob(data: bytes, shape: tuple) -> NDArray:
    arr = np.frombuffer(data, dtype=np.float32).reshape(shape)
    return nd.array(arr)


def ndarray_shape(handle: NDArray) -> tuple:
    return tuple(int(d) for d in handle.shape)


def ndarray_to_bytes(handle: NDArray) -> bytes:
    return np.ascontiguousarray(handle.asnumpy().astype(np.float32)).tobytes()


def _parse_attr(v: str):
    try:
        return ast.literal_eval(v)
    except (ValueError, SyntaxError):
        return v


def imperative_invoke(name: str, inputs: list, attrs: dict) -> list:
    kwargs = {k: _parse_attr(v) for k, v in attrs.items()}
    out = ops.invoke(name, *inputs, **kwargs)
    if isinstance(out, (list, tuple)):
        return list(out)
    return [out]


class _Predictor:
    """C-predict-API state (ref: src/c_api/c_predict_api.cc:59-213 — the
    reference binds a static executor; here bind = jit-compiled Symbol
    executor over the same checkpoint format)."""

    def __init__(self, prefix, epoch, input_name, shape):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        if symbol is None:
            raise MXNetError("no symbol file for prefix %r" % prefix)
        self.input_name = input_name
        self.shape = tuple(int(d) for d in shape)
        args = dict(arg_params)
        args[input_name] = nd.zeros(self.shape)
        self.executor = symbol.bind(args=args, aux_states=aux_params,
                                    grad_req="null")
        self._input = None
        self.outputs = []

    def set_input(self, data: bytes):
        arr = np.frombuffer(data, dtype=np.float32).reshape(self.shape)
        self._input = nd.array(arr)

    def forward(self):
        kwargs = {}
        if self._input is not None:
            kwargs[self.input_name] = self._input
        self.outputs = self.executor.forward(is_train=False, **kwargs)


def pred_create(prefix, epoch, input_name, shape) -> _Predictor:
    return _Predictor(prefix, epoch, input_name, shape)


def pred_set_input(pred: _Predictor, data: bytes):
    pred.set_input(data)
    return True


def pred_forward(pred: _Predictor):
    pred.forward()
    return True


def pred_output_shape(pred: _Predictor, index: int) -> tuple:
    return tuple(int(d) for d in pred.outputs[index].shape)


def pred_output_bytes(pred: _Predictor, index: int) -> bytes:
    return ndarray_to_bytes(pred.outputs[index])


# ---- autograd (ref: c_api_ndarray.cc MXAutogradSetIsRecording /
# MarkVariables / Backward; SURVEY §2.1 imperative+autograd) ----

def autograd_set_recording(flag: int) -> int:
    from . import autograd
    return int(autograd.set_recording(bool(flag)))


def autograd_set_training(flag: int) -> int:
    from . import autograd
    return int(autograd.set_training(bool(flag)))


def ndarray_attach_grad(handle: NDArray) -> None:
    handle.attach_grad()


def ndarray_grad(handle: NDArray) -> NDArray:
    g = handle.grad
    if g is None:
        raise MXNetError("no gradient: attach_grad() was not called or "
                         "backward has not run")
    return g


def ndarray_backward(handle: NDArray, retain_graph: int) -> None:
    handle.backward(retain_graph=bool(retain_graph))


# ---- KVStore (ref: c_api.cc MXKVStoreCreate / Init / Push / Pull /
# SetOptimizer; SURVEY §2.3) ----

def kvstore_create(kind: str):
    from .kvstore import create
    return create(kind or "local")


def kvstore_init(kv, keys: tuple, vals: tuple) -> None:
    kv.init(list(keys), list(vals))


def kvstore_push(kv, keys: tuple, vals: tuple, priority: int) -> None:
    kv.push(list(keys), list(vals), priority=priority)


def kvstore_pull(kv, keys: tuple, outs: tuple, priority: int) -> None:
    kv.pull(list(keys), out=list(outs), priority=priority)


def kvstore_set_optimizer(kv, name: str, attrs: dict) -> None:
    from .optimizer import Optimizer
    kv.set_optimizer(Optimizer.create_optimizer(
        name, **{k: _parse_attr(v) for k, v in attrs.items()}))


# ---- Symbol + Executor (ref: c_api_symbolic.cc MXSymbolCreateVariable /
# CreateAtomicSymbol+Compose / ListArguments / CreateFromJSON;
# c_api_executor.cc MXExecutorBindEX / Forward / Backward / Outputs) ----

def symbol_create_variable(name: str):
    from .symbol import var
    return var(name)


def symbol_create_from_json(json_str: str):
    from .symbol import load_json
    return load_json(json_str)


def symbol_create_from_file(path: str):
    from .symbol import load as sym_load
    return sym_load(path)


def symbol_invoke(op_name: str, attrs: dict, name: str, inputs: tuple):
    """CreateAtomicSymbol + Compose in one call (the reference splits
    these only because nnvm composes lazily — ref c_api_symbolic.cc
    MXSymbolCreateAtomicSymbol + MXSymbolCompose)."""
    from . import symbol as sym_mod
    fn = getattr(sym_mod, op_name, None)
    if fn is None:
        raise MXNetError("unknown symbolic operator %r" % op_name)
    kwargs = {k: _parse_attr(v) for k, v in attrs.items()}
    if name:
        kwargs["name"] = name
    return fn(*inputs, **kwargs)


def symbol_list_arguments(sym) -> tuple:
    return tuple(sym.list_arguments())


def symbol_list_outputs(sym) -> tuple:
    return tuple(sym.list_outputs())


def symbol_tojson(sym) -> str:
    return sym.tojson()


def executor_bind(sym, arg_names: tuple, arg_vals: tuple,
                  grad_req: str):
    args = dict(zip(arg_names, arg_vals))
    return sym.bind(None, args, grad_req=grad_req or "write")


def executor_forward(ex, is_train: int) -> tuple:
    return tuple(ex.forward(is_train=bool(is_train)))


def executor_backward(ex) -> None:
    ex.backward()


def executor_outputs(ex) -> tuple:
    return tuple(ex.outputs)


def executor_arg_grad(ex, name: str) -> NDArray:
    grads = ex.grad_dict if hasattr(ex, "grad_dict") else None
    if grads is None or name not in grads or grads[name] is None:
        raise MXNetError("no gradient for argument %r" % name)
    return grads[name]


# ---- dtype-aware create / save / load (ref: MXNDArrayCreateEx,
# MXNDArraySave, MXNDArrayLoad over src/c_api/c_api.cc:1035-1120) ----

_DTYPE_FLAGS = {0: "float32", 1: "float64", 2: "float16", 3: "uint8",
                4: "int32", 5: "int8", 6: "int64"}
_FLAGS_BY_NAME = {v: k for k, v in _DTYPE_FLAGS.items()}


def ndarray_from_blob_ex(data: bytes, dtype_flag: int, shape: tuple):
    name = _DTYPE_FLAGS.get(int(dtype_flag))
    if name is None:
        raise MXNetError("unknown mshadow dtype flag %d" % dtype_flag)
    a = np.frombuffer(data, dtype=np.dtype(name)).reshape(shape)
    return nd.array(a, dtype=name)


def ndarray_dtype_flag(handle: NDArray) -> int:
    name = str(handle.dtype)
    if name == "bfloat16":  # no reference flag; surfaced as its f32 carrier
        return 0
    flag = _FLAGS_BY_NAME.get(name)
    if flag is None:
        raise MXNetError("dtype %s has no mshadow flag" % name)
    return flag


def ndarray_save(fname: str, handles: tuple, names: tuple) -> None:
    from .ndarray.utils import save as nd_save
    if names:
        if len(set(names)) != len(names):
            # the dict-keyed writer would silently drop all but the last
            # duplicate; refuse loudly instead (the reference would write
            # both records, which this engine's named files cannot)
            raise MXNetError("duplicate keys in NDArray save")
        nd_save(fname, dict(zip(names, handles)))
    else:
        nd_save(fname, list(handles))


def ndarray_load(fname: str):
    from .ndarray.utils import load as nd_load
    out = nd_load(fname)
    if isinstance(out, dict):
        return tuple(out.values()), tuple(out.keys())
    return tuple(out), ()


# ---- introspection / sync (ref: MXGetVersion, MXListAllOpNames,
# MXNDArrayWaitAll) ----

def get_version() -> int:
    """Reference packs MAJOR*10000 + MINOR*100 + PATCH (c_api.cc)."""
    import re
    from .libinfo import __version__
    parts = (__version__.split(".") + ["0", "0"])[:3]
    nums = []
    for part in parts:
        m = re.match(r"\d+", part)  # "0rc1" -> 0 (pre-release suffixes)
        nums.append(int(m.group()) if m else 0)
    return nums[0] * 10000 + nums[1] * 100 + nums[2]


def list_all_op_names() -> tuple:
    from .ops.registry import list_ops
    return tuple(list_ops())


def ndarray_wait_all() -> None:
    # NOT ndarray.waitall(), which swallows: the C contract is that
    # deferred async errors SURFACE here (-1 + MXTPUGetLastError), the
    # reference's MXNDArrayWaitAll semantics
    import jax
    jax.effects_barrier()


# ---- DataIter surface (ref: MXListDataIters/MXDataIterCreateIter/
# MXDataIterNext/MXDataIterGetData..., src/c_api/c_api.cc MXDataIter*) ----

_DATA_ITERS = None


def _data_iter_registry():
    global _DATA_ITERS
    if _DATA_ITERS is None:
        from . import io as io_mod
        from .image import ImageIter
        _DATA_ITERS = {
            "NDArrayIter": io_mod.NDArrayIter,
            "CSVIter": io_mod.CSVIter,
            "LibSVMIter": io_mod.LibSVMIter,
            "ImageRecordIter": ImageIter,  # the reference's registered name
            "ImageIter": ImageIter,
        }
    return _DATA_ITERS


def list_data_iters() -> tuple:
    return tuple(sorted(_data_iter_registry()))


class _CIter:
    """Iterator handle: owns the iter + the current batch (the reference's
    MXDataIterNext caches the batch the Get* calls then read)."""

    def __init__(self, it):
        self.it = it
        self.batch = None


def data_iter_create(name: str, attrs: dict):
    cls = _data_iter_registry().get(name)
    if cls is None:
        raise MXNetError("unknown data iter %r (have: %s)"
                         % (name, ", ".join(sorted(_data_iter_registry()))))
    kwargs = {k: _parse_attr(v) for k, v in attrs.items()}
    return _CIter(cls(**kwargs))


def data_iter_before_first(handle: "_CIter") -> None:
    handle.it.reset()
    handle.batch = None


def data_iter_next(handle: "_CIter") -> int:
    try:
        handle.batch = handle.it.next()
        return 1
    except StopIteration:
        handle.batch = None
        return 0


def _require_batch(handle):
    if handle.batch is None:
        raise MXNetError("no current batch: call MXTPUDataIterNext first")
    return handle.batch


def data_iter_get_data(handle: "_CIter") -> NDArray:
    return _require_batch(handle).data[0]


def data_iter_get_label(handle: "_CIter") -> NDArray:
    return _require_batch(handle).label[0]


def data_iter_get_pad_num(handle: "_CIter") -> int:
    return int(_require_batch(handle).pad or 0)


def data_iter_get_index(handle: "_CIter") -> tuple:
    idx = _require_batch(handle).index
    return tuple(int(i) for i in idx) if idx is not None else ()


# ---- RecordIO surface (ref: MXRecordIOWriterCreate/WriteRecord/Tell,
# MXRecordIOReaderCreate/ReadRecord/Seek, c_api.cc) ----

def recordio_writer_create(path: str):
    from .recordio import MXRecordIO
    return MXRecordIO(path, "w")


def recordio_writer_write(w, data: bytes) -> None:
    w.write(data)


def recordio_writer_tell(w) -> int:
    return int(w.tell())


def recordio_reader_create(path: str):
    from .recordio import MXRecordIO
    return MXRecordIO(path, "r")


def recordio_reader_read(r):
    """(has_record, payload): a zero-length RECORD is (1, b"") — distinct
    from EOF (0, b""), which bare bytes could not express."""
    out = r.read()
    if out is None:
        return (0, b"")
    return (1, bytes(out))


def recordio_reader_seek(r, pos: int) -> None:
    r.seek(pos)


def recordio_reader_tell(r) -> int:
    return int(r.tell())


def recordio_close(h) -> None:
    h.close()


# ---- Symbol attributes / breadth (ref: MXSymbolSetAttr/GetAttr/ListAttr,
# MXSymbolListAuxiliaryStates, MXSymbolInferShape, MXSymbolSaveToFile) ----

def symbol_set_attr(sym, key: str, value: str) -> None:
    if len(sym._heads) != 1:
        raise MXNetError("set_attr needs a single-output symbol")
    sym._heads[0][0].attrs[key] = value


def symbol_get_attr(sym, key: str) -> str:
    v = sym.attr(key)
    if v is None:
        raise MXNetError("symbol has no attribute %r" % key)
    return str(v)


def symbol_list_attr(sym) -> tuple:
    """Flattened (key, value, key, value, ...) like MXSymbolListAttr."""
    flat = []
    for k, v in sorted(sym.list_attr().items()):
        flat += [str(k), str(v)]
    return tuple(flat)


def symbol_list_auxiliary_states(sym) -> tuple:
    return tuple(sym.list_auxiliary_states())


def symbol_save_to_file(sym, path: str) -> None:
    sym.save(path)


def symbol_copy(sym):
    import copy
    return copy.deepcopy(sym)


def symbol_infer_shape(sym, names: tuple, shapes: tuple) -> tuple:
    """Returns (arg_shapes, out_shapes, aux_shapes) each as a flat tuple of
    ('name-free' nested) tuples; unknown shapes come back as ()."""
    hints = {n: tuple(s) for n, s in zip(names, shapes)}
    args, outs, auxs = sym.infer_shape(**hints)
    def _clean(lst):
        return tuple(tuple(s) if s is not None else () for s in (lst or []))
    return _clean(args), _clean(outs), _clean(auxs)


# ---- Executor monitor callback (ref: MXExecutorSetMonitorCallback,
# src/executor/graph_executor.cc:104 monitor path; powers mx.monitor) ----

def executor_set_monitor_callback(ex, pyfun) -> None:
    """pyfun(name: str, ndarray) is invoked for every output each forward
    — the C layer wraps the user's C function pointer in ``pyfun``."""
    ex.set_monitor_callback(pyfun)


# ---- KVStore breadth (ref: MXKVStoreGetRank/GetGroupSize/Barrier) ----

def kvstore_get_rank(kv) -> int:
    return int(kv.rank)


def kvstore_get_group_size(kv) -> int:
    return int(kv.num_workers)


def kvstore_barrier(kv) -> None:
    kv.barrier()


def kvstore_pushpull(kv, keys: tuple, vals: tuple, outs: tuple,
                     priority: int) -> None:
    kv.push(list(keys), list(vals), priority=priority)
    kv.pull(list(keys), list(outs), priority=priority)


# ---- misc breadth ----

def random_seed(seed: int) -> None:
    from . import random as rnd
    rnd.seed(int(seed))


def ndarray_slice(handle: NDArray, begin: int, end: int) -> NDArray:
    return handle[int(begin):int(end)]


def ndarray_reshape(handle: NDArray, shape: tuple) -> NDArray:
    return handle.reshape(tuple(int(s) for s in shape))


def ndarray_sync_copy_from_cpu(handle: NDArray, data: bytes) -> None:
    a = np.frombuffer(data, dtype=np.dtype(str(handle.dtype)))
    handle._set_data(jnp.asarray(a.reshape(handle.shape),
                                 dtype=handle._data.dtype))


def ndarray_context(handle: NDArray) -> str:
    return str(handle.context)
