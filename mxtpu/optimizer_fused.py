"""Fused whole-model optimizer step: ONE donated jit per Trainer.step.

The eager update path (Optimizer.update driven from Updater.__call__) issues
3-10 tiny XLA dispatches *per parameter per step* — exactly the
consecutive-small-ops anti-pattern the reference engine exists to bulk
(SURVEY §1; "Operator Fusion in XLA" shows this elementwise chain is where
fusion pays). This module is the update-path analog of CachedOp for
forward/backward: every optimizer's update rule is restated as a pure
``step(weight, grad, state, hyper, rescale, static) -> (new_w, new_state)``
function; the whole parameter list is stacked into one pytree and compiled
as a single ``jax.jit`` with ``donate_argnums`` on weights and states, so
XLA updates every buffer in place with no copies and no per-param host
round trips ("Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training" treats the weight update as the same first-class
fusion target).

Cache key = (optimizer class, static config like momentum/betas/clip,
per-param shapes+dtypes+state structure). Hyperparameters that move between
steps — lr (schedules!), wd, rescale_grad=1/batch, bias-correction terms of
the update count t — enter as *traced* scalars, so an lr-schedule tick or a
batch-size change never retriggers compilation.

Fallback to the eager per-param loop: sparse (row_sparse) grads, optimizers
with host-side control flow (SGLD's rng draw, LBSGD's norm-driven LARS
ratio), aliased buffers (donation would invalidate a live input twice), or
``MXTPU_FUSED_OPTIMIZER=0``.

Numerics sentinel (mxtpu/resilience.py): with ``MXTPU_NUMERICS_GUARD=1``
or a :class:`~mxtpu.resilience.DynamicLossScaler` attached, the SAME
donated jit additionally computes one fused all-params finite flag + the
global grad norm and applies every update under ``jnp.where`` — a
non-finite step is a no-op on params and optimizer state (including the
bias-correction step count, which moves to a DEVICE scalar ``t_good`` so
the skip costs no host sync), and the loss-scaler growth/backoff runs
in-graph on traced scalars (flag flips never recompile; guard on/off is
exactly one extra compile — the guard bit is part of the jit cache key).

Mesh-native stepping (ISSUE 7): :meth:`FusedUpdater.set_mesh` adopts a
:class:`MeshPlan` — parameters live as ONE logical replicated array on a
``jax.sharding.Mesh`` and the cross-replica weight-update sharding of
arXiv:2004.13336 (ZeRO-1) moves INTO this donated jit: the gradient is
constrained to a data-axis shard (reduce-scatter, or a free slice when it
arrives replicated from the eager backward), the optimizer update runs
shard-local on 1/N of the rows, only the weight is all-gathered back, and
the optimizer state STAYS sharded — state memory and update FLOPs divide
by the replica count. The sharding layout (per-buffer tokens + the plan
fingerprint) is part of the jit cache key, the down payment on ROADMAP
item 5's one-compile-cache engine.
"""
from __future__ import annotations

import collections
import math
import os

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as _P

from . import resilience
from . import telemetry
from .ndarray import NDArray
from .ops import optimizer_ops as _uo
from .optimizer import (SGD, Adam, AdaGrad, RMSProp, AdaDelta, Ftrl, Adamax,
                        Nadam, NAG, Signum, FTML, DCASGD, Test, GroupAdaGrad,
                        Updater)

__all__ = ["FusedUpdater", "MeshPlan", "fused_enabled", "cache_size",
           "reset", "FUSED_STATS", "functional_rule", "traced_rule_names"]


def fused_enabled():
    """Measured default ON; MXTPU_FUSED_OPTIMIZER=0 is the escape hatch
    (read per call, so it can be flipped mid-process for A/Bs)."""
    return os.environ.get("MXTPU_FUSED_OPTIMIZER", "1") != "0"


# fused_steps: fused jit invocations; traces: actual retraces (bumped at
# trace time INSIDE the jitted fn — the recompile counter tests assert on);
# compiles: misses of the executable cache; eager_updates: per-param
# fallback updates
FUSED_STATS = {"fused_steps": 0, "traces": 0, "compiles": 0,
               "eager_updates": 0}


class _ServiceCacheView(dict):
    """Hot-path L1 view over the compile service's ``fused_optimizer``
    entries: steady-state dispatch is one plain dict hit; misses
    resolve through :mod:`mxtpu.compile_service` (reporting, disk
    cache, LRU). ``clear()`` drops the service entries too, so a test
    reset forces real recompiles instead of silent service hits."""

    def clear(self):
        super().clear()
        from . import compile_service
        compile_service.drop(site="fused_optimizer")


_JIT_CACHE = _ServiceCacheView()


def cache_size():
    return len(_JIT_CACHE)


def reset():
    """Test hook: drop compiled executables and zero the counters."""
    _JIT_CACHE.clear()
    for k in FUSED_STATS:
        FUSED_STATS[k] = 0


# --------------------------------------------------------------------- rules
class _Rule:
    """One optimizer class's pure functional update.

    ``static(opt)`` -> hashable config baked into the trace (part of the jit
    cache key); ``hyper(opt, index, t)`` -> per-param scalars traced as
    arguments (lr/wd after lr_mult/wd_mult, bias-correction terms of t);
    ``step(w, g, state, hyper, rescale, static)`` -> (new_w, new_state) with
    ``state`` the same tuple/None structure the Updater stores.

    ``thyper(static, lr, wd, t)`` is the guarded-mode twin of ``hyper``: it
    rebuilds the hyper tuple IN-GRAPH from traced (lr, wd, t) so the
    effective update count can live on device (a skipped step must not
    advance it, and fetching it per step would be a host sync). ``None``
    marks optimizers whose hyper depends on order-dependent host state
    (Nadam's m_schedule) — those take the guarded-eager path instead.
    """

    __slots__ = ("static", "hyper", "step", "thyper")

    def __init__(self, static, hyper, step, thyper=None):
        self.static = static
        self.hyper = hyper
        self.step = step
        self.thyper = thyper


def _clip_of(opt):
    return float(opt.clip_gradient) if opt.clip_gradient else -1.0


def _lr_wd(opt, index, _t=None):
    return float(opt._get_lr(index)), float(opt._get_wd(index))


def _sgd_static(opt):
    return (float(opt.momentum), _clip_of(opt))


def _sgd_step(w, g, state, hyper, rescale, static):
    lr, wd = hyper
    momentum, clip = static
    if state is None:
        return _uo.sgd_update_fn(w, g, lr, wd=wd, rescale_grad=rescale,
                                 clip_gradient=clip), None
    return _uo.sgd_mom_update_fn(w, g, state, lr, momentum=momentum, wd=wd,
                                 rescale_grad=rescale, clip_gradient=clip)


def _nag_step(w, g, state, hyper, rescale, static):
    lr, wd = hyper
    momentum, clip = static
    if state is None:
        return _uo.sgd_update_fn(w, g, lr, wd=wd, rescale_grad=rescale,
                                 clip_gradient=clip), None
    return _uo.nag_mom_update_fn(w, g, state, lr, momentum=momentum, wd=wd,
                                 rescale_grad=rescale, clip_gradient=clip)


def _signum_static(opt):
    return (float(opt.momentum), float(opt.wd_lh), _clip_of(opt))


def _signum_step(w, g, state, hyper, rescale, static):
    lr, wd = hyper
    momentum, wd_lh, clip = static
    if state is None:
        return _uo.signsgd_update_fn(w, g, lr, wd=wd, rescale_grad=rescale,
                                     clip_gradient=clip), None
    return _uo.signum_update_fn(w, g, state, lr, momentum=momentum, wd=wd,
                                rescale_grad=rescale, clip_gradient=clip,
                                wd_lh=wd_lh)


def _beta_eps_static(opt):
    return (float(opt.beta1), float(opt.beta2), float(opt.epsilon),
            _clip_of(opt))


def _ftml_hyper(opt, index, t):
    lr, wd = _lr_wd(opt, index)
    return (lr, wd, 1.0 - opt.beta1 ** t, 1.0 - opt.beta2 ** t)


def _ftml_step(w, g, state, hyper, rescale, static):
    lr, wd, bc1, bc2 = hyper  # 1 - beta1^t, 1 - beta2^t (host-computed)
    beta1, beta2, eps, clip = static
    d, v, z = state
    g = _uo._rescale_clip(g, rescale, clip, wd, w)
    v_new = beta2 * v + (1 - beta2) * jnp.square(g)
    d_new = bc1 / lr * (jnp.sqrt(v_new / bc2) + eps)
    sigma = d_new - beta1 * d
    z_new = beta1 * z + (1 - beta1) * g - sigma * w
    return -z_new / d_new, (d_new, v_new, z_new)


def _dcasgd_static(opt):
    return (float(opt.momentum), float(opt.lamda), _clip_of(opt))


def _dcasgd_step(w, g, state, hyper, rescale, static):
    lr, wd = hyper
    momentum, lamda, clip = static
    mom, prev = state
    g = _uo._rescale_clip(g, rescale, clip, wd, w)
    comp = g + lamda * g * g * (w - prev)
    if mom is None:
        new_mom, delta = None, -lr * comp
    else:
        new_mom = momentum * mom - lr * comp
        delta = new_mom
    return w + delta, (new_mom, w)  # prev <- pre-update weight, like eager


def _adam_hyper(opt, index, t):
    lr, wd = _lr_wd(opt, index)
    lr_t = lr * math.sqrt(1.0 - opt.beta2 ** t) / (1.0 - opt.beta1 ** t)
    return (lr_t, wd)


def _adam_step(w, g, state, hyper, rescale, static):
    lr_t, wd = hyper
    beta1, beta2, eps, clip = static
    mean, var = state
    nw, nm, nv = _uo.adam_update_fn(w, g, mean, var, lr_t, beta1=beta1,
                                    beta2=beta2, epsilon=eps, wd=wd,
                                    rescale_grad=rescale, clip_gradient=clip)
    return nw, (nm, nv)


def _adagrad_static(opt):
    return (float(opt.float_stable_eps), _clip_of(opt))


def _adagrad_step(w, g, state, hyper, rescale, static):
    lr, wd = hyper
    eps, clip = static
    return _uo.adagrad_update_fn(w, g, state, lr, epsilon=eps, wd=wd,
                                 rescale_grad=rescale, clip_gradient=clip)


def _rmsprop_static(opt):
    return (float(opt.gamma1), float(opt.gamma2), float(opt.epsilon),
            bool(opt.centered), _clip_of(opt),
            float(opt.clip_weights) if opt.clip_weights else -1.0)


def _rmsprop_step(w, g, state, hyper, rescale, static):
    lr, wd = hyper
    gamma1, gamma2, eps, centered, clip, clip_w = static
    if centered:
        n, g_avg, delta = state
        nw, nn, ng, nd = _uo.rmspropalex_update_fn(
            w, g, n, g_avg, delta, lr, gamma1=gamma1, gamma2=gamma2,
            epsilon=eps, wd=wd, rescale_grad=rescale, clip_gradient=clip,
            clip_weights=clip_w)
        return nw, (nn, ng, nd)
    (n,) = state
    nw, nn = _uo.rmsprop_update_fn(w, g, n, lr, gamma1=gamma1, epsilon=eps,
                                   wd=wd, rescale_grad=rescale,
                                   clip_gradient=clip, clip_weights=clip_w)
    return nw, (nn,)


def _adadelta_static(opt):
    return (float(opt.rho), float(opt.epsilon), _clip_of(opt))


def _adadelta_hyper(opt, index, t):
    return (float(opt._get_wd(index)),)  # AdaDelta has no lr


def _adadelta_step(w, g, state, hyper, rescale, static):
    (wd,) = hyper
    rho, eps, clip = static
    acc_g, acc_d = state
    g = _uo._rescale_clip(g, rescale, clip, wd, w)
    ag = rho * acc_g + (1 - rho) * jnp.square(g)
    delta = jnp.sqrt(acc_d + eps) / jnp.sqrt(ag + eps) * g
    ad = rho * acc_d + (1 - rho) * jnp.square(delta)
    return w - delta, (ag, ad)


def _ftrl_static(opt):
    return (float(opt.lamda1), float(opt.beta), _clip_of(opt))


def _ftrl_step(w, g, state, hyper, rescale, static):
    lr, wd = hyper
    lamda1, beta, clip = static
    z, n = state
    nw, nz, nn = _uo.ftrl_update_fn(w, g, z, n, lr, lamda1=lamda1, beta=beta,
                                    wd=wd, rescale_grad=rescale,
                                    clip_gradient=clip)
    return nw, (nz, nn)


def _adamax_static(opt):
    return (float(opt.beta1), float(opt.beta2), _clip_of(opt))


def _adamax_hyper(opt, index, t):
    lr, wd = _lr_wd(opt, index)
    return (lr / (1.0 - opt.beta1 ** t), wd)


def _adamax_step(w, g, state, hyper, rescale, static):
    lr_t, wd = hyper
    beta1, beta2, clip = static
    m, u = state
    g = _uo._rescale_clip(g, rescale, clip, wd, w)
    m_new = beta1 * m + (1 - beta1) * g
    u_new = jnp.maximum(beta2 * u, jnp.abs(g))
    return w - lr_t * m_new / (u_new + 1e-8), (m_new, u_new)


def _nadam_hyper(opt, index, t):
    lr, wd = _lr_wd(opt, index)
    momentum_t = opt.beta1 * (1.0 - 0.5 * 0.96 ** (t * opt.schedule_decay))
    momentum_t_1 = opt.beta1 * (
        1.0 - 0.5 * 0.96 ** ((t + 1) * opt.schedule_decay))
    opt.m_schedule *= momentum_t  # same host-side bookkeeping as eager
    return (lr, wd, momentum_t, momentum_t_1, opt.m_schedule,
            opt.m_schedule * momentum_t_1, 1.0 - opt.beta2 ** t)


def _nadam_step(w, g, state, hyper, rescale, static):
    lr, wd, momentum_t, momentum_t_1, m_sch, m_sch_next, bc2 = hyper
    beta1, beta2, eps, clip = static
    m, v = state
    g = _uo._rescale_clip(g, rescale, clip, wd, w)
    m_new = beta1 * m + (1 - beta1) * g
    v_new = beta2 * v + (1 - beta2) * jnp.square(g)
    g_prime = g / (1 - m_sch)
    m_prime = m_new / (1 - m_sch_next)
    v_prime = v_new / bc2
    m_bar = (1 - momentum_t) * g_prime + momentum_t_1 * m_prime
    return w - lr * m_bar / (jnp.sqrt(v_prime) + eps), (m_new, v_new)


def _groupadagrad_static(opt):
    return (float(opt.float_stable_eps), _clip_of(opt))


def _groupadagrad_hyper(opt, index, t):
    return (float(opt._get_lr(index)),)  # eager GroupAdaGrad ignores wd


def _groupadagrad_step(w, g, state, hyper, rescale, static):
    (lr,) = hyper
    eps, clip = static
    g = _uo._rescale_clip(g, rescale, clip)
    red = tuple(range(1, w.ndim))
    h_new = state + jnp.mean(jnp.square(g), axis=red)
    div = jnp.sqrt(h_new + eps)
    return w - lr * g / div.reshape((-1,) + (1,) * (g.ndim - 1)), h_new


def _test_step(w, g, state, hyper, rescale, static):
    nw = w + g * rescale
    return nw, nw


# ------------------------------------------------- guarded (traced-t) hyper
# Guarded-mode hyper twins: same tuples the host-side hyper fns produce, but
# built from traced (lr, wd, t) so the bias-correction step count can stay
# on device (resilience sentinel: a skipped step must not advance t, and a
# host-side t would cost one sync per step to keep honest).
def _t_lr_wd(static, lr, wd, t):
    return (lr, wd)


def _adam_thyper(static, lr, wd, t):
    beta1, beta2, _eps, _clip = static
    return (lr * jnp.sqrt(1.0 - beta2 ** t) / (1.0 - beta1 ** t), wd)


def _ftml_thyper(static, lr, wd, t):
    beta1, beta2, _eps, _clip = static
    return (lr, wd, 1.0 - beta1 ** t, 1.0 - beta2 ** t)


def _adadelta_thyper(static, lr, wd, t):
    return (wd,)


def _adamax_thyper(static, lr, wd, t):
    beta1, _beta2, _clip = static
    return (lr / (1.0 - beta1 ** t), wd)


def _groupadagrad_thyper(static, lr, wd, t):
    return (lr,)


def _test_thyper(static, lr, wd, t):
    return ()


# SGLD (per-step rng draw) and LBSGD (host-side weight/grad norms for the
# LARS trust ratio) keep the eager path: their updates are not pure
# functions of (weight, grad, state, scalars). Exact-type lookup also sends
# unknown Optimizer subclasses to the eager loop — a subclass overriding
# update() must not silently get its base class's fused rule.
_RULES = {
    SGD: _Rule(_sgd_static, _lr_wd, _sgd_step, _t_lr_wd),
    NAG: _Rule(_sgd_static, _lr_wd, _nag_step, _t_lr_wd),
    Signum: _Rule(_signum_static, _lr_wd, _signum_step, _t_lr_wd),
    FTML: _Rule(_beta_eps_static, _ftml_hyper, _ftml_step, _ftml_thyper),
    DCASGD: _Rule(_dcasgd_static, _lr_wd, _dcasgd_step, _t_lr_wd),
    Adam: _Rule(_beta_eps_static, _adam_hyper, _adam_step, _adam_thyper),
    AdaGrad: _Rule(_adagrad_static, _lr_wd, _adagrad_step, _t_lr_wd),
    RMSProp: _Rule(_rmsprop_static, _lr_wd, _rmsprop_step, _t_lr_wd),
    AdaDelta: _Rule(_adadelta_static, _adadelta_hyper, _adadelta_step,
                    _adadelta_thyper),
    Ftrl: _Rule(_ftrl_static, _lr_wd, _ftrl_step, _t_lr_wd),
    Adamax: _Rule(_adamax_static, _adamax_hyper, _adamax_step,
                  _adamax_thyper),
    # Nadam: m_schedule is ORDER-dependent host state — no traced-t twin;
    # guarded mode routes Nadam through the guarded-eager path
    Nadam: _Rule(_beta_eps_static, _nadam_hyper, _nadam_step),
    GroupAdaGrad: _Rule(_groupadagrad_static, _groupadagrad_hyper,
                        _groupadagrad_step, _groupadagrad_thyper),
    Test: _Rule(lambda opt: (), lambda opt, i, t: (), _test_step,
                _test_thyper),
}


def functional_rule(optimizer):
    """The pure functional update rule for an Optimizer INSTANCE (exact
    class match — a subclass overriding ``update`` must not inherit its
    base rule), or None for the eager-only set (sparse/SGLD/LBSGD/unknown).
    ONE registry serves both jit surfaces: this module's fused Trainer
    step and ``mxtpu.parallel.ShardedTrainStep``."""
    return _RULES.get(type(optimizer))


def traced_rule_names():
    """Registry names of optimizers with a traced-t hyper twin — the set a
    fully-in-graph step (guarded fused update, ShardedTrainStep) supports."""
    return sorted(k.__name__.lower()
                  for k, r in _RULES.items() if r.thyper is not None)


# ------------------------------------------------------------ mesh placement
class MeshPlan:
    """Weight-update placement plan for the fused step on a mesh.

    Parameters are ONE logical replicated array; ``zero1`` additionally
    shards the optimizer state (and the update computation) over the
    ``data_axis`` — the cross-replica weight-update sharding of
    arXiv:2004.13336: reduce-scatter(grad) -> shard-local update ->
    all-gather(weight), optimizer-state memory / replica count, loss
    trajectory bit-identical. Params whose dim 0 does not divide the axis
    keep replicated state (and a replicated update)."""

    __slots__ = ("mesh", "data_axis", "zero1", "axis_size")

    def __init__(self, mesh, data_axis="data", zero1=True):
        if data_axis not in mesh.shape:
            raise ValueError("data_axis %r not in mesh axes %s"
                             % (data_axis, tuple(mesh.shape)))
        self.mesh = mesh
        self.data_axis = data_axis
        self.zero1 = bool(zero1)
        self.axis_size = int(mesh.shape[data_axis])

    def fingerprint(self):
        """Hashable jit-cache-key component: the SAME step traced for a
        different mesh/axis/ZeRO setting — or the same axis shape over
        DIFFERENT devices (the constraint shardings are closed over the
        concrete mesh) — is a different executable."""
        return (tuple(self.mesh.shape.items()), self.data_axis, self.zero1,
                _mesh_dev_ids(self.mesh))

    def replicated(self):
        return NamedSharding(self.mesh, _P())

    def shard0(self):
        return NamedSharding(self.mesh, _P(self.data_axis))

    def _dim0_ok(self, shape):
        return bool(shape) and shape[0] % self.axis_size == 0

    def zero_eligible(self, w_shape, state):
        """ZeRO-1 eligibility for one param: dim 0 of the weight AND of
        every state leaf must divide the data axis (GroupAdaGrad's (dim0,)
        history and the mp f32 master both qualify with the weight)."""
        if not (self.zero1 and self.axis_size > 1
                and self._dim0_ok(tuple(w_shape))):
            return False
        shapes = []
        _leaf_shapes(state, shapes)
        return all(self._dim0_ok(s) for s in shapes)


def _leaf_shapes(s, acc):
    if s is None:
        return acc
    if isinstance(s, NDArray):
        acc.append(tuple(s.shape))
        return acc
    if hasattr(s, "shape"):  # raw jax array leaf
        acc.append(tuple(s.shape))
        return acc
    for x in s:
        _leaf_shapes(x, acc)
    return acc


def _mesh_dev_ids(mesh):
    # process-local ordinals, not global ids: an identical per-host mesh
    # on a replacement host must produce the same cache key as the peer
    # that spilled the blob (compile_service.device_token rationale)
    from . import compile_service as csvc
    return tuple(csvc._local_ordinal(d) for d in mesh.devices.flat)


def _shard_token(arr):
    """Hashable sharding descriptor for the jit cache key: the layout is
    part of the compiled executable's contract, so two steps over the same
    shapes but different placements — including the same axis shape over
    different device subsets — must not share an entry (ROADMAP item 5 —
    sharding enters the key)."""
    sh = getattr(arr, "sharding", None)
    if isinstance(sh, NamedSharding):
        return (tuple(sh.mesh.shape.items()), str(sh.spec),
                _mesh_dev_ids(sh.mesh))
    return None


def _tree_shard_token(s):
    if s is None:
        return None
    if isinstance(s, tuple):
        return tuple(_tree_shard_token(x) for x in s)
    return _shard_token(s)


# ----------------------------------------------------- state pytree helpers
def _tree_data(s):
    if s is None:
        return None
    if isinstance(s, NDArray):
        return s._data
    return tuple(_tree_data(x) for x in s)


def _tree_spec(s):
    if s is None:
        return None
    if isinstance(s, tuple):
        return tuple(_tree_spec(x) for x in s)
    return (tuple(s.shape), str(s.dtype))


def _tree_writeback(state, new):
    if state is None:
        return
    if isinstance(state, NDArray):
        state._set_data(new)
        return
    for s, n in zip(state, new):
        _tree_writeback(s, n)


def _split_aliased(items, states, eager_items):
    """Donation invalidates input buffers; a jax.Array appearing under more
    than one item (tied parameters, Test's state==weight aliasing) or under
    an eager-bound item must not be donated — the other holder would read a
    deleted buffer. EVERY item of such an alias group takes the eager loop
    (where nothing is invalidated); the rest of the batch still fuses."""

    def buf_key(arr):
        # the DEVICE buffer, not the Python wrapper: XLA output aliasing can
        # hand two distinct jax.Array objects one buffer (Test's
        # state==weight contract does exactly that), and donating it twice
        # is a runtime error on TPU. Sharded arrays have no single pointer —
        # fall back to object identity there.
        try:
            return arr.unsafe_buffer_pointer()
        except Exception:
            return id(arr)

    def leaves(x, acc):
        if isinstance(x, NDArray):
            acc.append(buf_key(x._data))
        elif x is not None:
            for c in x:
                leaves(c, acc)
        return acc

    counts = {}      # donated leaves: weights + states of fused candidates
    protected = set()  # must survive the call: grads + eager items' buffers
    item_ids = []
    for item in items:
        ids = leaves(item[2], leaves(states[item[0]], []))
        item_ids.append(ids)
        for b in ids:
            counts[b] = counts.get(b, 0) + 1
        protected.update(leaves(item[1], []))
    for i, g, w in eager_items:
        protected.update(leaves(w, leaves(g, leaves(states.get(i), []))))
    clean, aliased = [], []
    for item, ids in zip(items, item_ids):
        if all(counts[b] == 1 and b not in protected for b in ids):
            clean.append(item)
        else:
            aliased.append(item)
    return clean, aliased


def _tree_where(ok, new, old):
    """Per-leaf ``where(ok, new, old)`` over the Updater's tuple/None state
    structure — the skip-step select that makes a non-finite step a no-op."""
    if new is None:
        return None
    if isinstance(new, tuple):
        return tuple(_tree_where(ok, n, o) for n, o in zip(new, old))
    return jnp.where(ok, new, old)


def _fingerprint(values):
    """Divergence-sentinel fingerprint of a list of arrays / state trees
    (mxtpu/resilience.py): ONE f32 sum plus ONE wrapping int32
    bitcast-fold over every leaf — the fold catches sign flips and
    NaN-payload corruption that a float sum can absorb (x + (-x) == 0).
    Computed INSIDE the donated update jit from the post-update values,
    so it is a pure function of each device's own operands: a replica
    whose replicated buffers silently diverged computes a different copy
    of this (replicated) output, which the host-side
    ``DivergenceSentinel`` compares off the async scalars."""
    fsum = jnp.float32(0.0)
    fold = jnp.int32(0)

    def add(x):
        nonlocal fsum, fold
        if x is None:
            return
        if isinstance(x, tuple):
            for c in x:
                add(c)
            return
        xf = x.astype(jnp.float32)
        fsum = fsum + jnp.sum(xf)
        fold = fold + jnp.sum(
            jax.lax.bitcast_convert_type(xf, jnp.int32))

    for v in values:
        add(v)
    return fsum, fold


def _portable_build():
    """True when the fused jit must stay inside XLA:CPU's
    serialization-safe class: no donation, no sharding constraints.

    Measured on jaxlib 0.4.37 CPU: a serialized executable loaded in a
    FRESH process silently corrupts when it declares input-output
    aliasing (donation — wrong values from the second call on) or mixes
    sharding-constraint custom-calls with the bitcast fingerprint
    reduction (wrong values immediately, plus heap corruption). The
    same HLO without donation/constraints round-trips bit-exact, and on
    a single CPU device both are pure memory hints anyway: dropping
    them changes no value. Only the single-device build needs this —
    multi-device CPU executables are refused by the disk cache outright
    (compile_service ``cpu_multidevice`` drop), so their in-process
    donated/constrained form is never serialized; TPU/GPU keep the
    donated, constrained build — there the aliasing is the whole point
    of fusing the update. Local (not global) device count: on the CPU
    fleet tier every host jits over its own local mesh, so a 2-host
    world of 1-device hosts still builds — and disk-serves — the
    1-device portable form."""
    return jax.default_backend() == "cpu" and len(jax.local_devices()) == 1


def _donation():
    """donate_argnums for the fused update jits — () on CPU (see
    :func:`_portable_build`), weights+states everywhere else. The same
    tuple rides the compile-service canonical key, so a CPU blob and a
    TPU blob of one site can never alias."""
    return () if _portable_build() else (0, 2)


def _zero_shards(plan, zf):
    """The (shard, gather, tree-shard) constraint trio for one param under
    the plan — identity functions when the param is not ZeRO-eligible.

    ZeRO-1 inside the donated jit (arXiv:2004.13336): constrain grad,
    weight, and state to the data-axis shard (a reduce-scatter when the
    grad arrives sharded from an in-jit backward, a free dynamic-slice
    when it arrives replicated from the eager autograd), run the update
    rule shard-local, then all-gather ONLY the weight; the state keeps the
    sharded layout, so its memory divides by the replica count."""
    if plan is None or not zf or _portable_build():
        ident = lambda x: x  # noqa: E731
        return ident, ident, ident
    sh0, repl = plan.shard0(), plan.replicated()

    def shard(x):
        return jax.lax.with_sharding_constraint(x, sh0)

    def gather(x):
        return jax.lax.with_sharding_constraint(x, repl)

    def tree_shard(s):
        if s is None:
            return None
        if isinstance(s, tuple):
            return tuple(tree_shard(x) for x in s)
        return shard(s)

    return shard, gather, tree_shard


def _build(rule, static, mp_flags, out_dtypes, plan=None, zflags=None,
           emit_fp=False):
    zflags = zflags or (False,) * len(mp_flags)

    def fused(w_list, g_list, s_list, h_list, rescale):
        # trace-time only (host-side): counts real recompiles, mirrored
        # into the telemetry registry for report()/the JSONL sink
        FUSED_STATS["traces"] += 1
        telemetry.inc("fused_optimizer.retraces")
        new_w, new_s = [], []
        for w, g, s, h, mp, odt, zf in zip(w_list, g_list, s_list, h_list,
                                           mp_flags, out_dtypes, zflags):
            shard, gather, tshard = _zero_shards(plan, zf)
            w, g, s = shard(w), shard(g), tshard(s)
            if mp:
                # multi-precision: state = (f32 master, base state); the
                # update runs in f32 and storage keeps the bf16/f16 dtype
                # (the reference's mp_sgd_update pattern, optimizer.py:500)
                master, base = s
                nm, nb = rule.step(master, g.astype(jnp.float32), base, h,
                                   rescale, static)
                new_w.append(gather(nm).astype(odt))
                new_s.append((tshard(nm), tshard(nb)))
            else:
                nw, ns = rule.step(w, g, s, h, rescale, static)
                new_w.append(gather(nw))
                new_s.append(tshard(ns))
        if emit_fp:
            # divergence sentinel (MXTPU_DIVERGENCE_EVERY > 0): the
            # fingerprint rides the SAME executable — emit_fp is part of
            # the cache key and registry.policy_key, so a flip is one
            # recompile and steady-state compiles stay flat
            return new_w, new_s, _fingerprint(new_w + new_s)
        return new_w, new_s

    return jax.jit(fused, donate_argnums=_donation())


def _build_guarded(rule, static, mp_flags, out_dtypes, scaler_cfg,
                   plan=None, zflags=None, emit_fp=False):
    """The guarded twin of :func:`_build`: same donated whole-model update,
    plus (inside the SAME jit, so the guard costs no extra dispatches or
    host syncs) the fused finite flag, the global grad norm, the skip-step
    ``where`` select on params/state/t, loss-scale unscaling, and the
    scaler's growth/backoff. ``scaler_cfg`` is the STATIC policy tuple
    (part of the jit cache key); the scale value itself is traced. The
    ZeRO-1 constraints compose: the skip select runs shard-local too."""
    thyper = rule.thyper
    zflags = zflags or (False,) * len(mp_flags)

    def fused(w_list, g_list, s_list, lw_list, rescale, gstate, ext_sq):
        # trace-time only (host-side): counts real recompiles, mirrored
        # into the telemetry registry for report()/the JSONL sink
        FUSED_STATS["traces"] += 1
        telemetry.inc("fused_optimizer.retraces")
        scale, streak, t_good = gstate
        # ONE fused reduction serves flag AND norm: the sum of squares is
        # finite iff every grad element is (an f32 overflow of the sum also
        # trips it — a grad norm beyond f32 range is a skip-worthy step).
        # ext_sq carries the eager-bound items' contribution (a device
        # scalar, no sync), so both the flag and the reported norm are
        # global across a mixed fused+eager batch.
        sq = jnp.float32(0.0) + ext_sq
        for g in g_list:
            sq = sq + jnp.sum(jnp.square(g.astype(jnp.float32)))
        ok = jnp.isfinite(sq)
        inv = rescale / scale  # loss-scale unscaling folded into rescale
        grad_norm = jnp.sqrt(sq) * inv
        t_eff = (t_good + 1).astype(jnp.float32)
        new_w, new_s = [], []
        for w, g, s, lw, mp, odt, zf in zip(w_list, g_list, s_list, lw_list,
                                            mp_flags, out_dtypes, zflags):
            lr, wd = lw
            h = thyper(static, lr, wd, t_eff)
            shard, gather, tshard = _zero_shards(plan, zf)
            w, g, s = shard(w), shard(g), tshard(s)
            if mp:
                master, base = s
                nm, nb = rule.step(master, g.astype(jnp.float32), base, h,
                                   inv, static)
                nm = jnp.where(ok, nm, master)
                nb = _tree_where(ok, nb, base)
                new_w.append(gather(nm).astype(odt))
                new_s.append((tshard(nm), tshard(nb)))
            else:
                nw, ns = rule.step(w, g, s, h, inv, static)
                new_w.append(gather(jnp.where(ok, nw, w)))
                new_s.append(tshard(_tree_where(ok, ns, s)))
        new_t = jnp.where(ok, t_good + 1, t_good)
        if scaler_cfg is not None:
            gf, bf, gi, max_s, min_s = scaler_cfg
            streak2 = jnp.where(ok, streak + 1, 0)
            grow = streak2 >= gi
            new_scale = jnp.where(ok, jnp.where(grow, scale * gf, scale),
                                  scale * bf)
            new_scale = jnp.clip(new_scale, min_s, max_s)
            new_streak = jnp.where(ok & grow, 0, streak2)
        else:
            new_scale, new_streak = scale, streak
        if emit_fp:
            # same-executable divergence fingerprint as _build: the skip
            # select already ran, so a skipped step fingerprints the
            # UNTOUCHED buffers — replicas agree on skips too
            return (new_w, new_s, (new_scale, new_streak, new_t), ok,
                    grad_norm, _fingerprint(new_w + new_s))
        return new_w, new_s, (new_scale, new_streak, new_t), ok, grad_norm

    # gstate is NOT donated: the scale scalar is aliased by user code
    # (DynamicLossScaler.scale multiplies the loss by it) and by the
    # no-scaler cached constant — donating would delete a live buffer
    return jax.jit(fused, donate_argnums=_donation())


class FusedUpdater(Updater):
    """Updater whose ``update_batch`` compiles the whole optimizer step into
    one donated jit (the update-path CachedOp). ``__call__`` keeps the
    per-index eager semantics, so kvstore servers, serialization, and code
    driving single-param updates behave exactly as before."""

    # capability marker read by the kvstore's donation-safety copies: True
    # even under MXTPU_FUSED_OPTIMIZER=0 — the env flag is read per call
    # and may flip mid-process, so buffers must stay safe to donate
    donates = True

    def __init__(self, optimizer):
        super().__init__(optimizer)
        # resilience surface (mxtpu/resilience.py): attach a
        # DynamicLossScaler (Trainer(loss_scaler=...)) and/or set
        # MXTPU_NUMERICS_GUARD=1 to run every step under the in-jit
        # sentinel. last_step_ok / last_grad_norm are DEVICE scalars from
        # the latest guarded step, fetched asynchronously by callers.
        self.scaler = None
        self.health = resilience.StepHealth()
        self.last_step_ok = None
        self.last_grad_norm = None
        # divergence sentinel (MXTPU_DIVERGENCE_EVERY > 0): the latest
        # fused step's (f32 sum, i32 fold) fingerprint as async device
        # scalars — compared per-replica by resilience.DivergenceSentinel
        # at check cadence, never fetched in the hot loop
        self.last_fingerprint = None
        self._t_good = None     # device good-step count (guarded mode)
        self._noscaler_state = None  # cached (1.0, 0) scalars, never donated
        self._step_count = 0    # dispatched update_batch calls (fault index)
        # step index -> owning trace id (bounded): the poison-batch
        # quarantine attributes skipped steps back to their step traces
        self._step_traces = collections.OrderedDict()
        self._plan = None       # MeshPlan (Trainer(mesh=...) sets it)

    def _guard_active(self):
        return self.scaler is not None or resilience.guard_enabled()

    # ------------------------------------------------------- mesh placement
    def set_mesh(self, mesh, data_axis="data", zero1=True):
        """Adopt a :class:`MeshPlan` (or drop it with ``mesh=None``).
        Called by ``gluon.Trainer(mesh=...)`` at kvstore init; any state
        that already exists is re-placed onto the plan."""
        self._plan = MeshPlan(mesh, data_axis, zero1) \
            if mesh is not None else None
        for i in list(self.states):
            self._place_state(i)

    def ensure_state(self, index, weight):
        """Create (and mesh-place) the optimizer state for one param now —
        the Trainer calls this at ``_init_kvstore`` so every NamedSharding
        lands before the first step, not lazily inside it."""
        if index not in self.states:
            self.states[index] = \
                self.optimizer.create_state_multi_precision(index, weight)
            self._place_state(index, weight)

    def _place_state(self, index, weight=None):
        """Lay one param's state out per the plan: data-axis sharded for
        ZeRO-eligible params, replicated otherwise. In-place on the stored
        NDArray leaves, so serialization and eager fallbacks see the same
        objects."""
        if self._plan is None:
            return
        st = self.states.get(index)
        if st is None:
            return
        if weight is None:
            weight = self.optimizer.param_dict.get(index) \
                if isinstance(self.optimizer.param_dict, dict) else None
        zok = weight is not None and getattr(weight, "shape", None) \
            and self._plan.zero_eligible(tuple(weight.shape), st)
        sh = self._plan.shard0() if zok else self._plan.replicated()

        from .parallel.mesh import place_global

        def put(x):
            if x is None:
                return
            if isinstance(x, NDArray):
                # place_global: device_put single-process; on a fleet
                # (process-spanning) mesh it assembles the global array
                # from this host's full copy — valid for both layouts
                # here, since every host creates identical initial state
                x._set_data(place_global(x._data, sh))
                return
            for c in x:
                put(c)

        put(st)

    def update_batch(self, indices, grads, weights):
        if not indices:
            return  # no-op like the base Updater, guarded or not
        opt = self.optimizer
        step_idx = self._step_count
        self._step_count += 1
        # step -> trace attribution (bounded): Trainer.step roots a trace
        # per step (ISSUE 10); recording the owning id here lets the
        # poison-batch quarantine name the offending batches' traces
        ctx = telemetry.current_trace()
        if ctx is not None:
            self._step_traces[step_idx] = ctx.trace_id
            while len(self._step_traces) > 4096:
                self._step_traces.popitem(last=False)
        if grads and resilience.inject("nan_grad", step_idx):
            # poison ONE gradient buffer — pure data, no retrace, and it
            # flows through the exact production sentinel path
            grads[0]._set_data(grads[0]._data * float("nan"))
        guarded = self._guard_active()
        rule = _RULES.get(type(opt)) if fused_enabled() else None
        if guarded and rule is not None and rule.thyper is None:
            rule = None  # Nadam: t-hyper can't move in-graph -> guarded-eager
        from .ndarray.sparse import RowSparseNDArray
        fused, eager = [], []
        for i, g, w in zip(indices, grads, weights):
            self.ensure_state(i, w)
            if rule is None or isinstance(g, RowSparseNDArray) \
                    or isinstance(w, RowSparseNDArray):
                eager.append((i, g, w))
            else:
                fused.append((i, g, w))
        if fused:
            fused, aliased = _split_aliased(fused, self.states, eager)
            eager.extend(aliased)
        if guarded:
            self._guarded_step(rule, fused, eager, step_idx)
            return
        self.last_step_ok = None  # unguarded steps report no verdict
        self.last_fingerprint = None  # _fused_apply re-emits when enabled
        if fused and eager and isinstance(opt, Nadam):
            # Nadam's m_schedule is ORDER-dependent host state (one multiply
            # per param update): a mixed batch must keep the exact eager
            # call order, so run the whole batch eagerly in index order
            fused, eager = [], list(zip(indices, grads, weights))
        if fused:
            self._fused_apply(rule, fused)
        for i, g, w in eager:
            opt.update_multi_precision(i, w, g, self.states[i])
            FUSED_STATS["eager_updates"] += 1
            telemetry.inc("fused_optimizer.eager_updates")

    def _gather_items(self, items, hyper_of):
        """Per-item device buffers + the jit cache-key specs, ONE copy
        shared by the plain and guarded fused paths — a spec change must
        not silently fork the two cache-key semantics. ``hyper_of(i)``
        builds the traced per-param hyper tuple."""
        opt = self.optimizer
        plan = self._plan
        w_datas, g_datas, s_datas, hypers = [], [], [], []
        mp_flags, out_dtypes, specs, zflags = [], [], [], []
        for i, g, w in items:
            hypers.append(hyper_of(i))
            mp = bool(opt.multi_precision
                      and w.dtype in (jnp.float16, jnp.bfloat16))
            sd = _tree_data(self.states[i])
            zf = plan is not None \
                and plan.zero_eligible(tuple(w.shape), self.states[i])
            w_datas.append(w._data)
            g_datas.append(g._data)
            s_datas.append(sd)
            mp_flags.append(mp)
            out_dtypes.append(w._data.dtype)
            zflags.append(zf)
            # sharding tokens ride the spec: a layout change (mesh attach,
            # ZeRO flip, a restored-replicated state) is a new executable,
            # never a silent reuse of one traced for another placement
            specs.append((tuple(w.shape), str(w.dtype), str(g.dtype),
                          _tree_spec(sd), mp, zf, _shard_token(w._data),
                          _tree_shard_token(sd)))
        return (w_datas, g_datas, s_datas, hypers, tuple(mp_flags),
                tuple(out_dtypes), tuple(specs), tuple(zflags))

    def _cached_jit(self, key, build, example_args=None):
        fn = _JIT_CACHE.get(key)
        if fn is None:
            # retrace watchdog (mxtpu/telemetry.py): every executable-cache
            # miss reports its cache-key provenance — optimizer class,
            # guard bit, param count, and the policy levers active now —
            # so a steady-state recompile is attributable without a rerun.
            # The build resolves through the compile service: the jit
            # rides compiled= into the xprof ledger and comes back
            # wrapped — the wrapper IS what both caches hold — and with
            # MXTPU_COMPILE_CACHE_DIR set the executable persists, so a
            # restarted trainer's first step loads it with zero compiles.
            # policy participation: guard/divergence bits ride the key
            # explicitly (they are the levers this trace consults) — the
            # FULL policy_key must NOT join it, or every conv/BN lever
            # flip would needlessly recompile the optimizer step.
            from . import compile_service as csvc
            from .ops.registry import policy_key
            plan = self._plan
            ckey = csvc.canonical_key(
                site="fused_optimizer", fn_id="fused:%s" % key[0],
                signature=key,
                sharding=plan.fingerprint() if plan is not None else None,
                donation=_donation(),
                device=csvc.device_token(
                    mesh=plan.mesh if plan is not None else None))
            entry = csvc.get_or_build(
                ckey, build,
                provenance={"optimizer": key[0], "guard": "guard" in key,
                            "divergence": "div" in key,
                            "n_params": len(key[2]),
                            "mesh": key[3] is not None,
                            "policy_key": list(policy_key())},
                example_args=csvc.concrete_args(example_args)
                if example_args is not None else None)
            fn = entry.fn
            if entry.origin == "built":
                # bumped only after build() succeeded: a failed
                # trace/compile must leave compiles == retrace count (a
                # disk-restored executable is a load, not a compile)
                FUSED_STATS["compiles"] += 1
            _JIT_CACHE[key] = fn
        return fn

    def _fused_apply(self, rule, items):
        opt = self.optimizer
        # bump every count first so _get_lr sees the post-step num_update for
        # ALL params (the eager loop's first update already bumps it before
        # any lr is read)
        for i, _, _ in items:
            opt._update_count(i)

        def hyper_of(i):
            t = opt._index_update_count[i]
            return tuple(float(h) for h in rule.hyper(opt, i, t))

        (w_datas, g_datas, s_datas, hypers, mp_flags, out_dtypes,
         specs, zflags) = self._gather_items(items, hyper_of)
        static = rule.static(opt)
        plan = self._plan
        # divergence-sentinel bit: emitting the fingerprint changes the
        # traced program, so it rides the cache key (and policy_key) the
        # way the guard bit does — a cadence flip is one recompile
        emit_fp = resilience.divergence_every() > 0
        key = (type(opt).__name__, static, specs,
               plan.fingerprint() if plan else None) \
            + (("div",) if emit_fp else ())
        fn = self._cached_jit(
            key, lambda: _build(rule, static, mp_flags, out_dtypes,
                                plan, zflags, emit_fp),
            example_args=(w_datas, g_datas, s_datas, hypers,
                          float(opt.rescale_grad)))
        out = fn(w_datas, g_datas, s_datas, hypers,
                 float(opt.rescale_grad))
        if emit_fp:
            new_w, new_s, self.last_fingerprint = out
        else:
            new_w, new_s = out
            self.last_fingerprint = None
        FUSED_STATS["fused_steps"] += 1
        telemetry.inc("fused_optimizer.steps")
        for (i, _, w), nw, ns in zip(items, new_w, new_s):
            w._set_data(nw)
            _tree_writeback(self.states[i], ns)

    # ------------------------------------------------------- guarded stepping
    def _guard_state(self):
        """(scale, streak, t_good) device scalars threaded through the
        guarded jit. Without a scaler the (1.0, 0) pair is cached — these
        inputs are never donated, so reuse is safe."""
        if self._t_good is None:
            # warm start (guard enabled mid-run, or an unguarded checkpoint
            # resumed with the guard on): seed from the host update clock so
            # Adam-family bias correction continues at t=N+1 instead of
            # restarting at 1
            self._t_good = jnp.asarray(
                int(getattr(self.optimizer, "num_update", 0)), jnp.int32)
        if self.scaler is not None:
            self.scaler._ensure()
            return (self.scaler._scale, self.scaler._streak, self._t_good)
        if self._noscaler_state is None:
            self._noscaler_state = (jnp.float32(1.0), jnp.int32(0))
        return self._noscaler_state + (self._t_good,)

    def _guarded_step(self, rule, fused, eager, step_idx):
        """One sentinel-guarded optimizer step over a fused+eager split.

        The pure-fused hot path (every param fused — the common case) runs
        with ZERO host syncs: flag, norm, skip select, t bump, and scaler
        update all live inside the donated jit, and the step_ok scalar is
        only fetched when a caller asks. Eager-bound items (sparse grads,
        tied buffers, Nadam/SGLD-class optimizers) cost ONE host sync to
        keep the skip decision global across both halves of the batch."""
        opt = self.optimizer
        scaler = self.scaler
        gstate = self._guard_state()
        scale_used = gstate[0]
        scfg = scaler.config() if scaler is not None else None
        sq_e = jnp.float32(0.0)
        for _, g, _ in eager:
            sq_e = sq_e + jnp.sum(jnp.square(g._data.astype(jnp.float32)))
        if fused:
            # eager items' sum-of-squares rides INTO the jit (async): the
            # global flag/norm need no extra sync here — the one mixed-batch
            # sync is the ok fetch below that gates the eager updates
            ok, grad_norm = self._guarded_fused_apply(rule, fused, gstate,
                                                      scfg, sq_e)
        else:
            # all-eager guarded step: the flag must reach the host anyway
            # (it gates the eager updates); bookkeeping mirrors the in-jit
            # rule, device math stays async. The divergence fingerprint is
            # a fused-path feature — no stale value may survive here.
            self.last_fingerprint = None
            ok = bool(jnp.isfinite(sq_e))  # the documented eager sync
            grad_norm = jnp.sqrt(sq_e) * (
                jnp.float32(float(opt.rescale_grad)) / scale_used)
            if scaler is not None:
                scaler.host_update(ok)
            if ok:
                self._t_good = self._t_good + 1
        self.last_step_ok = ok
        self.last_grad_norm = grad_norm
        self.health.append(step_idx, ok, grad_norm)
        if eager:
            ok_all = bool(ok) if fused else ok  # mixed batches sync once
            if ok_all:
                saved = opt.rescale_grad
                try:
                    if scaler is not None:
                        # eager kernels know nothing of the loss scale:
                        # fold the unscale into rescale_grad for this step
                        opt.rescale_grad = saved / float(scale_used)
                    for i, g, w in eager:
                        opt.update_multi_precision(i, w, g, self.states[i])
                        FUSED_STATS["eager_updates"] += 1
                        telemetry.inc("fused_optimizer.eager_updates")
                finally:
                    opt.rescale_grad = saved
            # skipped: eager per-index update counts stay untouched too
            # (the count bumps inside Optimizer.update, which never ran)

    def _guarded_fused_apply(self, rule, items, gstate, scfg, ext_sq):
        opt = self.optimizer
        # host update-count still ticks per DISPATCHED step: it is the lr
        # SCHEDULE clock (and matches how schedules treat skipped steps
        # elsewhere); the bias-correction t is the device t_good, which
        # only good steps advance
        for i, _, _ in items:
            opt._update_count(i)
        (w_datas, g_datas, s_datas, hypers, mp_flags, out_dtypes,
         specs, zflags) = self._gather_items(
            items, lambda i: (float(opt._get_lr(i)), float(opt._get_wd(i))))
        static = rule.static(opt)
        plan = self._plan
        emit_fp = resilience.divergence_every() > 0
        # the guard bit + scaler policy + divergence bit ride the cache
        # key: each flip is exactly one extra compile, flag/scale flips
        # are zero
        key = (type(opt).__name__, static, specs,
               plan.fingerprint() if plan else None, "guard", scfg) \
            + (("div",) if emit_fp else ())
        fn = self._cached_jit(
            key, lambda: _build_guarded(rule, static, mp_flags, out_dtypes,
                                        scfg, plan, zflags, emit_fp),
            example_args=(w_datas, g_datas, s_datas, hypers,
                          float(opt.rescale_grad), gstate, ext_sq))
        out = fn(w_datas, g_datas, s_datas, hypers,
                 float(opt.rescale_grad), gstate, ext_sq)
        if emit_fp:
            new_w, new_s, new_gstate, ok, grad_norm, \
                self.last_fingerprint = out
        else:
            new_w, new_s, new_gstate, ok, grad_norm = out
            self.last_fingerprint = None
        FUSED_STATS["fused_steps"] += 1
        telemetry.inc("fused_optimizer.steps")
        for (i, _, w), nw, ns in zip(items, new_w, new_s):
            w._set_data(nw)
            _tree_writeback(self.states[i], ns)
        new_scale, new_streak, self._t_good = new_gstate
        if self.scaler is not None:
            self.scaler._scale = new_scale
            self.scaler._streak = new_streak
        return ok, grad_norm

    # ----------------------------------------------------------- serialization
    # Loss-scaler + guard scalars ride the optimizer-state blob so
    # Trainer.save_states / contrib.async_checkpoint.save_trainer resume
    # bit-exact. Plain (unguarded) updaters keep the base format.
    _RESILIENCE_TAG = "__mxtpu_resilience_v1__"

    def get_states(self, dump_optimizer=False):
        import pickle

        import numpy as np
        base = super().get_states(dump_optimizer)
        if self.scaler is None and self._t_good is None:
            return base
        payload = {
            "base": base,
            "t_good": None if self._t_good is None
            else np.asarray(self._t_good),
            "scaler": None if self.scaler is None
            else self.scaler.state_dict(),
        }
        return pickle.dumps((self._RESILIENCE_TAG, payload))

    def set_states(self, states):
        import pickle
        obj = pickle.loads(states)
        if not (isinstance(obj, tuple) and len(obj) == 2
                and obj[0] == self._RESILIENCE_TAG):
            super().set_states(states)
            self._replace_states_on_plan()
            return
        payload = obj[1]
        if payload["t_good"] is not None:
            self._t_good = jnp.asarray(payload["t_good"])
        sc = payload["scaler"]
        if sc is not None:
            if self.scaler is None:
                # do NOT auto-attach: the guarded jit would divide grads by
                # the restored scale while nothing scales the loss — a
                # silent stall. The user must pass the scaler explicitly
                # (Trainer(loss_scaler=...)) so their loop scales too.
                import logging
                logging.getLogger("mxtpu.resilience").warning(
                    "checkpoint carries DynamicLossScaler state (scale=%s) "
                    "but no loss scaler is attached — continuing UNSCALED; "
                    "pass loss_scaler= when building the Trainer to resume "
                    "scaled training", float(sc["scale"]))
            else:
                self.scaler.load_state_dict(sc)
        super().set_states(payload["base"])
        self._replace_states_on_plan()

    def _replace_states_on_plan(self):
        """Restored states arrive as host-built single-device arrays; with
        a MeshPlan active they must go back to their mesh layout (ZeRO
        shard or replicated) or the next step would silently trace a new
        executable for the foreign placement. A dump_optimizer blob
        carries a STRIPPED param_dict (see Updater.get_states) under which
        ZeRO eligibility cannot be decided — skip that pass entirely: the
        load paths (Trainer.load_states, async_checkpoint.load_trainer)
        re-invoke after rebinding the live params, and placing twice would
        double the full-state transfers."""
        if self._plan is None:
            return
        if not getattr(self.optimizer, "param_dict", None):
            return
        for i in list(self.states):
            self._place_state(i)
