"""Fused whole-model optimizer step: ONE donated jit per Trainer.step.

The eager update path (Optimizer.update driven from Updater.__call__) issues
3-10 tiny XLA dispatches *per parameter per step* — exactly the
consecutive-small-ops anti-pattern the reference engine exists to bulk
(SURVEY §1; "Operator Fusion in XLA" shows this elementwise chain is where
fusion pays). This module is the update-path analog of CachedOp for
forward/backward: every optimizer's update rule is restated as a pure
``step(weight, grad, state, hyper, rescale, static) -> (new_w, new_state)``
function; the whole parameter list is stacked into one pytree and compiled
as a single ``jax.jit`` with ``donate_argnums`` on weights and states, so
XLA updates every buffer in place with no copies and no per-param host
round trips ("Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training" treats the weight update as the same first-class
fusion target).

Cache key = (optimizer class, static config like momentum/betas/clip,
per-param shapes+dtypes+state structure). Hyperparameters that move between
steps — lr (schedules!), wd, rescale_grad=1/batch, bias-correction terms of
the update count t — enter as *traced* scalars, so an lr-schedule tick or a
batch-size change never retriggers compilation.

Fallback to the eager per-param loop: sparse (row_sparse) grads, optimizers
with host-side control flow (SGLD's rng draw, LBSGD's norm-driven LARS
ratio), aliased buffers (donation would invalidate a live input twice), or
``MXTPU_FUSED_OPTIMIZER=0``.
"""
from __future__ import annotations

import math
import os

import jax
import jax.numpy as jnp

from .ndarray import NDArray
from .ops import optimizer_ops as _uo
from .optimizer import (SGD, Adam, AdaGrad, RMSProp, AdaDelta, Ftrl, Adamax,
                        Nadam, NAG, Signum, FTML, DCASGD, Test, GroupAdaGrad,
                        Updater)

__all__ = ["FusedUpdater", "fused_enabled", "cache_size", "reset",
           "FUSED_STATS"]


def fused_enabled():
    """Measured default ON; MXTPU_FUSED_OPTIMIZER=0 is the escape hatch
    (read per call, so it can be flipped mid-process for A/Bs)."""
    return os.environ.get("MXTPU_FUSED_OPTIMIZER", "1") != "0"


# fused_steps: fused jit invocations; traces: actual retraces (bumped at
# trace time INSIDE the jitted fn — the recompile counter tests assert on);
# compiles: misses of the executable cache; eager_updates: per-param
# fallback updates
FUSED_STATS = {"fused_steps": 0, "traces": 0, "compiles": 0,
               "eager_updates": 0}
_JIT_CACHE = {}


def cache_size():
    return len(_JIT_CACHE)


def reset():
    """Test hook: drop compiled executables and zero the counters."""
    _JIT_CACHE.clear()
    for k in FUSED_STATS:
        FUSED_STATS[k] = 0


# --------------------------------------------------------------------- rules
class _Rule:
    """One optimizer class's pure functional update.

    ``static(opt)`` -> hashable config baked into the trace (part of the jit
    cache key); ``hyper(opt, index, t)`` -> per-param scalars traced as
    arguments (lr/wd after lr_mult/wd_mult, bias-correction terms of t);
    ``step(w, g, state, hyper, rescale, static)`` -> (new_w, new_state) with
    ``state`` the same tuple/None structure the Updater stores.
    """

    __slots__ = ("static", "hyper", "step")

    def __init__(self, static, hyper, step):
        self.static = static
        self.hyper = hyper
        self.step = step


def _clip_of(opt):
    return float(opt.clip_gradient) if opt.clip_gradient else -1.0


def _lr_wd(opt, index, _t=None):
    return float(opt._get_lr(index)), float(opt._get_wd(index))


def _sgd_static(opt):
    return (float(opt.momentum), _clip_of(opt))


def _sgd_step(w, g, state, hyper, rescale, static):
    lr, wd = hyper
    momentum, clip = static
    if state is None:
        return _uo.sgd_update_fn(w, g, lr, wd=wd, rescale_grad=rescale,
                                 clip_gradient=clip), None
    return _uo.sgd_mom_update_fn(w, g, state, lr, momentum=momentum, wd=wd,
                                 rescale_grad=rescale, clip_gradient=clip)


def _nag_step(w, g, state, hyper, rescale, static):
    lr, wd = hyper
    momentum, clip = static
    if state is None:
        return _uo.sgd_update_fn(w, g, lr, wd=wd, rescale_grad=rescale,
                                 clip_gradient=clip), None
    return _uo.nag_mom_update_fn(w, g, state, lr, momentum=momentum, wd=wd,
                                 rescale_grad=rescale, clip_gradient=clip)


def _signum_static(opt):
    return (float(opt.momentum), float(opt.wd_lh), _clip_of(opt))


def _signum_step(w, g, state, hyper, rescale, static):
    lr, wd = hyper
    momentum, wd_lh, clip = static
    if state is None:
        return _uo.signsgd_update_fn(w, g, lr, wd=wd, rescale_grad=rescale,
                                     clip_gradient=clip), None
    return _uo.signum_update_fn(w, g, state, lr, momentum=momentum, wd=wd,
                                rescale_grad=rescale, clip_gradient=clip,
                                wd_lh=wd_lh)


def _beta_eps_static(opt):
    return (float(opt.beta1), float(opt.beta2), float(opt.epsilon),
            _clip_of(opt))


def _ftml_hyper(opt, index, t):
    lr, wd = _lr_wd(opt, index)
    return (lr, wd, 1.0 - opt.beta1 ** t, 1.0 - opt.beta2 ** t)


def _ftml_step(w, g, state, hyper, rescale, static):
    lr, wd, bc1, bc2 = hyper  # 1 - beta1^t, 1 - beta2^t (host-computed)
    beta1, beta2, eps, clip = static
    d, v, z = state
    g = _uo._rescale_clip(g, rescale, clip, wd, w)
    v_new = beta2 * v + (1 - beta2) * jnp.square(g)
    d_new = bc1 / lr * (jnp.sqrt(v_new / bc2) + eps)
    sigma = d_new - beta1 * d
    z_new = beta1 * z + (1 - beta1) * g - sigma * w
    return -z_new / d_new, (d_new, v_new, z_new)


def _dcasgd_static(opt):
    return (float(opt.momentum), float(opt.lamda), _clip_of(opt))


def _dcasgd_step(w, g, state, hyper, rescale, static):
    lr, wd = hyper
    momentum, lamda, clip = static
    mom, prev = state
    g = _uo._rescale_clip(g, rescale, clip, wd, w)
    comp = g + lamda * g * g * (w - prev)
    if mom is None:
        new_mom, delta = None, -lr * comp
    else:
        new_mom = momentum * mom - lr * comp
        delta = new_mom
    return w + delta, (new_mom, w)  # prev <- pre-update weight, like eager


def _adam_hyper(opt, index, t):
    lr, wd = _lr_wd(opt, index)
    lr_t = lr * math.sqrt(1.0 - opt.beta2 ** t) / (1.0 - opt.beta1 ** t)
    return (lr_t, wd)


def _adam_step(w, g, state, hyper, rescale, static):
    lr_t, wd = hyper
    beta1, beta2, eps, clip = static
    mean, var = state
    nw, nm, nv = _uo.adam_update_fn(w, g, mean, var, lr_t, beta1=beta1,
                                    beta2=beta2, epsilon=eps, wd=wd,
                                    rescale_grad=rescale, clip_gradient=clip)
    return nw, (nm, nv)


def _adagrad_static(opt):
    return (float(opt.float_stable_eps), _clip_of(opt))


def _adagrad_step(w, g, state, hyper, rescale, static):
    lr, wd = hyper
    eps, clip = static
    return _uo.adagrad_update_fn(w, g, state, lr, epsilon=eps, wd=wd,
                                 rescale_grad=rescale, clip_gradient=clip)


def _rmsprop_static(opt):
    return (float(opt.gamma1), float(opt.gamma2), float(opt.epsilon),
            bool(opt.centered), _clip_of(opt),
            float(opt.clip_weights) if opt.clip_weights else -1.0)


def _rmsprop_step(w, g, state, hyper, rescale, static):
    lr, wd = hyper
    gamma1, gamma2, eps, centered, clip, clip_w = static
    if centered:
        n, g_avg, delta = state
        nw, nn, ng, nd = _uo.rmspropalex_update_fn(
            w, g, n, g_avg, delta, lr, gamma1=gamma1, gamma2=gamma2,
            epsilon=eps, wd=wd, rescale_grad=rescale, clip_gradient=clip,
            clip_weights=clip_w)
        return nw, (nn, ng, nd)
    (n,) = state
    nw, nn = _uo.rmsprop_update_fn(w, g, n, lr, gamma1=gamma1, epsilon=eps,
                                   wd=wd, rescale_grad=rescale,
                                   clip_gradient=clip, clip_weights=clip_w)
    return nw, (nn,)


def _adadelta_static(opt):
    return (float(opt.rho), float(opt.epsilon), _clip_of(opt))


def _adadelta_hyper(opt, index, t):
    return (float(opt._get_wd(index)),)  # AdaDelta has no lr


def _adadelta_step(w, g, state, hyper, rescale, static):
    (wd,) = hyper
    rho, eps, clip = static
    acc_g, acc_d = state
    g = _uo._rescale_clip(g, rescale, clip, wd, w)
    ag = rho * acc_g + (1 - rho) * jnp.square(g)
    delta = jnp.sqrt(acc_d + eps) / jnp.sqrt(ag + eps) * g
    ad = rho * acc_d + (1 - rho) * jnp.square(delta)
    return w - delta, (ag, ad)


def _ftrl_static(opt):
    return (float(opt.lamda1), float(opt.beta), _clip_of(opt))


def _ftrl_step(w, g, state, hyper, rescale, static):
    lr, wd = hyper
    lamda1, beta, clip = static
    z, n = state
    nw, nz, nn = _uo.ftrl_update_fn(w, g, z, n, lr, lamda1=lamda1, beta=beta,
                                    wd=wd, rescale_grad=rescale,
                                    clip_gradient=clip)
    return nw, (nz, nn)


def _adamax_static(opt):
    return (float(opt.beta1), float(opt.beta2), _clip_of(opt))


def _adamax_hyper(opt, index, t):
    lr, wd = _lr_wd(opt, index)
    return (lr / (1.0 - opt.beta1 ** t), wd)


def _adamax_step(w, g, state, hyper, rescale, static):
    lr_t, wd = hyper
    beta1, beta2, clip = static
    m, u = state
    g = _uo._rescale_clip(g, rescale, clip, wd, w)
    m_new = beta1 * m + (1 - beta1) * g
    u_new = jnp.maximum(beta2 * u, jnp.abs(g))
    return w - lr_t * m_new / (u_new + 1e-8), (m_new, u_new)


def _nadam_hyper(opt, index, t):
    lr, wd = _lr_wd(opt, index)
    momentum_t = opt.beta1 * (1.0 - 0.5 * 0.96 ** (t * opt.schedule_decay))
    momentum_t_1 = opt.beta1 * (
        1.0 - 0.5 * 0.96 ** ((t + 1) * opt.schedule_decay))
    opt.m_schedule *= momentum_t  # same host-side bookkeeping as eager
    return (lr, wd, momentum_t, momentum_t_1, opt.m_schedule,
            opt.m_schedule * momentum_t_1, 1.0 - opt.beta2 ** t)


def _nadam_step(w, g, state, hyper, rescale, static):
    lr, wd, momentum_t, momentum_t_1, m_sch, m_sch_next, bc2 = hyper
    beta1, beta2, eps, clip = static
    m, v = state
    g = _uo._rescale_clip(g, rescale, clip, wd, w)
    m_new = beta1 * m + (1 - beta1) * g
    v_new = beta2 * v + (1 - beta2) * jnp.square(g)
    g_prime = g / (1 - m_sch)
    m_prime = m_new / (1 - m_sch_next)
    v_prime = v_new / bc2
    m_bar = (1 - momentum_t) * g_prime + momentum_t_1 * m_prime
    return w - lr * m_bar / (jnp.sqrt(v_prime) + eps), (m_new, v_new)


def _groupadagrad_static(opt):
    return (float(opt.float_stable_eps), _clip_of(opt))


def _groupadagrad_hyper(opt, index, t):
    return (float(opt._get_lr(index)),)  # eager GroupAdaGrad ignores wd


def _groupadagrad_step(w, g, state, hyper, rescale, static):
    (lr,) = hyper
    eps, clip = static
    g = _uo._rescale_clip(g, rescale, clip)
    red = tuple(range(1, w.ndim))
    h_new = state + jnp.mean(jnp.square(g), axis=red)
    div = jnp.sqrt(h_new + eps)
    return w - lr * g / div.reshape((-1,) + (1,) * (g.ndim - 1)), h_new


def _test_step(w, g, state, hyper, rescale, static):
    nw = w + g * rescale
    return nw, nw


# SGLD (per-step rng draw) and LBSGD (host-side weight/grad norms for the
# LARS trust ratio) keep the eager path: their updates are not pure
# functions of (weight, grad, state, scalars). Exact-type lookup also sends
# unknown Optimizer subclasses to the eager loop — a subclass overriding
# update() must not silently get its base class's fused rule.
_RULES = {
    SGD: _Rule(_sgd_static, _lr_wd, _sgd_step),
    NAG: _Rule(_sgd_static, _lr_wd, _nag_step),
    Signum: _Rule(_signum_static, _lr_wd, _signum_step),
    FTML: _Rule(_beta_eps_static, _ftml_hyper, _ftml_step),
    DCASGD: _Rule(_dcasgd_static, _lr_wd, _dcasgd_step),
    Adam: _Rule(_beta_eps_static, _adam_hyper, _adam_step),
    AdaGrad: _Rule(_adagrad_static, _lr_wd, _adagrad_step),
    RMSProp: _Rule(_rmsprop_static, _lr_wd, _rmsprop_step),
    AdaDelta: _Rule(_adadelta_static, _adadelta_hyper, _adadelta_step),
    Ftrl: _Rule(_ftrl_static, _lr_wd, _ftrl_step),
    Adamax: _Rule(_adamax_static, _adamax_hyper, _adamax_step),
    Nadam: _Rule(_beta_eps_static, _nadam_hyper, _nadam_step),
    GroupAdaGrad: _Rule(_groupadagrad_static, _groupadagrad_hyper,
                        _groupadagrad_step),
    Test: _Rule(lambda opt: (), lambda opt, i, t: (), _test_step),
}


# ----------------------------------------------------- state pytree helpers
def _tree_data(s):
    if s is None:
        return None
    if isinstance(s, NDArray):
        return s._data
    return tuple(_tree_data(x) for x in s)


def _tree_spec(s):
    if s is None:
        return None
    if isinstance(s, tuple):
        return tuple(_tree_spec(x) for x in s)
    return (tuple(s.shape), str(s.dtype))


def _tree_writeback(state, new):
    if state is None:
        return
    if isinstance(state, NDArray):
        state._set_data(new)
        return
    for s, n in zip(state, new):
        _tree_writeback(s, n)


def _split_aliased(items, states, eager_items):
    """Donation invalidates input buffers; a jax.Array appearing under more
    than one item (tied parameters, Test's state==weight aliasing) or under
    an eager-bound item must not be donated — the other holder would read a
    deleted buffer. EVERY item of such an alias group takes the eager loop
    (where nothing is invalidated); the rest of the batch still fuses."""

    def buf_key(arr):
        # the DEVICE buffer, not the Python wrapper: XLA output aliasing can
        # hand two distinct jax.Array objects one buffer (Test's
        # state==weight contract does exactly that), and donating it twice
        # is a runtime error on TPU. Sharded arrays have no single pointer —
        # fall back to object identity there.
        try:
            return arr.unsafe_buffer_pointer()
        except Exception:
            return id(arr)

    def leaves(x, acc):
        if isinstance(x, NDArray):
            acc.append(buf_key(x._data))
        elif x is not None:
            for c in x:
                leaves(c, acc)
        return acc

    counts = {}      # donated leaves: weights + states of fused candidates
    protected = set()  # must survive the call: grads + eager items' buffers
    item_ids = []
    for item in items:
        ids = leaves(item[2], leaves(states[item[0]], []))
        item_ids.append(ids)
        for b in ids:
            counts[b] = counts.get(b, 0) + 1
        protected.update(leaves(item[1], []))
    for i, g, w in eager_items:
        protected.update(leaves(w, leaves(g, leaves(states.get(i), []))))
    clean, aliased = [], []
    for item, ids in zip(items, item_ids):
        if all(counts[b] == 1 and b not in protected for b in ids):
            clean.append(item)
        else:
            aliased.append(item)
    return clean, aliased


def _build(rule, static, mp_flags, out_dtypes):
    def fused(w_list, g_list, s_list, h_list, rescale):
        FUSED_STATS["traces"] += 1  # trace-time only: counts real recompiles
        new_w, new_s = [], []
        for w, g, s, h, mp, odt in zip(w_list, g_list, s_list, h_list,
                                       mp_flags, out_dtypes):
            if mp:
                # multi-precision: state = (f32 master, base state); the
                # update runs in f32 and storage keeps the bf16/f16 dtype
                # (the reference's mp_sgd_update pattern, optimizer.py:500)
                master, base = s
                nm, nb = rule.step(master, g.astype(jnp.float32), base, h,
                                   rescale, static)
                new_w.append(nm.astype(odt))
                new_s.append((nm, nb))
            else:
                nw, ns = rule.step(w, g, s, h, rescale, static)
                new_w.append(nw)
                new_s.append(ns)
        return new_w, new_s

    return jax.jit(fused, donate_argnums=(0, 2))


class FusedUpdater(Updater):
    """Updater whose ``update_batch`` compiles the whole optimizer step into
    one donated jit (the update-path CachedOp). ``__call__`` keeps the
    per-index eager semantics, so kvstore servers, serialization, and code
    driving single-param updates behave exactly as before."""

    # capability marker read by the kvstore's donation-safety copies: True
    # even under MXTPU_FUSED_OPTIMIZER=0 — the env flag is read per call
    # and may flip mid-process, so buffers must stay safe to donate
    donates = True

    def update_batch(self, indices, grads, weights):
        opt = self.optimizer
        rule = _RULES.get(type(opt)) if fused_enabled() else None
        from .ndarray.sparse import RowSparseNDArray
        fused, eager = [], []
        for i, g, w in zip(indices, grads, weights):
            if i not in self.states:
                self.states[i] = opt.create_state_multi_precision(i, w)
            if rule is None or isinstance(g, RowSparseNDArray) \
                    or isinstance(w, RowSparseNDArray):
                eager.append((i, g, w))
            else:
                fused.append((i, g, w))
        if fused:
            fused, aliased = _split_aliased(fused, self.states, eager)
            eager.extend(aliased)
        if fused and eager and isinstance(opt, Nadam):
            # Nadam's m_schedule is ORDER-dependent host state (one multiply
            # per param update): a mixed batch must keep the exact eager
            # call order, so run the whole batch eagerly in index order
            fused, eager = [], list(zip(indices, grads, weights))
        if fused:
            self._fused_apply(rule, fused)
        for i, g, w in eager:
            opt.update_multi_precision(i, w, g, self.states[i])
            FUSED_STATS["eager_updates"] += 1

    def _fused_apply(self, rule, items):
        opt = self.optimizer
        # bump every count first so _get_lr sees the post-step num_update for
        # ALL params (the eager loop's first update already bumps it before
        # any lr is read)
        for i, _, _ in items:
            opt._update_count(i)
        w_datas, g_datas, s_datas, hypers = [], [], [], []
        mp_flags, out_dtypes, specs = [], [], []
        for i, g, w in items:
            t = opt._index_update_count[i]
            hypers.append(tuple(float(h) for h in rule.hyper(opt, i, t)))
            mp = bool(opt.multi_precision
                      and w.dtype in (jnp.float16, jnp.bfloat16))
            sd = _tree_data(self.states[i])
            w_datas.append(w._data)
            g_datas.append(g._data)
            s_datas.append(sd)
            mp_flags.append(mp)
            out_dtypes.append(w._data.dtype)
            specs.append((tuple(w.shape), str(w.dtype), str(g.dtype),
                          _tree_spec(sd), mp))
        static = rule.static(opt)
        key = (type(opt).__name__, static, tuple(specs))
        fn = _JIT_CACHE.get(key)
        if fn is None:
            fn = _build(rule, static, tuple(mp_flags), tuple(out_dtypes))
            _JIT_CACHE[key] = fn
            FUSED_STATS["compiles"] += 1
        new_w, new_s = fn(w_datas, g_datas, s_datas, hypers,
                          float(opt.rescale_grad))
        FUSED_STATS["fused_steps"] += 1
        for (i, _, w), nw, ns in zip(items, new_w, new_s):
            w._set_data(nw)
            _tree_writeback(self.states[i], ns)
