"""Testing utilities (ref: python/mxnet/test_utils.py — assert_almost_equal,
check_numeric_gradient, check_symbolic_forward/backward, default contexts).

The numeric-gradient checker is the reference's central operator-test mechanism
(SURVEY §4); here it validates the jax.vjp-derived gradients against central
finite differences computed in float64 on host.
"""
from __future__ import annotations

import numpy as np

from . import autograd
from .base import Context, current_context
from .ndarray import NDArray, array


def default_context() -> Context:
    return current_context()


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-20, names=("a", "b")):
    a = a.asnumpy() if isinstance(a, NDArray) else np.asarray(a)
    b = b.asnumpy() if isinstance(b, NDArray) else np.asarray(b)
    np.testing.assert_allclose(a, b, rtol=rtol, atol=atol,
                               err_msg="%s vs %s mismatch" % names)


def almost_equal(a, b, rtol=1e-5, atol=1e-20) -> bool:
    a = a.asnumpy() if isinstance(a, NDArray) else np.asarray(a)
    b = b.asnumpy() if isinstance(b, NDArray) else np.asarray(b)
    return np.allclose(a, b, rtol=rtol, atol=atol)


def rand_ndarray(shape, stype="default", density=None, dtype="float32"):
    a = np.random.uniform(-1, 1, size=shape).astype(dtype)
    nd = array(a)
    if stype != "default":
        return nd.tostype(stype)
    return nd


def check_numeric_gradient(fn, inputs, eps=1e-3, rtol=1e-2, atol=1e-4,
                           head_grad=None):
    """Compare autograd gradients of ``fn(*inputs) -> NDArray`` against central
    finite differences (ref: mxnet.test_utils.check_numeric_gradient)."""
    inputs = [array(x) if not isinstance(x, NDArray) else x for x in inputs]
    for x in inputs:
        x.attach_grad()
    with autograd.record():
        out = fn(*inputs)
    if head_grad is None:
        hg = np.ones(out.shape, np.float32)
    else:
        hg = np.asarray(head_grad, np.float32)
    out.backward(array(hg))
    analytic = [x.grad.asnumpy().astype(np.float64) for x in inputs]

    for i, x in enumerate(inputs):
        base = x.asnumpy().astype(np.float64)
        flat = base.reshape(-1)
        num = np.zeros_like(flat)
        for j in range(flat.size):
            for sgn, acc in ((+1, 1.0), (-1, -1.0)):
                pert = flat.copy()
                pert[j] += sgn * eps
                args = [inputs[k] if k != i else array(pert.reshape(base.shape).astype(np.float32))
                        for k in range(len(inputs))]
                with autograd.pause():
                    val = fn(*args).asnumpy().astype(np.float64)
                num[j] += acc * np.sum(val * hg)
        num /= 2 * eps
        np.testing.assert_allclose(analytic[i].reshape(-1), num, rtol=rtol, atol=atol,
                                   err_msg="numeric grad mismatch for input %d" % i)


def check_consistency(fn, inputs, ctxs=None, rtol=1e-4, atol=1e-5):
    """Run fn on multiple contexts and compare (ref: test_utils.check_consistency,
    used by tests/python/gpu/test_operator_gpu.py to cross-check CPU vs GPU)."""
    from .base import cpu, tpu
    ctxs = ctxs or [cpu(), tpu()]
    outs = []
    for ctx in ctxs:
        args = [x.as_in_context(ctx) for x in inputs]
        outs.append(fn(*args).asnumpy())
    for o in outs[1:]:
        np.testing.assert_allclose(outs[0], o, rtol=rtol, atol=atol)


def same(a, b):
    return np.array_equal(np.asarray(a), np.asarray(b))
